"""Scheduler state machine — the pure control-plane core.

This is the sans-IO heart of the scheduler, the equivalent of the reference's
``SchedulerState`` (scheduler.py:1554): every task in the cluster moves
through the states

    released -> waiting -> [processing | queued | no-worker] -> memory
                                   \\-> erred
    (any) -> released -> forgotten

via a transition engine: ``_transition(key, finish)`` dispatches on the
``(start, finish)`` pair (reference _TRANSITIONS_TABLE, scheduler.py:2889);
each handler mutates state and returns ``(recommendations, client_msgs,
worker_msgs)``; ``_transitions`` (scheduler.py:2045) drains recommendations
to a fixed point.  Every transition is appended to ``transition_log`` with a
``stimulus_id`` for causal tracing (``story``).

Worker placement (``decide_worker_*``, reference scheduler.py:2135-2336 and
module-level decide_worker :8550) is routed through ``self.placement`` — by
default the pure-python objective below, optionally the JAX co-processor in
``distributed_tpu.ops.placement`` which batches these decisions into
cost-matrix kernels on device (the framework's north star).

This class performs **no IO**: it returns message dicts destined for workers
and clients; the networked ``Scheduler`` server drains them onto batched
comm streams.  That makes the whole control plane deterministic and unit
testable (reference test strategy tier 1, SURVEY.md §4).
"""

from __future__ import annotations

import logging
from collections import defaultdict, deque
from collections.abc import Iterable
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.exceptions import (
    InvalidTaskState,
    InvalidTransition,
    KilledWorker,
    NoValidWorkerError,
    TransitionCounterMaxExceeded,
)
from distributed_tpu.diagnostics.census import build_scheduler_census
from distributed_tpu.diagnostics.selfprofile import WallBudget
from distributed_tpu.graph.spec import TaskSpec
from distributed_tpu.ledger import DecisionLedger
from distributed_tpu.protocol.serialize import compact_frames, wrap_opaque
from distributed_tpu.telemetry import ClusterTelemetry
from distributed_tpu.tracing import (
    SECONDS_BUCKETS,
    SIZE_BUCKETS,
    FlightRecorder,
    Histogram,
)
from distributed_tpu.utils import HeapSet, OrderedSet, key_split, time

logger = logging.getLogger("distributed_tpu.scheduler")

Key = str

ALL_TASK_STATES = (
    "released",
    "waiting",
    "no-worker",
    "queued",
    "processing",
    "memory",
    "erred",
    "forgotten",
)

# worker lifecycle statuses (subset of reference Status enum, core.py:77)
WORKER_STATUS_RUNNING = "running"
WORKER_STATUS_PAUSED = "paused"
WORKER_STATUS_CLOSING = "closing"
WORKER_STATUS_CLOSING_GRACEFULLY = "closing_gracefully"
WORKER_STATUS_INIT = "init"

RUNNING_STATUSES = frozenset({WORKER_STATUS_RUNNING})


class TaskPrefix:
    """Statistics per function name, used for duration estimation
    (reference scheduler.py:923)."""

    __slots__ = (
        "name",
        "duration_average",
        "max_exec_time",
        "nbytes_total",
        "state_counts",
        "groups",
        "n_durations",
    )

    def __init__(self, name: str):
        self.name = name
        self.duration_average: float = -1.0
        self.max_exec_time: float = -1.0
        self.nbytes_total = 0
        self.n_durations = 0
        self.state_counts: defaultdict[str, int] = defaultdict(int)
        self.groups: set[TaskGroup] = set()

    def add_exec_time(self, duration: float) -> None:
        self.max_exec_time = max(duration, self.max_exec_time)
        if duration > 2 * self.duration_average:
            self.duration_average = -1.0  # invalidate on surprise (ref :947)

    def add_duration(self, duration: float) -> None:
        self.n_durations += 1
        if self.duration_average < 0:
            self.duration_average = duration
        else:
            self.duration_average = 0.5 * duration + 0.5 * self.duration_average

    def __repr__(self) -> str:
        return f"<TaskPrefix {self.name!r}>"


class Computation:
    """One batch of submitted graphs, for diagnostics
    (reference scheduler.py:864): groups the TaskGroups born in one
    ``update_graph`` so dashboards and dumps can slice cluster activity
    by submission instead of by prefix."""

    __slots__ = ("start", "groups", "id")

    def __init__(self, now: float | None = None):
        from distributed_tpu.utils.misc import seq_name

        self.start = now if now is not None else time()
        self.groups: set[TaskGroup] = set()
        self.id = seq_name("computation")

    @property
    def stop(self) -> float:
        return max((tg.stop for tg in self.groups), default=0.0)

    @property
    def states(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for tg in self.groups:
            for st, n in tg.states.items():
                out[st] = out.get(st, 0) + n
        return out

    def __repr__(self) -> str:
        return (
            f"<Computation {self.id}: {len(self.groups)} groups, "
            f"{sum(self.states.values())} tasks>"
        )


class TaskGroup:
    """Statistics per key-group; unit of root-ish detection
    (reference scheduler.py:1033)."""

    __slots__ = (
        "name",
        "prefix",
        "states",
        "dependencies",
        "nbytes_total",
        "duration",
        "types",
        "start",
        "stop",
        "last_worker",
        "last_worker_tasks_left",
        "span_id",
        "n_tasks",
    )

    def __init__(self, name: str):
        self.name = name
        self.prefix: TaskPrefix | None = None
        self.states: dict[str, int] = dict.fromkeys(ALL_TASK_STATES, 0)
        self.dependencies: set[TaskGroup] = set()
        self.nbytes_total = 0
        self.duration = 0.0
        self.types: set[str] = set()
        self.start = 0.0
        self.stop = 0.0
        self.last_worker: WorkerState | None = None
        self.last_worker_tasks_left = 0
        self.span_id: str | None = None
        self.n_tasks = 0

    def add(self, ts: TaskState) -> None:
        self.states[ts.state] += 1
        self.n_tasks += 1
        ts.group = self

    def __len__(self) -> int:
        return self.n_tasks

    def __repr__(self) -> str:
        return f"<TaskGroup {self.name!r}: {self.n_tasks} tasks>"

    @property
    def done(self) -> bool:
        return sum(self.states.get(s, 0) for s in ("memory", "erred", "forgotten")) == self.n_tasks


# --------------------------------------------------------------------------
# Deferred materialization (docs/native_engine.md "authoritative SoA").
#
# While the native engine holds un-replayed transition records, the C++
# SoA — not the python objects — is the source of truth for the
# SoA-backed TaskState/WorkerState fields below.  Engines with pending
# records park themselves in this module-level registry; ANY read or
# write of a backed field drains it first (ordered replay through the
# same appliers the eager path uses, so materialized python state is
# bit-identical to the oracle's).  The registry is almost always empty
# — the fast path is one global truthiness check.
_NATIVE_PENDING: list = []


def _drain_native_pending() -> None:
    for eng in list(_NATIVE_PENDING):
        eng.sync()


class TaskState:
    """Per-task record on the scheduler (reference scheduler.py:1173).

    The fields exposed as properties below are SoA-backed: while the
    native engine defers materialization, their python slots may lag
    the authoritative C++ rows, and every access hydrates first (see
    ``_NATIVE_PENDING``).  Mutate them only through the property (or
    the registered hydration/write-back helpers — graft-lint's
    mirror-parity rule audits direct ``_``-slot writes)."""

    __slots__ = (
        "key",
        "run_spec",
        "priority",
        "_state",
        "dependencies",
        "dependents",
        "_waiting_on",
        "_waiters",
        "who_wants",
        "_who_has",
        "_processing_on",
        "_nbytes",
        "_type",
        "exception",
        "traceback",
        "exception_text",
        "traceback_text",
        "exception_blame",
        "erred_on",
        "suspicious",
        "retries",
        "host_restrictions",
        "worker_restrictions",
        "resource_restrictions",
        "loose_restrictions",
        "actor",
        "prefix",
        "group",
        "_metadata",
        "annotations",
        "run_id",
        "queueable",
        "_homed",
        "_ledger_row",
        "nrow",
        "_rootish",
        "_hash",
    )

    def __init__(self, key: Key, run_spec: Any, state: str = "released"):
        self.key = key
        self._hash = hash(key)
        self.run_spec = run_spec
        self.priority: tuple | None = None
        # SoA-backed slots are written directly here: a task under
        # construction is not yet registered with any engine
        self._state = state
        # relation fields are insertion-ordered (utils.collections.
        # OrderedSet), NOT hash-ordered sets: the transition engine's
        # recommendation order derives from iterating them, so this is
        # what makes engine outcomes deterministic across processes —
        # and what the native engine's SoA mirror (native_engine.py)
        # reproduces with plain C++ vectors
        self.dependencies: OrderedSet[TaskState] = OrderedSet()
        self.dependents: OrderedSet[TaskState] = OrderedSet()
        self._waiting_on: OrderedSet[TaskState] = OrderedSet()
        self._waiters: OrderedSet[TaskState] = OrderedSet()
        # insertion-ordered like the relation fields: report/erred
        # client messages are emitted by iterating this
        self.who_wants: OrderedSet[ClientState] = OrderedSet()
        self._who_has: OrderedSet[WorkerState] = OrderedSet()
        self._processing_on: WorkerState | None = None
        self._nbytes = -1
        self._type: str | None = None
        self.exception: Any = None
        self.traceback: Any = None
        self.exception_text = ""
        self.traceback_text = ""
        self.exception_blame: TaskState | None = None
        # insertion-ordered: free-keys messages are built by iterating
        # this (one worker_msgs row per erred-on address)
        self.erred_on: OrderedSet[str] = OrderedSet()
        self.suspicious = 0
        self.retries = 0
        self.host_restrictions: set[str] | None = None
        self.worker_restrictions: set[str] | None = None
        self.resource_restrictions: dict[str, float] | None = None
        self.loose_restrictions = False
        self.actor = False
        self.prefix: TaskPrefix | None = None
        self.group: TaskGroup | None = None
        self._metadata: dict | None = None
        self.annotations: dict | None = None
        self.run_id: int | None = None
        self.queueable = True
        # placed on its plan-assigned home worker: exempt from stealing
        # (the balancer scattering a co-assigned tile undoes the plan's
        # whole point); cleared on processing exit and on home pause.
        # Truthy values carry provenance for the decision ledger:
        # "plan" = jax_placement plan home, "pin" = shuffle pin (same
        # steal exemption, different ledger attribution)
        self._homed: bool | str = False
        # open decision-ledger row handle (ledger.py): -1 = none.  The
        # handle lives on the task instead of a key-indexed dict so the
        # file/join hot path pays no string hash; stale handles are
        # validity-checked by the ledger.
        self._ledger_row = -1
        # stable row in the native engine's SoA (scheduler/
        # native_engine.py): -1 = not registered
        self.nrow = -1
        self._rootish: bool | None = None

    def __repr__(self) -> str:
        return f"<TaskState {self.key!r} {self.state}>"

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other: object) -> bool:
        return self is other

    @property
    def group_key(self) -> str:
        return self.group.name if self.group else key_split(self.key)

    def get_nbytes(self) -> int:
        return self.nbytes if self.nbytes >= 0 else DEFAULT_DATA_SIZE

    def add_dependency(self, dep: TaskState) -> None:
        self.dependencies.add(dep)
        if self.group is not None and dep.group is not None and dep.group is not self.group:
            self.group.dependencies.add(dep.group)
        dep.dependents.add(self)

    @property
    def has_restrictions(self) -> bool:
        return bool(
            self.host_restrictions or self.worker_restrictions or self.resource_restrictions
        )

    # SoA-backed fields: explicit property pairs (not a factory loop) so
    # the hot oracle path pays one global truthiness check + slot access

    @property
    def state(self) -> str:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._state

    @state.setter
    def state(self, value: str) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._state = value

    @property
    def waiting_on(self) -> OrderedSet[TaskState]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._waiting_on

    @waiting_on.setter
    def waiting_on(self, value: OrderedSet[TaskState]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._waiting_on = value

    @property
    def waiters(self) -> OrderedSet[TaskState]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._waiters

    @waiters.setter
    def waiters(self, value: OrderedSet[TaskState]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._waiters = value

    @property
    def who_has(self) -> OrderedSet[WorkerState]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._who_has

    @who_has.setter
    def who_has(self, value: OrderedSet[WorkerState]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._who_has = value

    @property
    def processing_on(self) -> WorkerState | None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._processing_on

    @processing_on.setter
    def processing_on(self, value: WorkerState | None) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._processing_on = value

    @property
    def nbytes(self) -> int:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._nbytes

    @nbytes.setter
    def nbytes(self, value: int) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._nbytes = value

    @property
    def type(self) -> str | None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._type

    @type.setter
    def type(self, value: str | None) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._type = value

    @property
    def metadata(self) -> dict | None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._metadata

    @metadata.setter
    def metadata(self, value: dict | None) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._metadata = value

    @property
    def homed(self) -> bool | str:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._homed

    @homed.setter
    def homed(self, value: bool | str) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._homed = value

    @property
    def ledger_row(self) -> int:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._ledger_row

    @ledger_row.setter
    def ledger_row(self, value: int) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._ledger_row = value


DEFAULT_DATA_SIZE = 1024  # bytes assumed for unknown results


class ClientState:
    """Per-client record (reference scheduler.py:196)."""

    __slots__ = ("client_key", "wants_what", "last_seen", "versions")

    def __init__(self, client: str, now: float | None = None):
        self.client_key = client
        # insertion-ordered: client-releases and restart paths iterate
        # this to build key lists
        self.wants_what: OrderedSet[TaskState] = OrderedSet()
        self.last_seen = now if now is not None else time()
        self.versions: dict = {}

    def __repr__(self) -> str:
        return f"<ClientState {self.client_key!r}>"

    def __hash__(self) -> int:
        return hash(self.client_key)


class WorkerState:
    """Scheduler-side mirror of one worker (reference scheduler.py:406).

    ``nbytes``/``has_what``/``processing``/``occupancy``/``long_running``
    are SoA-backed like the TaskState fields above: property access
    drains pending native records first."""

    __slots__ = (
        "address",
        "name",
        "nthreads",
        "memory_limit",
        "status",
        "_nbytes",
        "_has_what",
        "_processing",
        "_long_running",
        "executing",
        "resources",
        "used_resources",
        "_occupancy",
        "_network_occ",
        "last_seen",
        "status_changed_at",
        "status_seq",
        "metrics",
        "memory_unmanaged_old",
        "bandwidth",
        "actors",
        "extra",
        "server_id",
        "idx",
        "nidx",
    )

    def __init__(
        self,
        address: str,
        nthreads: int = 1,
        memory_limit: int = 0,
        name: object = None,
        server_id: str | None = None,
    ):
        self.address = address
        self.name = name if name is not None else address
        self.nthreads = nthreads
        self.memory_limit = memory_limit
        self.status = WORKER_STATUS_RUNNING
        self._nbytes = 0
        self._has_what: dict[TaskState, None] = {}  # insertion-ordered set
        self._processing: dict[TaskState, float] = {}
        self._long_running: set[TaskState] = set()
        self.executing: dict[TaskState, float] = {}
        self.resources: dict[str, float] = {}
        # diagnostics-only: placement filters by SUPPLY (valid_workers);
        # actual execution concurrency is constrained worker-side
        self.used_resources: dict[str, float] = {}
        self._occupancy = 0.0
        self._network_occ = 0  # bytes pending transfer to this worker
        self.last_seen = time()
        self.status_changed_at = 0.0  # last stream-delivered status flip
        # worker-stamped monotonic sequence of the last applied status
        # flip: a heartbeat's status view is reconciled only when its
        # seq proves it is at least as new (see heartbeat_worker)
        self.status_seq = 0
        self.metrics: dict = {}
        self.memory_unmanaged_old = 0
        self.bandwidth = float(config.get("scheduler.bandwidth"))
        self.actors: set[TaskState] = set()
        self.extra: dict = {}
        self.server_id = server_id or address
        self.idx = -1  # stable slot in the device mirror (ops/)
        self.nidx = -1  # stable slot in the native engine SoA

    def __repr__(self) -> str:
        return (
            f"<WorkerState {self.address!r} status: {self.status} "
            f"processing: {len(self.processing)} has_what: {len(self.has_what)}>"
        )

    def __hash__(self) -> int:
        return hash(self.server_id)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, WorkerState) and other.server_id == self.server_id

    def clean(self) -> WorkerState:
        ws = WorkerState(self.address, self.nthreads, self.memory_limit, self.name)
        ws.status = self.status
        return ws

    @property
    def nbytes(self) -> int:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._nbytes

    @nbytes.setter
    def nbytes(self, value: int) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._nbytes = value

    @property
    def has_what(self) -> dict[TaskState, None]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._has_what

    @has_what.setter
    def has_what(self, value: dict[TaskState, None]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._has_what = value

    @property
    def processing(self) -> dict[TaskState, float]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._processing

    @processing.setter
    def processing(self, value: dict[TaskState, float]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._processing = value

    @property
    def long_running(self) -> set[TaskState]:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._long_running

    @long_running.setter
    def long_running(self, value: set[TaskState]) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._long_running = value

    @property
    def occupancy(self) -> float:
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._occupancy

    @occupancy.setter
    def occupancy(self, value: float) -> None:
        if _NATIVE_PENDING:
            _drain_native_pending()
        self._occupancy = value


class SchedulerState:
    """The whole mutable scheduler core (reference scheduler.py:1554)."""

    def __init__(
        self,
        *,
        validate: bool | None = None,
        transition_counter_max: int | None = None,
        placement: Any | None = None,
        mirror: bool | None = None,
        clock: Callable[[], float] | None = None,
    ):
        # injectable clock (ROADMAP item 1 simulator): every timestamp
        # this engine writes — transition-log rows, event stamps,
        # no-worker parking, nthreads history — reads ``self.clock``.
        # Default is the monotonic utils.misc.time; the sans-io cluster
        # simulator (distributed_tpu/sim) passes its VirtualClock so a
        # whole cluster's control plane runs on virtual seconds.
        self.clock = clock if clock is not None else time
        # flight recorder + engine histograms (tracing.py;
        # docs/observability.md) — created FIRST: worker registration and
        # the mirror emit through them during the rest of this __init__
        self.trace = FlightRecorder()
        self.trace.clock = self.clock
        # wall-budget phase attribution (diagnostics/selfprofile.py;
        # docs/observability.md "Self-profiling"): exact monotonic
        # accumulators entered at the hot-path seams.  Always REAL
        # monotonic time, even under the simulator's virtual clock —
        # the budget measures python cost, not simulated time.
        self.wall = WallBudget()
        # per-transition-arm attribution (engine.scalar-arm:<s>,<f>):
        # opt-in — two monotonic reads per transition are not free on
        # the flood path, so sim.profile_run turns it on explicitly
        self.WALL_ARMS: bool = bool(
            config.get("scheduler.profile.arm-attribution", False)
        )
        self._arm_phases: dict[tuple[str, str], str] = {}
        # recommendations per engine pass / flood fold size
        self.hist_engine_batch = Histogram(SIZE_BUCKETS)
        # wall seconds per engine pass (one flood fold or one
        # recommendation round drained to its fixed point)
        self.hist_engine_pass = Histogram(SECONDS_BUCKETS)
        # messages folded per coalesced egress envelope (server-side
        # observe site: Scheduler.stream_payload_flush)
        self.hist_egress = Histogram(SIZE_BUCKETS)
        # per-shard telemetry of the SHARDED placement engine (mesh
        # plan path, scheduler/jax_placement.py): one entry per mesh
        # shard — last plan's kernel completion ms, cumulative H2D
        # bytes, plans counted.  Exposed as dtpu_engine_shard_* at
        # /metrics; empty until a sharded plan ran.
        self.engine_shards: list[dict] = []
        # measured-truth telemetry plane (telemetry.py): fleet link
        # EWMAs/t-digests folded from worker heartbeats, task-prefix
        # priors, and the shadow cost-model divergence monitor.
        # STRICTLY read-only: no decision path consults it (property-
        # tested in tests/test_telemetry.py); ROADMAP item 3 swaps the
        # kernel inputs in a future PR.
        self.telemetry = ClusterTelemetry()
        self.telemetry.clock = self.clock
        # decision–outcome ledger (ledger.py; docs/observability.md
        # "Decision ledger & critical-path"): every placement / steal /
        # AMM replica decision files a bounded preallocated row carrying
        # the prediction (constants AND PR 7's measured shadow); the
        # realized outcome joins it at memory/erred/confirm and emits
        # per-decision regret.  Runs on the same injectable clock, so
        # the simulator's joins are exact and deterministic.
        self.ledger = DecisionLedger()
        self.ledger.clock = self.clock
        self.tasks: dict[Key, TaskState] = {}
        self.task_groups: dict[str, TaskGroup] = {}
        # one entry per update_graph batch (reference scheduler.py:864)
        self.computations: deque[Computation] = deque(
            maxlen=config.get("diagnostics.computations.max-history")
        )
        self.task_prefixes: dict[str, TaskPrefix] = {}
        self.workers: dict[str, WorkerState] = {}
        self.aliases: dict[object, str] = {}  # name -> address
        self.clients: dict[str, ClientState] = {}
        self.host_info: defaultdict[str, dict] = defaultdict(dict)
        self.resources: defaultdict[str, dict[str, float]] = defaultdict(dict)

        self.idle: dict[str, WorkerState] = {}
        # insertion-ordered like the task relation fields: the steal
        # balancer's victim scan iterates saturated, and restart
        # recovery (scheduler/durability.py) must rebuild the exact
        # iteration order — built-in set order is allocation-dependent
        self.idle_task_count: OrderedSet[WorkerState] = OrderedSet()
        self.saturated: OrderedSet[WorkerState] = OrderedSet()
        self.running: set[WorkerState] = set()

        self.queued: HeapSet[TaskState] = HeapSet(key=lambda ts: ts.priority)
        # placement-parked subset of ``queued``: tasks deferred for ONE
        # worker's next slot-open (plan co-assignment), indexed by home
        # address.  They are deliberately kept OUT of the globally
        # poppable heap — a queue head wall-to-wall with parked tasks
        # would otherwise be re-scanned on every completion.
        # queued == queued_unparked  ∪  {tasks in parked heaps}
        self.queued_unparked: HeapSet[TaskState] = HeapSet(
            key=lambda ts: ts.priority
        )
        self.parked: dict[str, HeapSet[TaskState]] = {}
        self._parked_keys: dict[Key, str] = {}
        self.unrunnable: dict[TaskState, float] = {}
        # insertion-ordered: ReduceReplicas iterates this to yield
        # drop suggestions (amm.py), so scan order is decision order
        self.replicated_tasks: OrderedSet[TaskState] = OrderedSet()

        self.validate = (
            validate if validate is not None else config.get("scheduler.validate")
        )
        self.transition_counter = 0
        self.transition_counter_max = transition_counter_max
        # SoA-backed like the TaskState fields: read through the
        # ``transition_log`` property, which drains pending native
        # records so deferred story rows materialize first
        self._transition_log: deque = deque(
            maxlen=config.get("scheduler.transition-log-length")
        )
        self._transitions_table: dict[tuple[str, str], Callable] = {
            ("released", "waiting"): self._transition_released_waiting,
            ("waiting", "released"): self._transition_waiting_released,
            ("waiting", "processing"): self._transition_waiting_processing,
            ("waiting", "queued"): self._transition_waiting_queued,
            ("waiting", "no-worker"): self._transition_waiting_no_worker,
            ("waiting", "memory"): self._transition_waiting_memory,
            ("queued", "released"): self._transition_queued_released,
            ("queued", "processing"): self._transition_queued_processing,
            ("processing", "released"): self._transition_processing_released,
            ("processing", "memory"): self._transition_processing_memory,
            ("processing", "erred"): self._transition_processing_erred,
            ("no-worker", "released"): self._transition_no_worker_released,
            ("no-worker", "erred"): self._transition_no_worker_erred,
            ("no-worker", "processing"): self._transition_no_worker_processing,
            ("released", "forgotten"): self._transition_released_forgotten,
            ("memory", "forgotten"): self._transition_memory_forgotten,
            ("erred", "released"): self._transition_erred_released,
            ("memory", "released"): self._transition_memory_released,
            ("released", "erred"): self._transition_released_erred,
            ("released", "memory"): self._transition_released_memory,
        }

        # hot-path config cached at init (reference scheduler.py:1756-1791)
        self.UNKNOWN_TASK_DURATION: float = config.parse_timedelta(
            config.get("scheduler.unknown-task-duration")
        )
        ws_cfg = config.get("scheduler.worker-saturation")
        self.WORKER_SATURATION: float = float("inf") if ws_cfg in ("inf", None) else float(ws_cfg)
        self.bandwidth: float = float(config.get("scheduler.bandwidth"))
        self.transfer_latency: float = config.parse_timedelta(
            config.get("scheduler.transfer-latency")
        )
        self.ALLOWED_FAILURES: int = config.get("scheduler.allowed-failures")
        self.DEFAULT_TASK_DURATIONS: dict[str, float] = {
            k: config.parse_timedelta(v)
            for k, v in config.get("scheduler.default-task-durations").items()
        }

        self.total_nthreads = 0
        # bounded: one row per fleet-capacity flip — as a plain list
        # this grew forever under autoscaling churn (census-found; the
        # reference keeps the same unbounded list)
        self.total_nthreads_history: deque[tuple[float, int]] = deque(
            [(self.clock(), 0)], maxlen=4096
        )
        self._total_occupancy = 0.0
        self.n_tasks = 0
        self.plugins: dict[str, Any] = {}
        self.placement = placement  # JAX co-processor hook (ops/placement.py)
        # persistent fleet SoA shared by every co-processor kernel
        # (scheduler/mirror.py); None = consumers use the from-scratch
        # Python pack (the oracle) every cycle
        self.mirror: Any | None = None
        if mirror if mirror is not None else config.get("scheduler.jax.mirror", True):
            from distributed_tpu.scheduler.mirror import SchedulerMirror

            self.mirror = SchedulerMirror(
                self,
                capacity_doubling=bool(
                    config.get("scheduler.jax.capacity-doubling")
                ),
            )
        # native (C++) transition engine for the four dominant arms
        # (scheduler/native_engine.py; docs/native_engine.md).  None =
        # the pure-python oracle runs everything.  Attach never blocks
        # on a g++ compile here: servers prebuild asynchronously and
        # re-attach on the ready callback; sim/bench contexts call
        # attach_native(build=True) explicitly.
        self.native: Any | None = None
        if config.get("scheduler.native-engine.enabled") and not self.validate:
            self.attach_native()
        self.extensions: dict[str, Any] = {}
        # durability dirty-mark tracker (scheduler/durability.py): when
        # attached, out-of-engine mutations (replica truth, worker
        # lifecycle, client interest) mark rows here so incremental
        # snapshots re-serialize O(changed) task rows; per-transition
        # marks are direct calls from the _transition funnel and from
        # the native tape replay's transition arms (both engines feed
        # the same dirty sets).
        self.durability: Any | None = None
        self.events_subscriber_hook: Callable | None = None
        self.events: defaultdict[str, deque] = defaultdict(
            lambda: deque(maxlen=config.get("scheduler.events-log-length"))
        )
        self.event_counts: defaultdict[str, int] = defaultdict(int)
        self.task_metadata: dict = {}
        self.unknown_durations: dict[str, set[TaskState]] = {}
        # state census (diagnostics/census.py; docs/observability.md
        # "State census & retention"): typed inventory of every
        # long-lived container above — built LAST so every probe
        # closure sees the final containers.  Registration is the
        # contract: a new container attribute must be census-registered
        # or allowlisted with a reason (tests/test_census.py).
        self.census = build_scheduler_census(self)

    # ------------------------------------------------------------------ misc

    def attach_native(self, build: bool = False) -> bool:
        """Attach the native transition engine if the compiled library
        is available (``build=True`` compiles on demand — only call off
        the event loop).  Idempotent; returns True when attached."""
        if self.native is not None:
            return True
        from distributed_tpu.scheduler.native_engine import NativeEngine

        self.native = NativeEngine.attach(self, build=build)
        return self.native is not None

    @property
    def memory_total(self) -> int:
        return sum(ws.memory_limit for ws in self.workers.values())

    def new_task_prefix(self, name: str) -> TaskPrefix:
        tp = self.task_prefixes.get(name)
        if tp is None:
            tp = self.task_prefixes[name] = TaskPrefix(name)
            if name in self.DEFAULT_TASK_DURATIONS:
                tp.duration_average = self.DEFAULT_TASK_DURATIONS[name]
        return tp

    def new_task(
        self,
        key: Key,
        run_spec: Any,
        state: str = "released",
        computation: Any = None,
    ) -> TaskState:
        """Create and register a new TaskState (reference scheduler.py:1817)."""
        ts = TaskState(key, run_spec, state)
        prefix_key = key_split(key)
        tp = self.new_task_prefix(prefix_key)
        ts.prefix = tp
        tp.state_counts[state] += 1
        group_key = prefix_key  # group == prefix family for string keys
        tg = self.task_groups.get(group_key)
        if tg is None:
            tg = self.task_groups[group_key] = TaskGroup(group_key)
            tg.prefix = tp
            tp.groups.add(tg)
        tg.add(ts)
        self.tasks[key] = ts
        self.n_tasks += 1
        if self.native is not None:
            self.native.on_new_task(ts)
        if self.durability is not None:
            self.durability.mark_task(ts)
        return ts

    def _clear_task_state(self) -> None:
        for coll in (
            self.tasks,
            self.task_groups,
            self.task_prefixes,
            self.unrunnable,
            self.replicated_tasks,
        ):
            coll.clear()
        self.queued.clear()
        self.queued_unparked.clear()
        self.parked.clear()
        self._parked_keys.clear()
        # per-worker mirrors reference the cleared TaskStates: reset them
        # too or memory/occupancy accounting is permanently wrong
        for ws in self.workers.values():
            ws.has_what.clear()
            ws.processing.clear()
            ws.long_running.clear()
            ws.executing.clear()
            ws.actors.clear()
            ws.nbytes = 0
            ws.occupancy = 0.0
            ws._network_occ = 0
            ws.used_resources = dict.fromkeys(ws.used_resources, 0)
            self.check_idle_saturated(ws)
        self._total_occupancy = 0.0
        # open decision rows reference the cleared tasks: close them so
        # they don't age out as false unjoineds after a restart
        self.ledger.resolve_all("released", now=self.clock())
        if self.native is not None:
            self.native.reset()

    # ------------------------------------------------- transition engine

    def _transition(
        self, key: Key, finish: str, stimulus_id: str, **kwargs: Any
    ) -> tuple[dict, dict, dict]:
        """Move task ``key`` to state ``finish`` (reference scheduler.py:1909).

        Returns (recommendations, client_msgs, worker_msgs).  Unknown
        (start, finish) pairs route through "released" like the reference
        (scheduler.py:1961-1984).
        """
        ts = self.tasks.get(key)
        if ts is None:
            return {}, {}, {}
        start = ts.state
        if start == finish:
            return {}, {}, {}
        if self.transition_counter_max:
            if self.transition_counter >= self.transition_counter_max:
                raise TransitionCounterMaxExceeded(key, start, finish, self.story(key))
        self.transition_counter += 1

        # opt-in per-arm wall attribution (sim.profile_run's table):
        # everything from dispatch through log/trace/plugins bills to
        # this (start, finish) arm; a routed pair's released leg nests
        # its own arm, so self-time stays exact
        arms = self.WALL_ARMS
        if arms:
            self.wall.push(self._arm_phase(start, finish), stimulus_id)
        try:
            func = self._transitions_table.get((start, finish))
            if func is not None:
                recommendations, client_msgs, worker_msgs = func(
                    key, stimulus_id=stimulus_id, **kwargs
                )
            elif "released" not in (start, finish):
                # untable'd pair: route through released (reference scheduler.py:1961)
                assert not kwargs, (kwargs, start, finish)
                a_recs, a_cmsgs, a_wmsgs = self._transition(key, "released", stimulus_id)
                v = a_recs.get(key, finish)
                func = self._transitions_table.get(("released", v))
                if func is None:
                    raise InvalidTransition(key, start, finish, self.story(key))
                b_recs, b_cmsgs, b_wmsgs = func(key, stimulus_id=stimulus_id)
                recommendations = {**a_recs, **b_recs}
                client_msgs = _merge_msgs(a_cmsgs, b_cmsgs)
                worker_msgs = _merge_msgs(a_wmsgs, b_wmsgs)
                start = "released"
            else:
                raise InvalidTransition(key, start, finish, self.story(key))

            actual_finish = ts.state
            self.transition_log.append(
                (key, start, actual_finish, dict(recommendations), stimulus_id, self.clock())
            )
            # task-level trace hop (sampled 1-in-N): name=finish, dest=start
            # — interned strings only, so the flood fast path allocates
            # nothing (the bench-smoke "trace" gate enforces both the alloc
            # contract and the <5% traced-on overhead)
            self.trace.emit_task(
                "transition", actual_finish, stimulus_id, key=key, dest=start
            )
            if self.validate:
                self.validate_task_state(ts)
            if self.plugins:
                for plugin in list(self.plugins.values()):
                    try:
                        plugin.transition(
                            key, start, actual_finish, stimulus_id=stimulus_id, **kwargs
                        )
                    except Exception:
                        logger.exception("Plugin %r failed in transition", plugin)
            return recommendations, client_msgs, worker_msgs
        finally:
            # native SoA delta-consistency: an oracle transition may
            # have touched ts and both relation neighborhoods
            if self.native is not None:
                self.native.mark_transition(ts)
            # durability dirty mark — direct call, not the plugin seam:
            # the dispatch machinery costs more than the mark and this
            # runs per transition on the flood path
            if self.durability is not None:
                self.durability.mark_transition(ts)
            if arms:
                self.wall.pop()

    def _arm_phase(self, start: str, finish: str) -> str:
        """Interned wall-budget phase name for one transition arm —
        built once per (start, finish) pair so the opt-in hot path never
        formats strings per transition."""
        p = self._arm_phases.get((start, finish))
        if p is None:
            p = self._arm_phases[(start, finish)] = (
                f"engine.scalar-arm:{start},{finish}"
            )
        return p

    def _transitions(
        self,
        recommendations: dict[Key, str],
        client_msgs: dict,
        worker_msgs: dict,
        stimulus_id: str,
    ) -> None:
        """Drain recommendations to a fixed point (reference scheduler.py:2045)."""
        keys: set[Key] = set()
        recommendations = dict(recommendations)
        while recommendations:
            key, finish = recommendations.popitem()
            keys.add(key)
            new_recs, new_cmsgs, new_wmsgs = self._transition(key, finish, stimulus_id)
            recommendations.update(new_recs)
            _merge_msgs_inplace(client_msgs, new_cmsgs)
            _merge_msgs_inplace(worker_msgs, new_wmsgs)
        if self.validate:
            for key in keys:
                ts = self.tasks.get(key)
                if ts is not None:
                    self.validate_task_state(ts)

    def _drain_round(
        self,
        recommendations: dict[Key, str],
        client_msgs: dict,
        worker_msgs: dict,
        stimulus_id: str,
    ) -> None:
        """One recommendation round: the native engine when attached
        and eligible (scheduler/native_engine.py — escapes per key back
        to the oracle), else the pure-python drain.  Both paths produce
        bit-identical state, stories and message multisets; the oracle
        stays selectable at runtime (scheduler.native-engine.enabled,
        DTPU_NATIVE_DISABLE)."""
        ne = self.native
        if ne is not None and ne.active():
            ne.drive_recs_round(
                recommendations, stimulus_id, client_msgs, worker_msgs
            )
        else:
            self._transitions(
                dict(recommendations), client_msgs, worker_msgs, stimulus_id
            )

    def transitions(self, recommendations: dict[Key, str], stimulus_id: str) -> tuple[dict, dict]:
        """Public entry: process recommendations, return (client_msgs, worker_msgs)."""
        tr = self.trace
        if tr.journal_enabled:
            tr.record(
                "transitions", {"recs": dict(recommendations)}, stimulus_id
            )
        return self._transitions_observed(recommendations, stimulus_id)

    def _transitions_observed(
        self, recommendations: dict[Key, str], stimulus_id: str
    ) -> tuple[dict, dict]:
        """One observed engine round WITHOUT a journal record: the drain
        plus the histogram/trace-ring observations.  Journaled stimuli
        that drive an engine round internally (reschedule,
        missing-data) MUST use this — their own journal op replays the
        round, so a nested ``transitions`` record would run it twice
        on replay (the same rule release-worker-data documents)."""
        client_msgs: dict = {}
        worker_msgs: dict = {}
        t0 = self.clock()
        self.wall.push("engine.drain", stimulus_id)
        try:
            self._drain_round(
                recommendations, client_msgs, worker_msgs, stimulus_id
            )
        finally:
            self.wall.pop()
        # histograms observe regardless of trace.enabled: dtpu_engine_*
        # are documented /metrics families, not trace output
        n = len(recommendations)
        self.hist_engine_batch.observe(n)
        self.hist_engine_pass.observe(self.clock() - t0)
        self.trace.emit("engine", "transitions", stimulus_id, n=n)
        return client_msgs, worker_msgs

    @property
    def transition_log(self) -> deque:
        """The story deque, with any deferred native records drained
        first so pending story rows materialize before the read."""
        if _NATIVE_PENDING:
            _drain_native_pending()
        return self._transition_log

    def story(self, *keys_or_stimuli: Key) -> list[tuple]:
        """Transition log entries touching any of the given keys/stimuli
        (reference scheduler.py:2915)."""
        keys = set(keys_or_stimuli)
        return [
            t
            for t in self.transition_log
            if t[0] in keys or t[4] in keys or keys & set(t[3])
        ]

    # ------------------------------------------------- transition handlers

    def _transition_released_waiting(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert ts.run_spec is not None
            assert not ts.waiting_on
            assert not ts.who_has
            assert not ts.processing_on
        recommendations: dict[Key, str] = {}
        for dts in ts.dependencies:
            if dts.state == "forgotten":
                # dependency irrecoverably gone (e.g. scattered data lost)
                ts.state = "erred"  # pragma: no cover
                return recommendations, {}, {}
            # replica truth, not task state: mid-cascade (e.g. worker
            # removal) a dep can be "memory" with an empty who_has while
            # its own released recommendation is still queued — treating
            # it satisfied would place this task with a bare dependency
            # (reference scheduler.py _transition_released_waiting checks
            # who_has)
            if not dts.who_has:
                ts.waiting_on.add(dts)
                if dts.state == "released":
                    recommendations[dts.key] = "waiting"
                elif dts.state == "memory":
                    # last replica vanished while the dep still reads
                    # "memory" (worker-death race): kick its recompute;
                    # if a released rec is already queued in this cascade
                    # the dict merge dedupes it
                    recommendations[dts.key] = "released"
            # register as a waiter on EVERY dependency, satisfied ones
            # included (reference scheduler.py:2110): if an in-memory
            # dep later loses its replicas, _transition_memory_released
            # must find this task in dep.waiters to reschedule it — else
            # it keeps processing against a released dependency
            dts.waiters.add(ts)
        ts.state = "waiting"
        self._count_transition(ts, "released", "waiting")
        if not ts.waiting_on:
            if self.workers:
                recommendations[key] = "processing"
            else:
                self.unrunnable[ts] = self.clock()
                ts.state = "no-worker"
                self._count_transition(ts, "waiting", "no-worker")
        return recommendations, {}, {}

    def _transition_waiting_processing(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        """Possibly schedule a waiting task (reference scheduler.py:2313)."""
        ts = self.tasks[key]
        if self.validate:
            assert not ts.waiting_on
            assert not ts.who_has
            assert not ts.exception_blame
            assert not ts.processing_on
        # planned tasks — rootish included: the partitioner co-assigns a
        # tile's SOURCES with the tile, so its inputs are born home
        # instead of round-robined by co-assignment and fetched once per
        # consuming worker
        if (
            self.placement is not None
            and not ts.actor
            and self.placement.wants(ts)
        ):
            verdict, pws = self.placement.resolve(
                self, ts, self._valid_or_running(ts)
            )
            if verdict == "park":
                # defer for the home worker's next slot-open: the
                # task queues scheduler-side and the home worker
                # pulls it via stimulus_queue_slots_maybe_opened
                self.park_task(ts, pws)
                return {ts.key: "queued"}, {}, {}
            if verdict == "hit":
                worker_msgs = self._add_to_processing(ts, pws, stimulus_id)
                self._count_transition(ts, "waiting", "processing")
                return {}, {}, worker_msgs
        if self.is_rootish(ts):
            if math_isfinite(self.WORKER_SATURATION) and ts.queueable:
                if not (ws := self.decide_worker_rootish_queuing_enabled()):
                    return {ts.key: "queued"}, {}, {}
            else:
                if not (ws := self.decide_worker_rootish_queuing_disabled(ts)):
                    return {ts.key: "no-worker"}, {}, {}
        else:
            if not (ws := self.decide_worker_non_rootish(ts)):
                if ts.waiting_on:
                    # A dependency's last replica vanished between the
                    # transition that recommended us and placement (worker
                    # death race); _decide_worker_locality parked us back in
                    # waiting.  Kick recompute of the bare deps instead of
                    # crashing (reference scheduler.py:2247-2250 guards the
                    # equivalent invariant behind validate).
                    # deps already on their way back (a sibling waiter's
                    # reroute, same cascade) must not be cancelled again
                    return (
                        {
                            dts.key: (
                                "waiting" if dts.state == "released" else "released"
                            )
                            for dts in ts.waiting_on
                            if dts.state not in (
                                "waiting", "queued", "no-worker", "processing"
                            )
                        },
                        {},
                        {},
                    )
                return {ts.key: "no-worker"}, {}, {}
        worker_msgs = self._add_to_processing(ts, ws, stimulus_id)
        self._count_transition(ts, "waiting", "processing")
        return {}, {}, worker_msgs

    def _transition_waiting_released(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        recommendations: dict[Key, str] = {}
        # membership guard: an erred dep already cleared its waiters and must
        # not be released/resurrected here (reference scheduler.py:2587-2592)
        for dts in ts.dependencies:
            if ts in dts.waiters:
                dts.waiters.discard(ts)
                if not dts.waiters and not dts.who_wants:
                    recommendations[dts.key] = "released"
        ts.waiting_on.clear()
        ts.state = "released"
        self._count_transition(ts, "waiting", "released")
        if not ts.dependents and not ts.who_wants:
            recommendations[key] = "forgotten"
        elif not ts.exception_blame and (ts.who_wants or ts.waiters):
            recommendations[key] = "waiting"
            for dts in ts.dependencies:
                dts.waiters.add(ts)
        else:
            # not rerunning (reference scheduler.py:2602 clears waiters
            # here).  A WAITING waiter at this point re-registered
            # mid-cascade: an erred-retry hop (erred -> released ->
            # waiting) can resurrect a dependent while our own
            # "released" recommendation is still queued in the same
            # drain — blindly clearing would leave it waiting on a dep
            # that will never run (dangling waiting_on, a liveness
            # hole; hash-order-dependent flake in the mirror churn
            # trace, deterministically pinned by
            # tests/test_races.py::test_waiting_released_reroutes_resurrected_waiters).
            # Reroute it through released: its re-registration then
            # sees our final "released" state and recommends our rerun.
            for dts in ts.waiters:
                if dts.state == "waiting":
                    recommendations[dts.key] = "released"
            ts.waiters.clear()
        return recommendations, {}, {}

    def _transition_waiting_queued(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert ts not in self.queued
            # rootish tasks queue only when no slot is open anywhere; a
            # PARKED task queues deliberately while other workers have
            # slots — it is waiting for its home worker specifically
            assert not self.idle_task_count or self.is_parked(key), (
                ts, self.idle_task_count,
            )
        ts.state = "queued"
        self._count_transition(ts, "waiting", "queued")
        self.queued.add(ts)
        if key not in self._parked_keys:
            self.queued_unparked.add(ts)
        return {}, {}, {}

    def _transition_waiting_no_worker(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        ts.state = "no-worker"
        self._count_transition(ts, "waiting", "no-worker")
        self.unrunnable[ts] = self.clock()
        return {}, {}, {}

    def _transition_waiting_memory(
        self, key: Key, stimulus_id: str, *, nbytes: int | None = None,
        type: str | None = None, typename: str | None = None, worker: str = "", **kwargs: Any
    ) -> tuple[dict, dict, dict]:
        """Data arrived unexpectedly early (e.g. scatter / AMM replica)."""
        ts = self.tasks[key]
        ws = self.workers.get(worker)
        if ws is None:
            return {}, {}, {}
        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        self._remove_from_waiting(ts, recommendations)
        if nbytes is not None:
            self.update_nbytes(ts, nbytes)
        self.add_replica(ts, ws)
        ts.state = "memory"
        ts.type = typename or type
        self._count_transition(ts, "waiting", "memory")
        self._notify_waiters_task_in_memory(ts, recommendations, client_msgs)
        return recommendations, client_msgs, {}

    def _transition_released_memory(
        self, key: Key, stimulus_id: str, *, nbytes: int | None = None,
        typename: str | None = None, worker: str = "", **kwargs: Any,
    ) -> tuple[dict, dict, dict]:
        """Out-of-band data landed (scatter): enter memory through the
        engine so prefix/state accounting stays consistent and waiting
        dependents get recommendations (reference scatter semantics,
        scheduler.py:6103)."""
        ts = self.tasks[key]
        ws = self.workers.get(worker)
        if ws is None:
            return {}, {}, {}
        if nbytes is not None:
            self.update_nbytes(ts, nbytes)
        self.add_replica(ts, ws)
        ts.state = "memory"
        if typename:
            ts.type = typename
        self._count_transition(ts, "released", "memory")
        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        self._notify_waiters_task_in_memory(ts, recommendations, client_msgs)
        return recommendations, client_msgs, {}

    def _transition_queued_released(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        self.queued.discard(ts)
        self.queued_unparked.discard(ts)
        self.unpark_task(ts, requeue=False)
        ts.state = "released"
        self._count_transition(ts, "queued", "released")
        recommendations: dict[Key, str] = {}
        self._propagate_released_followup(ts, recommendations)
        return recommendations, {}, {}

    def _transition_queued_processing(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert not ts.actor, "queued actors not supported"
        pl = self.placement
        if pl is not None and (self.is_parked(key) or pl.wants(ts)):
            # parked/hinted task: re-resolve against live state.  Home
            # slot open -> go home (this stimulus usually IS the home
            # worker freeing a slot).  Still busy within slack -> keep
            # waiting (re-registering in the index: _parked_pop_for pops
            # destructively).  Home gone/overloaded -> resolve falls to
            # hit-elsewhere or miss; on miss take the least busy
            # open-slot worker (queued semantics require an open slot).
            valid = self._valid_or_running(ts)
            verdict, ws = pl.resolve(self, ts, valid)
            if verdict == "park":
                self.park_task(ts, ws)
                return {}, {}, {}
            if verdict != "hit":
                # restriction-aware fallback: the rootish pick ignores
                # valid_workers (safe there — rootish tasks are never
                # restricted), but parked tasks are non-rootish and may
                # carry worker/host/resource restrictions
                cands = [
                    w for w in self.idle_task_count
                    if valid is None or w in valid
                ]
                ws = min(
                    cands,
                    key=lambda w: (len(w.processing) / max(w.nthreads, 1),
                                   w.address),
                    default=None,
                )
        else:
            ws = self.decide_worker_rootish_queuing_enabled()
        if ws is None:
            # nothing can run it right now; it must stay POPPABLE — a
            # destructively-popped parked task left in neither heap would
            # strand forever (no stimulus ever revisits it)
            if not self.is_parked(key) and ts not in self.queued_unparked:
                self.queued_unparked.add(ts)
            return {}, {}, {}  # remain queued
        self.queued.discard(ts)
        self.queued_unparked.discard(ts)
        self.unpark_task(ts, requeue=False)
        worker_msgs = self._add_to_processing(ts, ws, stimulus_id)
        self._count_transition(ts, "queued", "processing")
        return {}, {}, worker_msgs

    def _transition_processing_released(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        ws = ts.processing_on
        if self.validate:
            assert ws is not None
            assert not ts.who_has
            assert not ts.waiting_on
        worker_msgs: dict = {}
        if ws is not None and ws.address in self.workers:
            worker_msgs[ws.address] = [
                {
                    "op": "free-keys",
                    "keys": [key],
                    "stimulus_id": stimulus_id,
                }
            ]
        if ts.ledger_row >= 0:
            # the placement was cancelled mid-flight: no regret to
            # observe, but the row must close (else it ages out as a
            # false unjoined)
            self.ledger.join_row(ts.ledger_row, "released")
            ts.ledger_row = -1
        self._exit_processing_common(ts)
        ts.state = "released"
        self._count_transition(ts, "processing", "released")
        recommendations: dict[Key, str] = {}
        self._propagate_released_followup(ts, recommendations)
        return recommendations, {}, worker_msgs

    def _transition_processing_memory(
        self,
        key: Key,
        stimulus_id: str,
        *,
        nbytes: int | None = None,
        typename: str | None = None,
        worker: str,
        startstops: list | None = None,
        **kwargs: Any,
    ) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        assert worker
        ws = ts.processing_on
        if ws is None or ws.address != worker or self.workers.get(worker) is not ws:
            # stale or misrouted completion (reference scheduler.py:2380
            # ignores it outright).  The reporter computed a value this
            # scheduler will never account — an overtaken steal victim,
            # or a pre-partition assignment finishing after the key was
            # re-placed.  Without an answer the reporter holds task +
            # data FOREVER (the forget-time free-keys only reaches
            # who_has members): tell it to drop the unaccounted copy.
            # The native engine's OP_META tape row replays the same
            # message (scheduler/native_engine.py).
            logger.debug("Unexpected finished task %s from %s", key, worker)
            return {}, {}, {worker: [{
                "op": "free-keys", "keys": [key],
                "stimulus_id": stimulus_id,
            }]}
        wws = ws

        # update duration statistics (reference scheduler.py:2366 + _observe)
        realized_compute = 0.0
        if startstops:
            for startstop in startstops:
                if startstop.get("action") == "compute":
                    duration = startstop["stop"] - startstop["start"]
                    realized_compute += duration
                    ts.prefix.add_duration(duration)
                    # the prefix now HAS a measured duration: release
                    # the tasks parked under it at placement time
                    # (reference scheduler.py pops unknown_durations in
                    # _transition_processing_memory).  This dict was
                    # append-only — every TaskState placed before its
                    # prefix's first completion was pinned FOREVER,
                    # with its whole dependency-object cluster: ~10 GB
                    # over a 1M-task simulated run (found by the
                    # sim_10k headline; invisible at test scale).
                    self.unknown_durations.pop(ts.prefix.name, None)
                    ts.group.duration += duration
                    if not ts.group.start:
                        ts.group.start = startstop["start"]
                    ts.group.stop = max(ts.group.stop, startstop["stop"])

        row = ts.ledger_row
        if row >= 0:
            # decision–outcome join (ledger.py): realized compute is the
            # worker-reported duration (clock-agnostic); the join stamp
            # and the decision stamp share THIS engine's clock, so
            # realized total — and therefore regret — is exact under
            # the simulator's virtual time
            ts.ledger_row = -1
            self.ledger.join_row(
                row, "memory", worker, self.clock(),
                realized_compute, self.telemetry,
            )
        self._exit_processing_common(ts)
        if nbytes is not None:
            self.update_nbytes(ts, nbytes)
        self.add_replica(ts, wws)
        ts.state = "memory"
        ts.type = typename
        if typename and ts.group is not None:
            ts.group.types.add(typename)
        self._count_transition(ts, "processing", "memory")

        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        self._notify_waiters_task_in_memory(ts, recommendations, client_msgs)
        return recommendations, client_msgs, {}

    def _transition_processing_erred(
        self,
        key: Key,
        stimulus_id: str,
        *,
        worker: str | None = None,
        cause: Key | None = None,
        exception: Any = None,
        traceback: Any = None,
        exception_text: str = "",
        traceback_text: str = "",
        **kwargs: Any,
    ) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        failing_ws = ts.processing_on
        if failing_ws is not None:
            if ts.ledger_row >= 0:
                self.ledger.join_row(
                    ts.ledger_row, "erred", worker or "", self.clock(),
                )
                ts.ledger_row = -1
            self._exit_processing_common(ts)
        if self.validate:
            assert cause or ts.exception_blame
        if ts.actor and failing_ws is not None:
            failing_ws.actors.discard(ts)

        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}

        if ts.retries > 0:
            ts.retries -= 1
            ts.state = "released"
            self._count_transition(ts, "processing", "released")
            recommendations[key] = "waiting"
            return recommendations, client_msgs, {}

        if exception is not None:
            # erred state can outlive the wire message indefinitely:
            # compact so the stored frames stop pinning the receive buffer
            ts.exception = compact_frames(exception)
            ts.exception_text = exception_text
        if traceback is not None:
            ts.traceback = compact_frames(traceback)
            ts.traceback_text = traceback_text
        if cause is not None:
            ts.exception_blame = self.tasks.get(cause)
        if worker:
            ts.erred_on.add(worker)
        blame = ts.exception_blame or ts

        for dts in ts.dependents:
            dts.exception_blame = blame
            recommendations[dts.key] = "erred"
        for dts in ts.dependencies:
            dts.waiters.discard(ts)
            if not dts.waiters and not dts.who_wants:
                recommendations[dts.key] = "released"
        ts.waiters.clear()
        ts.state = "erred"
        self._count_transition(ts, "processing", "erred")

        report_msg = {
            "op": "task-erred",
            "key": key,
            "exception": blame.exception,
            "traceback": blame.traceback,
        }
        for cs in ts.who_wants:
            client_msgs.setdefault(cs.client_key, []).append(report_msg)
        self.log_event(
            "all",
            {
                "action": "task-erred",
                "key": key,
                "exception": ts.exception_text,
                "worker": worker,
            },
        )
        return recommendations, client_msgs, {}

    def _transition_released_erred(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert ts.exception_blame
            assert not ts.who_has
            assert not ts.waiting_on
        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        failure = ts.exception_blame
        assert failure is not None
        for dts in ts.dependents:
            if dts.state not in ("erred", "forgotten"):
                dts.exception_blame = failure
                recommendations[dts.key] = "erred"
        report_msg = {
            "op": "task-erred",
            "key": key,
            "exception": failure.exception,
            "traceback": failure.traceback,
        }
        for cs in ts.who_wants:
            client_msgs.setdefault(cs.client_key, []).append(report_msg)
        ts.state = "erred"
        self._count_transition(ts, "released", "erred")
        return recommendations, client_msgs, {}

    def _transition_erred_released(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        ts.exception = None
        ts.exception_blame = None
        ts.traceback = None
        # build free-keys messages before clearing the erred_on record
        w_msg = {"op": "free-keys", "keys": [key], "stimulus_id": stimulus_id}
        worker_msgs = {addr: [w_msg] for addr in ts.erred_on if addr in self.workers}
        ts.erred_on.clear()
        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        for dts in ts.dependents:
            if dts.state == "erred":
                recommendations[dts.key] = "waiting"
        report_msg = {"op": "task-retried", "key": key}
        for cs in ts.who_wants:
            client_msgs.setdefault(cs.client_key, []).append(report_msg)
        ts.state = "released"
        self._count_transition(ts, "erred", "released")
        return recommendations, client_msgs, worker_msgs

    def _transition_no_worker_released(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        del self.unrunnable[ts]
        ts.state = "released"
        self._count_transition(ts, "no-worker", "released")
        recommendations: dict[Key, str] = {}
        self._propagate_released_followup(ts, recommendations)
        return recommendations, {}, {}

    def _transition_no_worker_erred(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        """no-workers-timeout expiry: unsatisfiable restrictions fail the
        task instead of parking it forever (reference no-workers-timeout)."""
        ts = self.tasks[key]
        del self.unrunnable[ts]
        recommendations: dict[Key, str] = {}
        # deregister from dependencies exactly like processing->erred:
        # the failed task must not pin its (possibly in-memory) deps
        for dts in ts.dependencies:
            dts.waiters.discard(ts)
            if not dts.waiters and not dts.who_wants:
                recommendations[dts.key] = "released"
        # a bare-dep reroute can park a no-worker task with waiting_on
        # set; released->erred asserts it empty under validate
        for dts in list(ts.waiting_on):
            dts.waiters.discard(ts)
        ts.waiting_on.clear()
        ts.state = "released"
        self._count_transition(ts, "no-worker", "released")
        recs2, client_msgs, worker_msgs = self._transition_released_erred(
            key, stimulus_id
        )
        recommendations.update(recs2)
        return recommendations, client_msgs, worker_msgs

    def _transition_no_worker_processing(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if ws := self.decide_worker_non_rootish(ts):
            del self.unrunnable[ts]
            worker_msgs = self._add_to_processing(ts, ws, stimulus_id)
            self._count_transition(ts, "no-worker", "processing")
            return {}, {}, worker_msgs
        if ts.waiting_on:
            # bare-dep reroute (see _transition_waiting_processing): move back
            # to waiting and recompute the deps whose replicas vanished —
            # skipping deps already on their way back (same filter as the
            # waiting-path branch: a sibling's reroute must not cancel an
            # in-flight recompute)
            del self.unrunnable[ts]
            ts.state = "waiting"
            self._count_transition(ts, "no-worker", "waiting")
            return (
                {
                    dts.key: (
                        "waiting" if dts.state == "released" else "released"
                    )
                    for dts in ts.waiting_on
                    if dts.state not in (
                        "waiting", "queued", "no-worker", "processing"
                    )
                },
                {},
                {},
            )
        return {}, {}, {}

    def _transition_memory_released(
        self, key: Key, stimulus_id: str, *, safe: bool = False
    ) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert not ts.waiting_on
            assert not ts.processing_on
            if safe:
                assert not ts.waiters
        if ts.actor:
            for ws in ts.who_has:
                ws.actors.discard(ts)
            if ts.who_wants:
                ts.exception_blame = ts
                ts.exception = "Worker holding Actor was lost"
                return {ts.key: "erred"}, {}, {}

        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        worker_msgs: dict = {}
        # dependents that were waiting on us must go back to waiting
        for dts in ts.waiters:
            if dts.state in ("no-worker", "processing", "queued"):
                recommendations[dts.key] = "waiting"
            elif dts.state == "waiting":
                dts.waiting_on.add(ts)
        # free replicas on all workers
        freed = [ws.address for ws in ts.who_has]
        for ws in list(ts.who_has):
            self.remove_replica(ts, ws)
        for addr in freed:
            if addr in self.workers:
                worker_msgs.setdefault(addr, []).append(
                    {"op": "free-keys", "keys": [key], "stimulus_id": stimulus_id}
                )
        ts.state = "released"
        self._count_transition(ts, "memory", "released")
        report_msg = {"op": "lost-data", "key": key}
        for cs in ts.who_wants:
            client_msgs.setdefault(cs.client_key, []).append(report_msg)
        if not ts.run_spec:  # pure data (scatter) — cannot be recomputed
            recommendations[key] = "forgotten"
        elif not ts.exception_blame and (ts.who_wants or ts.waiters):
            # exception_blame guard: a task being routed memory->erred
            # (e.g. shuffle restart-budget exhaustion) must not be
            # resurrected here — the composed transition would let this
            # "waiting" override the "erred" target
            recommendations[key] = "waiting"
        if recommendations.get(key) == "waiting":
            for dts in ts.dependencies:
                dts.waiters.add(ts)
        else:
            self._deregister_waiter(ts, recommendations)
        return recommendations, client_msgs, worker_msgs

    def _transition_released_forgotten(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert ts.state in ("released", "erred")
            assert not ts.who_has
            assert not ts.processing_on
            assert not ts.waiting_on
            # pure data (scatter) may be forgotten while dependents
            # remain: it cannot be recomputed, so holding the record
            # preserves nothing — the reference allows exactly this
            # ("It's ok to forget a pure data task", scheduler.py
            # _transition_released_forgotten).  Found by the simulator's
            # scatter -> consume -> client-release flow under validate.
            if ts.run_spec is not None:
                assert not any(
                    dts.state != "forgotten" for dts in ts.dependents
                ), (ts, [d for d in ts.dependents if d.state != "forgotten"])
        recommendations: dict[Key, str] = {}
        self._propagate_forgotten(ts, recommendations)
        client_msgs = self._task_erred_or_forgotten_report(ts)
        self.remove_all_replicas(ts)
        self._remove_task(ts)
        return recommendations, client_msgs, {}

    def _transition_memory_forgotten(self, key: Key, stimulus_id: str) -> tuple[dict, dict, dict]:
        ts = self.tasks[key]
        if self.validate:
            assert ts.state == "memory"
            assert not ts.processing_on
            assert not ts.waiting_on
        recommendations: dict[Key, str] = {}
        worker_msgs: dict = {}
        for ws in ts.who_has:
            worker_msgs.setdefault(ws.address, []).append(
                {"op": "free-keys", "keys": [key], "stimulus_id": stimulus_id}
            )
        self._propagate_forgotten(ts, recommendations)
        client_msgs = self._task_erred_or_forgotten_report(ts)
        self.remove_all_replicas(ts)
        self._remove_task(ts)
        return recommendations, client_msgs, worker_msgs

    # --------------------------------------------- transition helper pieces

    def _count_transition(self, ts: TaskState, start: str, finish: str) -> None:
        if ts.group is not None:
            ts.group.states[start] -= 1
            ts.group.states[finish] += 1
        if ts.prefix is not None:
            ts.prefix.state_counts[finish] += 1

    def _propagate_released_followup(self, ts: TaskState, recommendations: dict) -> None:
        """After a task lands in released: rerun, or forget, or stay."""
        if not ts.dependents and not ts.who_wants:
            recommendations[ts.key] = "forgotten"
        elif not ts.exception_blame and (ts.who_wants or ts.waiters):
            recommendations[ts.key] = "waiting"
            for dts in ts.dependencies:
                dts.waiters.add(ts)
        else:
            # staying released (nobody reruns us): deregister as a waiter
            # so finished deps can be collected — tasks register on EVERY
            # dep at scheduling time (released->waiting), so without this
            # a released-for-good task pins its deps in memory forever
            self._deregister_waiter(ts, recommendations)

    def _deregister_waiter(self, ts: TaskState, recommendations: dict) -> None:
        for dts in ts.dependencies:
            if ts in dts.waiters:
                dts.waiters.discard(ts)
                if not dts.waiters and not dts.who_wants:
                    recommendations[dts.key] = "released"

    def _remove_from_waiting(self, ts: TaskState, recommendations: dict) -> None:
        for dts in ts.waiting_on:
            dts.waiters.discard(ts)
            if not dts.waiters and not dts.who_wants:
                recommendations[dts.key] = "released"
        ts.waiting_on.clear()

    def _notify_waiters_task_in_memory(
        self, ts: TaskState, recommendations: dict, client_msgs: dict
    ) -> None:
        """Task hit memory: unblock waiters, report to clients, release
        no-longer-needed dependencies (reference scheduler.py:2366 tail)."""
        for dts in list(ts.dependents):
            if ts in dts.waiting_on:
                dts.waiting_on.discard(ts)
                if not dts.waiting_on and dts.state == "waiting":
                    recommendations[dts.key] = "processing"
        for dts in ts.dependencies:
            dts.waiters.discard(ts)
            if not dts.waiters and not dts.who_wants:
                recommendations[dts.key] = "released"
        if not ts.waiters and not ts.who_wants:
            recommendations[ts.key] = "released"
        else:
            report_msg = {
                "op": "key-in-memory",
                "key": ts.key,
                "type": ts.type,
            }
            for cs in ts.who_wants:
                client_msgs.setdefault(cs.client_key, []).append(report_msg)

    def _task_erred_or_forgotten_report(self, ts: TaskState) -> dict:
        client_msgs: dict = {}
        if ts.who_wants:
            report_msg = {"op": "cancelled-keys", "keys": [ts.key]}
            for cs in ts.who_wants:
                client_msgs.setdefault(cs.client_key, []).append(report_msg)
        return client_msgs

    def _propagate_forgotten(self, ts: TaskState, recommendations: dict) -> None:
        self._count_transition(ts, ts.state, "forgotten")
        ts.state = "forgotten"
        for dts in ts.dependents:
            dts.dependencies.discard(ts)
            dts.waiting_on.discard(ts)
        ts.dependents.clear()
        ts.waiters.clear()
        for dts in ts.dependencies:
            dts.dependents.discard(ts)
            dts.waiters.discard(ts)
            if not dts.dependents and not dts.who_wants:
                recommendations[dts.key] = "forgotten"
        ts.dependencies.clear()
        ts.waiting_on.clear()

    def _remove_task(self, ts: TaskState) -> None:
        if ts.group is not None:
            tg = ts.group
            tg.n_tasks -= 1
            if tg.n_tasks <= 0:
                self.task_groups.pop(tg.name, None)
                if tg.prefix is not None:
                    tg.prefix.groups.discard(tg)
        for cs in list(ts.who_wants):
            cs.wants_what.discard(ts)
        ts.who_wants.clear()
        self.tasks.pop(ts.key, None)
        if self.native is not None:
            self.native.on_forget_task(ts)
        if self.durability is not None:
            self.durability.on_remove_task(ts)

    def _exit_processing_common(self, ts: TaskState) -> None:
        """Remove from processing_on worker and fix occupancy
        (reference _exit_processing_common scheduler.py:3264)."""
        ws = ts.processing_on
        assert ws is not None
        # stealing's confirm path calls this OUTSIDE a _transition, so
        # the SoA mark cannot ride the _transition funnel
        if self.native is not None:
            self.native.mark_task(ts)
        if self.durability is not None:
            self.durability.mark_replica(ts, ws)
        ts.processing_on = None
        ts.homed = False
        duration = ws.processing.pop(ts, 0.0)
        was_long_running = ts in ws.long_running
        ws.long_running.discard(ts)
        ws.executing.pop(ts, None)
        if not was_long_running:
            self._adjust_occupancy(ws, -duration)
        if not ws.processing:
            self._total_occupancy -= ws.occupancy
            ws.occupancy = 0.0
        if ts.resource_restrictions:
            for r, quantity in ts.resource_restrictions.items():
                if r in ws.used_resources:
                    ws.used_resources[r] -= quantity
        self.check_idle_saturated(ws)

    def _add_to_processing(
        self, ts: TaskState, ws: WorkerState, stimulus_id: str,
        kind: str | None = None,
    ) -> dict:
        """Assign ts to ws (reference scheduler.py:3199).

        ``kind`` labels the decision in the ledger (``steal`` /
        ``steal-spec`` from the stealing extension); ``None`` derives
        ``plan`` for jax_placement plan homes and ``placement``
        otherwise."""
        if self.validate:
            assert not ts.waiting_on
            assert not ts.who_has
            assert not ts.exception_blame
            assert not ts.processing_on
            assert ws in self.running, (ws, ts)
        duration = self.get_task_duration(ts)
        comm = self.get_comm_cost(ts, ws)
        # shadow divergence monitor (read-only): this is THE placement
        # decision — record what the measured model would have priced
        self.shadow_comm_cost(ts, ws, comm, "placement", stimulus_id)
        led = self.ledger
        if led.enabled:
            if ts.dependencies or (kind is None and ts.homed):
                # dep-bearing (link pricing) or homed (plan/pin kind
                # derivation incl. plan_stim): the full filing helper
                self.ledger_file_decision(ts, ws, stimulus_id, kind,
                                          duration, comm)
            else:
                # dep-free fast path, inlined: no links to price, both
                # models predict 0 transfer — the row carries identity
                # + the duration prediction only
                prefix = ts.prefix
                ts.ledger_row = led.file(
                    kind if kind is not None else "placement", ts.key,
                    prefix.name if prefix is not None else "",
                    ws.address, stimulus_id, comm, comm, False,
                    0, 0, duration, "", "",
                    supersede=ts.ledger_row,
                )
        # stealing's re-placement calls this OUTSIDE a _transition (see
        # _exit_processing_common); the mark must not depend on the
        # _transition funnel
        if self.native is not None:
            self.native.mark_task(ts)
        if self.durability is not None:
            self.durability.mark_replica(ts, ws)
        ws.processing[ts] = duration + comm
        ts.processing_on = ws
        ts.state = "processing"
        # occupancy is booked in raw seconds of queued work; consumers divide
        # by nthreads once at compare time (reference scheduler.py:3140)
        self._adjust_occupancy(ws, duration + comm)
        if ts.resource_restrictions:
            for r, quantity in ts.resource_restrictions.items():
                ws.used_resources[r] = ws.used_resources.get(r, 0) + quantity
        if ts.actor:
            ws.actors.add(ts)
        self.check_idle_saturated(ws)
        return {ws.address: [self._task_to_msg(ts, stimulus_id)]}

    def _task_to_msg(self, ts: TaskState, stimulus_id: str) -> dict:
        """Build the compute-task message (reference scheduler.py:3421).

        ``run_spec`` arrived from the client as an opaque wrapper
        (``Serialize`` over inproc, ``Serialized`` frames over tcp —
        the scheduler runs deserialize=False) and is forwarded to the
        worker verbatim: no unpickle/repickle on the scheduler, and no
        user code needed here (reference scheduler.py:3438).  Raw specs
        (internal callers, tests) are wrapped so they cross tcp pickled.
        """
        assert ts.priority is not None
        return {
            "op": "compute-task",
            "key": ts.key,
            "priority": ts.priority,
            "stimulus_id": stimulus_id,
            "who_has": {
                dts.key: [wws.address for wws in dts.who_has] for dts in ts.dependencies
            },
            "nbytes": {dts.key: dts.nbytes for dts in ts.dependencies},
            "run_spec": wrap_opaque(ts.run_spec),
            "duration": self.get_task_duration(ts),
            "resource_restrictions": ts.resource_restrictions,
            "actor": ts.actor,
            "annotations": ts.annotations or {},
            "span_id": ts.group.span_id if ts.group else None,
        }

    # ------------------------------------------------------- cost model

    def get_task_duration(self, ts: TaskState) -> float:
        """Estimated runtime (reference scheduler.py:2986)."""
        prefix = ts.prefix
        duration = prefix.duration_average if prefix is not None else -1.0
        if duration >= 0:
            return duration
        if prefix is not None:
            s = self.unknown_durations.setdefault(prefix.name, set())
            s.add(ts)
        return self.UNKNOWN_TASK_DURATION

    def get_comm_cost(self, ts: TaskState, ws: WorkerState) -> float:
        """Bytes that must move to run ts on ws, over bandwidth
        (reference scheduler.py:3003)."""
        if len(ts.dependencies) < 10:
            deps = [dts for dts in ts.dependencies if ws not in dts.who_has]
        else:
            deps = [
                dts for dts in ts.dependencies.difference(ws.has_what)
            ]
        nbytes = sum(dts.get_nbytes() for dts in deps)
        return nbytes / self.bandwidth + len(deps) * self.transfer_latency

    def get_comm_cost_measured(
        self, ts: TaskState, ws: WorkerState
    ) -> tuple[float, bool]:
        """The measured-model twin of :meth:`get_comm_cost` — same
        shape (missing-dep bytes over bandwidth plus a per-dep fixed
        cost) with per-link MEASURED inputs where the telemetry plane
        has them (telemetry.py):

        - bandwidth: the best (highest-EWMA) measured link from any of
          the dep's holders to ``ws`` — the optimistic achievable
          fetch, matching gather's freedom to pick any holder;
        - fixed cost: that link's residual-latency EWMA, else the
          worker's heartbeat-RTT EWMA, else ``transfer_latency``;
        - constant fallback for links never observed.

        Returns ``(cost, used_measured)`` — the flag marks whether any
        measured link actually priced a dep (a pure-fallback cost says
        nothing about the constants).  READ-ONLY shadow: no decision
        path consults this (ROADMAP item 3 swaps the inputs later).
        """
        tel = self.telemetry
        rtt = tel.rtt.get(ws.address, 0.0)
        total = 0.0
        used_measured = False
        for dts in ts.dependencies:
            if ws in dts.who_has:
                continue
            nb = dts.get_nbytes()
            best_bw = 0.0
            best_lat = -1.0
            for hws in dts.who_has:
                link = tel.links.get((hws.address, ws.address))
                if link is not None and link.bandwidth.count:
                    bw = link.bandwidth.value
                    if bw > best_bw:
                        best_bw = bw
                        best_lat = link.latency.value
            if best_bw > 0.0:
                used_measured = True
                total += nb / best_bw + best_lat
            elif rtt > 0.0:
                # unseen link, but the fleet's control-plane RTT is
                # measured: constant bandwidth + measured fixed cost
                used_measured = True
                total += nb / self.bandwidth + rtt
            else:
                total += nb / self.bandwidth + self.transfer_latency
        return total, used_measured

    def shadow_comm_cost(self, ts: TaskState, ws: WorkerState,
                         constant: float | None, site: str,
                         stimulus_id: str) -> None:
        """Shadow cost-model divergence monitor: next to a decision that
        just priced ``ts`` on ``ws`` with the CONSTANT model, compute
        the measured model and record ``measured / constant`` in the
        ``dtpu_costmodel_divergence_ratio`` histogram plus a sampled
        flight-recorder ``shadow`` event carrying the stimulus id — so
        Perfetto shows which decisions the constants are lying about.
        Zero behavior change: callers already made their decision.

        Pass ``constant=None`` from callers that did NOT already
        compute the constant cost for their own use — it is then
        computed here, BEHIND the enabled/sampling gates, so a
        disabled or sampled-out eval costs two attribute reads."""
        tel = self.telemetry
        if not tel.enabled or not tel.tick_divergence():
            return
        if constant is None:
            constant = self.get_comm_cost(ts, ws)
        measured, used_measured = self.get_comm_cost_measured(ts, ws)
        ratio = tel.observe_divergence(constant, measured, used_measured)
        self.trace.emit_task(
            "shadow", site, stimulus_id, key=ts.key,
            n=int(ratio * 1000), dest=ws.address,
        )

    # --------------------------------------------- decision ledger filing

    def ledger_file_decision(self, ts: TaskState, ws: WorkerState,
                             stimulus_id: str, kind: str | None,
                             duration: float, comm: float,
                             now: float | None = None) -> None:
        """File one task-cost decision row (ledger.py): the prediction
        half — constant comm cost, the measured shadow's price, the
        missing-dep byte total, and the dominant dep link (best holder
        of the heaviest missing dep).  The realized half joins when the
        task reaches memory/erred (docs/observability.md).  ``now``
        carries the flood-hoisted decision stamp when the native engine
        replays deferred tape rows (the ledger digest folds it, so the
        stamp must match what the eager path would have read)."""
        dep_bytes = 0
        n_deps = 0
        src = ""
        measured, used = comm, False
        if ts.dependencies:
            heaviest = -1
            for dts in ts.dependencies:
                if ws in dts.who_has:
                    continue
                nb = dts.get_nbytes()
                dep_bytes += nb
                n_deps += 1
                if nb > heaviest:
                    heaviest = nb
                    for hws in dts.who_has:
                        src = hws.address
                        break
            if n_deps:
                tel = self.telemetry
                if tel.enabled and (tel.links or tel.rtt):
                    measured, used = self.get_comm_cost_measured(ts, ws)
                # else: nothing measured yet — the measured model falls
                # back to the constants dep-for-dep, so its price IS
                # ``comm``; skip the recompute on the flood hot path
        plan_stim = ""
        if kind is None:
            if ts.homed == "plan":
                # a jax_placement plan home — NOT a shuffle "pin"
                # (ts.homed carries the provenance): stamp the landed
                # plan's stimulus so the row joins its kernel event
                kind = "plan"
                if self.placement is not None:
                    plan_stim = getattr(self.placement, "plan_stim", "")
            else:
                kind = "placement"
        prefix = ts.prefix
        ts.ledger_row = self.ledger.file(
            kind, ts.key, prefix.name if prefix is not None else "",
            ws.address, stimulus_id, comm, measured, used,
            dep_bytes, n_deps, duration, src, plan_stim,
            supersede=ts.ledger_row, now=now,
        )

    def get_replica_cost_measured(
        self, ts: TaskState, ws: WorkerState
    ) -> tuple[float, bool]:
        """Measured transfer price for moving ``ts``'s own payload to
        ``ws`` (the AMM replica decision's cost): best measured holder
        link, RTT fallback, constant fallback — the replica twin of
        :meth:`get_comm_cost_measured`'s per-dep pricing."""
        tel = self.telemetry
        nb = ts.get_nbytes()
        best_bw = 0.0
        best_lat = -1.0
        for hws in ts.who_has:
            link = tel.links.get((hws.address, ws.address))
            if link is not None and link.bandwidth.count:
                bw = link.bandwidth.value
                if bw > best_bw:
                    best_bw = bw
                    best_lat = link.latency.value
        if best_bw > 0.0:
            return nb / best_bw + best_lat, True
        rtt = tel.rtt.get(ws.address, 0.0)
        if rtt > 0.0:
            return nb / self.bandwidth + rtt, True
        return nb / self.bandwidth + self.transfer_latency, False

    def worker_objective(self, ts: TaskState, ws: WorkerState) -> tuple:
        """Lower is better (reference scheduler.py:3131 — plus a fixed
        per-missing-dep latency term the reference lacks: with tiny
        chunks, bytes/bandwidth alone calls transfers free and the
        objective degenerates to load-balancing, scattering reduction
        trees and drowning the loop in gather_dep RPCs)."""
        n_missing = 0
        dep_bytes = 0
        for dts in ts.dependencies:
            if ws not in dts.who_has:
                n_missing += 1
                dep_bytes += dts.get_nbytes()
        stack_time = (
            ws.occupancy / max(ws.nthreads, 1)
            + dep_bytes / self.bandwidth
            + n_missing * self.transfer_latency
        )
        start_time = stack_time + self.get_task_duration(ts)
        if ts.actor:
            return (len(ws.actors), start_time, ws.nbytes)
        return (start_time, ws.nbytes)

    # ------------------------------------------------------- placement

    def observe_engine_shards(self, shards: list[dict]) -> None:
        """Fold one sharded plan's per-shard stats (from
        ``ops/leveled.place_graph_leveled_sharded``) into the
        /metrics-facing aggregates: kernel ms is last-plan, H2D bytes
        and plan count accumulate."""
        if len(self.engine_shards) != len(shards):
            self.engine_shards = [
                {"kernel_ms": 0.0, "h2d_bytes": 0, "plans": 0}
                for _ in shards
            ]
        for agg, s in zip(self.engine_shards, shards):
            agg["kernel_ms"] = float(s.get("kernel_ms", 0.0))
            agg["h2d_bytes"] += int(s.get("h2d_bytes", 0))
            agg["plans"] += 1

    def is_rootish(self, ts: TaskState) -> bool:
        """Root-ish: a task in a large group with few deps
        (reference scheduler.py:2929)."""
        if ts._rootish is not None:
            return ts._rootish
        if ts.resource_restrictions or ts.worker_restrictions or ts.host_restrictions:
            return False
        tg = ts.group
        if tg is None:
            return False
        return (
            len(tg) > self.total_nthreads * 2
            and len(tg.dependencies) < 5
            and sum(map(len, tg.dependencies)) < 5
        )

    def decide_worker_rootish_queuing_disabled(self, ts: TaskState) -> WorkerState | None:
        """Co-assign sibling root tasks to the same worker
        (reference scheduler.py:2135)."""
        assert ts.group is not None
        tg = ts.group
        lws = tg.last_worker
        if not (lws and tg.last_worker_tasks_left and lws.address in self.workers
                and lws.status == WORKER_STATUS_RUNNING):
            # pick the least-occupied running worker
            lws = min(
                self.running,
                key=lambda ws: (len(ws.processing) / max(ws.nthreads, 1), ws.nbytes, ws.address),
                default=None,
            )
            if lws is None:
                return None
            tg.last_worker_tasks_left = len(tg) // max(len(self.running), 1) or 1
        tg.last_worker = lws
        tg.last_worker_tasks_left -= 1
        if tg.last_worker_tasks_left == 0:
            tg.last_worker = None
        return lws

    def decide_worker_rootish_queuing_enabled(self) -> WorkerState | None:
        """Least-busy idle worker, or None to queue
        (reference scheduler.py:2195)."""
        if not self.idle_task_count:
            return None
        ws = min(
            self.idle_task_count,
            key=lambda ws: (len(ws.processing) / max(ws.nthreads, 1), ws.address),
        )
        if self.validate:
            assert not _worker_full(ws, self.WORKER_SATURATION), (ws, self.WORKER_SATURATION)
        return ws

    def _valid_or_running(self, ts: TaskState) -> set[WorkerState] | None:
        """Restriction set for placement decisions; running-only when
        some workers are paused (same narrowing as decide_worker_non_rootish)."""
        valid_workers = self.valid_workers(ts)
        if valid_workers is None and len(self.running) < len(self.workers):
            valid_workers = self.running
        return valid_workers

    def decide_worker_non_rootish(self, ts: TaskState) -> WorkerState | None:
        """Place by data locality + occupancy (reference scheduler.py:2247, 8550)."""
        if not self.running:
            return None
        valid_workers = self._valid_or_running(ts)
        if self.placement is not None and self.placement.wants(ts):
            ws = self.placement.decide_worker(self, ts, valid_workers)
            if ws is not None:
                return ws
        return self._decide_worker_locality(ts, valid_workers)

    def _decide_worker_locality(
        self, ts: TaskState, valid_workers: set[WorkerState] | None
    ) -> WorkerState | None:
        """The python oracle for decide_worker (reference scheduler.py:8550).

        A dependency may lose its last replica between the transition that
        recommended this placement and the placement itself (worker death
        races).  The reference guards the invariant check behind ``validate``
        (reference scheduler.py:2247-2250); in production we reroute the
        bare dependency through ``released`` instead of crashing.
        """
        if self.validate:
            assert all(dts.who_has for dts in ts.dependencies), (
                ts,
                [d for d in ts.dependencies if not d.who_has],
            )
        bare = [dts for dts in ts.dependencies if not dts.who_has]
        if bare:
            # Replica vanished in a race: park this task back in waiting on
            # the bare deps; _transition_waiting_processing kicks recompute.
            for dts in bare:
                ts.waiting_on.add(dts)
                dts.waiters.add(ts)
            return None
        if ts.actor:
            candidates = set(self.running)
        else:
            candidates = {ws for dts in ts.dependencies for ws in dts.who_has}
            candidates &= self.running
        if valid_workers is None:
            if not candidates:
                candidates = set(self.running)
        else:
            candidates &= valid_workers
            if not candidates:
                candidates = valid_workers & self.running
                if not candidates:
                    if ts.loose_restrictions:
                        return self._decide_worker_locality(ts, None)
                    return None
        if not candidates:
            return None
        if len(candidates) == 1:
            return next(iter(candidates))
        return min(
            candidates, key=lambda ws: self.worker_objective(ts, ws) + (ws.address,)
        )

    def valid_workers(self, ts: TaskState) -> set[WorkerState] | None:
        """Workers satisfying ts's restrictions; None = all
        (reference scheduler.py:3043)."""
        if not ts.has_restrictions:
            return None
        s: set[WorkerState] | None = None
        if ts.worker_restrictions:
            s = {
                self.workers[addr]
                for addr in ts.worker_restrictions
                if addr in self.workers
            }
        if ts.host_restrictions:
            hosts = {
                ws
                for ws in self.workers.values()
                if ws.address.rsplit(":", 1)[0].split("://")[-1] in ts.host_restrictions
                or str(ws.name) in ts.host_restrictions
            }
            s = hosts if s is None else s & hosts
        if ts.resource_restrictions:
            # filter by total SUPPLY, not currently-free amount (reference
            # scheduler.py:3043 checks self.resources supply): the worker
            # state machine serializes execution against its available
            # resources, so oversubscribed processing just queues there.
            # Filtering by free amount sends later tasks to "no-worker"
            # with nothing to ever wake them once the resource frees.
            res_ok = {
                ws
                for ws in self.workers.values()
                if all(
                    ws.resources.get(r, 0) >= q
                    for r, q in ts.resource_restrictions.items()
                )
            }
            s = res_ok if s is None else s & res_ok
        return s if s is not None else set()

    # ------------------------------------------------ idle/saturated model

    def check_idle_saturated(self, ws: WorkerState, occ: float | None = None) -> None:
        """Update the idle/saturated sets (reference scheduler.py:2949)."""
        # callers reach here after any occupancy/processing change, so
        # this is the mirror's cheapest single choke point — mark before
        # the early return (the return skips set updates, not mutations
        # the caller already made)
        if self.mirror is not None:
            self.mirror.mark(ws)
        if self.native is not None:
            self.native.mark_worker(ws)
        if self.total_nthreads == 0 or ws.status == WORKER_STATUS_CLOSED:
            return
        if occ is None:
            occ = ws.occupancy
        p = len(ws.processing)
        avg = self.total_occupancy / self.total_nthreads if self.total_nthreads else 0

        idle = self.idle
        saturated = self.saturated
        if (p < ws.nthreads or occ < ws.nthreads * avg / 2) and ws.status == WORKER_STATUS_RUNNING:
            idle[ws.address] = ws
            saturated.discard(ws)
        else:
            idle.pop(ws.address, None)
            nc = ws.nthreads
            if p > nc and occ > nc * avg:
                saturated.add(ws)
            else:
                saturated.discard(ws)

        if not _worker_full(ws, self.WORKER_SATURATION) and ws.status == WORKER_STATUS_RUNNING:
            self.idle_task_count.add(ws)
        else:
            self.idle_task_count.discard(ws)

    @property
    def total_occupancy(self) -> float:
        return self._total_occupancy

    def _adjust_occupancy(self, ws: WorkerState, delta: float) -> None:
        ws.occupancy = max(0.0, ws.occupancy + delta)
        self._total_occupancy = max(0.0, self._total_occupancy + delta)
        if self.mirror is not None:
            self.mirror.mark(ws)
        if self.native is not None:
            self.native.mark_worker(ws)

    def _task_slots_available(self, ws: WorkerState) -> int:
        """Open slots below the saturation threshold (reference scheduler.py:8762)."""
        if ws.status != WORKER_STATUS_RUNNING:
            return 0
        return max(
            math_ceil(ws.nthreads * self.WORKER_SATURATION) - len(ws.processing), 0
        )

    # ------------------------------------------------------- parked tasks

    def park_task(self, ts: TaskState, ws: WorkerState) -> None:
        """Register a queued task as waiting for ws's next slot-open.
        Parked tasks live in ``queued`` (state invariants) but NOT in
        ``queued_unparked`` (global pops)."""
        heap = self.parked.get(ws.address)
        if heap is None:
            heap = self.parked[ws.address] = HeapSet(
                key=lambda t: t.priority
            )
        heap.add(ts)
        self._parked_keys[ts.key] = ws.address
        self.queued_unparked.discard(ts)

    def unpark_task(self, ts: TaskState, requeue: bool = True) -> None:
        """Drop park bookkeeping; re-enter global pops when ``requeue``
        (leaving-queued callers pass False)."""
        addr = self._parked_keys.pop(ts.key, None)
        if addr is not None:
            heap = self.parked.get(addr)
            if heap is not None:
                heap.discard(ts)
                if not heap:
                    del self.parked[addr]
            if requeue and ts.state == "queued":
                self.queued_unparked.add(ts)

    def is_parked(self, key: Key) -> bool:
        return key in self._parked_keys

    def splice_parked(self, address: str) -> None:
        """Return every task parked for ``address`` to the global pop
        heap — the home can no longer pull (paused / removed / dead)."""
        heap = self.parked.pop(address, None)
        if heap is not None:
            for ts in list(heap):
                self._parked_keys.pop(ts.key, None)
                if ts.state == "queued":
                    self.queued_unparked.add(ts)

    def _parked_pop_for(self, ws: WorkerState, n: int) -> list[TaskState]:
        """Up to n parked tasks for ws, best priority first — DESTRUCTIVE
        (the queued->processing transition re-parks any that must keep
        waiting), so repeatedly-scanned stale entries never build up."""
        heap = self.parked.get(ws.address)
        if heap is None:
            return []
        out: list[TaskState] = []
        while heap and len(out) < n:
            ts = heap.pop()
            self._parked_keys.pop(ts.key, None)
            if ts.state == "queued":
                out.append(ts)
        if not heap:
            self.parked.pop(ws.address, None)
        return out

    def stimulus_queue_slots_maybe_opened(self, stimulus_id: str) -> dict[Key, str]:
        """Pop exactly as many queued tasks as there are open slots
        (reference scheduler.py:4983).

        Each open-slot worker first pulls tasks PARKED for it (the
        placement plan's co-assignment, pulled past the slot line so the
        worker pipeline never drains between stimuli); the global
        priority order over non-parked tasks fills what remains."""
        if not self.queued:
            return {}
        recs: dict[Key, str] = {}
        slots = 0
        if self._parked_keys:
            for ws in self.idle_task_count:
                s = self._task_slots_available(ws)
                slots += s
                if ws.address in self.parked:
                    for ts in self._parked_pop_for(ws, s + ws.nthreads):
                        recs[ts.key] = "processing"
        else:
            slots = sum(
                self._task_slots_available(ws) for ws in self.idle_task_count
            )
        remaining = slots - len(recs)
        if remaining > 0 and self.queued_unparked:
            for ts in self.queued_unparked.peekn(remaining):
                recs[ts.key] = "processing"
        return recs

    def stimulus_no_workers_timeout(
        self, timeout: float, stimulus_id: str
    ) -> tuple[dict, dict]:
        """Fail tasks stuck in no-worker longer than ``timeout``
        (reference scheduler.no-workers-timeout): their restrictions
        cannot be satisfied by the current fleet, and waiting forever
        hides the misconfiguration from the client."""
        now = self.clock()
        recs: dict[Key, str] = {}
        for ts, since in list(self.unrunnable.items()):
            if now - since <= timeout:
                continue
            exc = NoValidWorkerError(
                ts.key,
                worker_restrictions=sorted(ts.worker_restrictions)
                if ts.worker_restrictions else None,
                resource_restrictions=dict(ts.resource_restrictions)
                if ts.resource_restrictions else None,
            )
            ts.exception = exc
            ts.exception_text = (
                f"no running worker satisfies the restrictions of "
                f"{ts.key!r} within the no-workers-timeout"
            )
            ts.exception_blame = ts
            recs[ts.key] = "erred"
        if not recs:
            return {}, {}
        return self.transitions(recs, stimulus_id)

    # ------------------------------------------------------ replica model

    def add_replica(self, ts: TaskState, ws: WorkerState) -> None:
        """Record that ws holds a replica of ts (reference scheduler.py:4760)."""
        if ws in ts.who_has:
            return
        ws.nbytes += ts.get_nbytes()
        ws.has_what[ts] = None
        ts.who_has.add(ws)
        if len(ts.who_has) == 2:
            self.replicated_tasks.add(ts)
        if self.mirror is not None:
            self.mirror.mark(ws)
        if self.native is not None:
            self.native.on_replica(ts, ws, True)
        if self.durability is not None:
            self.durability.mark_replica(ts, ws)

    def remove_replica(self, ts: TaskState, ws: WorkerState) -> None:
        ws.nbytes -= ts.get_nbytes()
        del ws.has_what[ts]
        ts.who_has.discard(ws)
        if len(ts.who_has) == 1:
            self.replicated_tasks.discard(ts)
        if self.mirror is not None:
            self.mirror.mark(ws)
        if self.native is not None:
            self.native.on_replica(ts, ws, False)
        if self.durability is not None:
            self.durability.mark_replica(ts, ws)

    def remove_all_replicas(self, ts: TaskState) -> None:
        nbytes = ts.get_nbytes()
        mirror = self.mirror
        if self.native is not None:
            self.native.mark_task(ts)
        for ws in ts.who_has:
            ws.nbytes -= nbytes
            del ws.has_what[ts]
            if mirror is not None:
                mirror.mark(ws)
            if self.native is not None:
                self.native.mark_worker(ws)
        if len(ts.who_has) > 1:
            self.replicated_tasks.discard(ts)
        if self.durability is not None:
            self.durability.mark_task(ts)
            for ws in ts.who_has:
                self.durability.mark_worker(ws)
        ts.who_has.clear()

    def update_nbytes(self, ts: TaskState, nbytes: int) -> None:
        old = ts.get_nbytes() if ts.nbytes >= 0 else 0
        diff = nbytes - old
        if ts.group is not None:
            ts.group.nbytes_total += diff
        if ts.prefix is not None:
            ts.prefix.nbytes_total += diff
        mirror = self.mirror
        native = self.native
        if native is not None:
            # incremental: the SoA applies the same holder-nbytes diffs
            native.on_nbytes(ts, nbytes)
        for ws in ts.who_has:
            ws.nbytes += diff
            if mirror is not None:
                mirror.mark(ws)
        ts.nbytes = nbytes
        if self.durability is not None:
            self.durability.mark_task(ts)

    # ------------------------------------------------------- events

    def log_event(self, topic: str | Iterable[str], msg: Any) -> None:
        """Ring-buffered structured events (reference scheduler.py:8244).

        Every call — internal state-machine events included — also reaches
        live topic subscribers via ``events_subscriber_hook`` (set by the
        Scheduler server)."""
        if isinstance(topic, str):
            topic = [topic]
        topic = list(topic)
        stamp = self.clock()
        for t in topic:
            self.events[t].append((stamp, msg))
            self.event_counts[t] += 1
        if self.events_subscriber_hook is not None:
            try:
                self.events_subscriber_hook(topic, msg)
            except Exception:
                logger.exception("event subscriber hook failed")

    # ----------------------------------------------------- stimuli (pure)

    def stimulus_task_finished(
        self, key: Key, worker: str, stimulus_id: str, **kwargs: Any
    ) -> tuple[dict, dict]:
        """A worker reported a finished task (reference scheduler.py:5025)."""
        if self.trace.journal_enabled:
            self.trace.record(
                "task-finished",
                {"key": key, "worker": worker, "kwargs": dict(kwargs)},
                stimulus_id,
            )
        ts = self.tasks.get(key)
        if ts is None or ts.state in ("released", "forgotten", "erred"):
            # stale completion for a cancelled task: tell worker to drop it
            wmsg = {
                "op": "free-keys",
                "keys": [key],
                "stimulus_id": stimulus_id,
            }
            return {}, {worker: [wmsg]}
        if ts.state == "memory":
            ws = self.workers.get(worker)
            if ws is not None and ws not in ts.who_has:
                self.add_replica(ts, ws)
            return {}, {}
        if ts.state != "processing":
            return {}, {}
        ts.metadata = kwargs.pop("metadata", None) or ts.metadata
        recs, cmsgs, wmsgs = self._transition(
            key, "memory", stimulus_id, worker=worker, **kwargs
        )
        client_msgs: dict = dict(cmsgs)
        worker_msgs: dict = dict(wmsgs)
        self._transitions(recs, client_msgs, worker_msgs, stimulus_id)
        recs2 = self.stimulus_queue_slots_maybe_opened(stimulus_id)
        self._transitions(recs2, client_msgs, worker_msgs, stimulus_id)
        return client_msgs, worker_msgs

    def stimulus_task_erred(
        self,
        key: Key,
        worker: str,
        stimulus_id: str,
        *,
        exception: Any = None,
        traceback: Any = None,
        exception_text: str = "",
        traceback_text: str = "",
        **kwargs: Any,
    ) -> tuple[dict, dict]:
        """A worker reported a task failure (reference scheduler.py:5106)."""
        if self.trace.journal_enabled:
            self.trace.record(
                "task-erred",
                {
                    "key": key,
                    "worker": worker,
                    "kwargs": {
                        "exception": exception,
                        "traceback": traceback,
                        "exception_text": exception_text,
                        "traceback_text": traceback_text,
                        **kwargs,
                    },
                },
                stimulus_id,
            )
        ts = self.tasks.get(key)
        if ts is None or ts.state != "processing":
            return {}, {}
        if ts.processing_on is None or ts.processing_on.address != worker:
            return {}, {}
        recs = {}
        client_msgs: dict = {}
        worker_msgs: dict = {}
        r, c, w = self._transition(
            key,
            "erred",
            stimulus_id,
            cause=key,
            exception=exception,
            traceback=traceback,
            exception_text=exception_text,
            traceback_text=traceback_text,
            worker=worker,
            **kwargs,
        )
        _merge_msgs_inplace(client_msgs, c)
        _merge_msgs_inplace(worker_msgs, w)
        self._transitions(r, client_msgs, worker_msgs, stimulus_id)
        recs2 = self.stimulus_queue_slots_maybe_opened(stimulus_id)
        self._transitions(recs2, client_msgs, worker_msgs, stimulus_id)
        return client_msgs, worker_msgs

    # ------------------------------------------- batched stimulus engine
    #
    # A batched-stream payload frequently carries a same-op FLOOD: a
    # worker reporting dozens of finished tasks, an AMM round releasing
    # replicas everywhere, a client graph submission.  The per-stimulus
    # entries above process one message per call — handler dispatch,
    # fresh message dicts, a queue-slots pass and a send_all flush per
    # message.  The ``*_batch`` entries fold a whole flood into one
    # engine pass: every event still drains through the SAME per-key
    # ``_transition`` handlers in the same order with its own
    # stimulus_id (so task states, ``transition_log``/``story`` entries
    # and message multisets are bit-identical to N sequential calls —
    # the per-key path remains the oracle, and
    # tests/test_batched_engine.py replays random traces through both),
    # but recommendations drain into ONE shared (client_msgs,
    # worker_msgs) pair, the ready frontier of each drain is placed
    # against the live occupancy without per-message re-entry, and the
    # queue-slots pass runs only when the queue is non-empty (when it is
    # empty the per-key pass is a no-op, so skipping it is exact).  The
    # caller flushes the merged messages once per payload; the server
    # additionally coalesces per-destination runs (compute-task batches,
    # merged free-keys) on the wire.

    def transitions_batch(
        self,
        batches: Iterable[tuple[dict[Key, str], str]],
    ) -> tuple[dict, dict]:
        """Drain several recommendation rounds into one shared message
        pair.  Each ``(recommendations, stimulus_id)`` round is processed
        to its fixed point before the next starts — identical semantics
        to calling :meth:`transitions` per round, without the per-round
        dict churn and per-round send."""
        client_msgs: dict = {}
        worker_msgs: dict = {}
        tr = self.trace
        for recommendations, stimulus_id in batches:
            if tr.journal_enabled:
                tr.record(
                    "transitions", {"recs": dict(recommendations)},
                    stimulus_id,
                )
            t0 = self.clock()
            # fault isolation matches the per-message path (one logged
            # failure per message, the rest of the payload proceeds):
            # a poison round must not discard the messages of rounds
            # already applied to state
            self.wall.push("engine.drain", stimulus_id)
            try:
                self._drain_round(
                    recommendations, client_msgs, worker_msgs, stimulus_id
                )
            except Exception:
                logger.exception(
                    "batched transition round failed (stimulus %s)",
                    stimulus_id,
                )
            finally:
                self.wall.pop()
            n = len(recommendations)
            self.hist_engine_batch.observe(n)
            self.hist_engine_pass.observe(self.clock() - t0)
            tr.emit("engine", "transitions", stimulus_id, n=n)
        return client_msgs, worker_msgs

    def stimulus_tasks_finished_batch(
        self,
        finishes: Iterable[tuple[Key, str, str, dict]],
    ) -> tuple[dict, dict]:
        """Batched :meth:`stimulus_task_finished`: one engine pass over a
        flood of ``(key, worker, stimulus_id, kwargs)`` completions.

        Events are processed in arrival order; each event's ready
        frontier drains to a fixed point (placing newly-ready dependents
        against the occupancy the sequential engine would see) before
        the next event is applied, so the result is bit-identical to N
        per-key calls — including per-key ``story`` entries, which keep
        their own per-event stimulus_id for causal tracing.
        """
        if not isinstance(finishes, (list, tuple)):
            finishes = list(finishes)
        ne = self.native
        if ne is not None and ne.active():
            # the native drain owns the whole flood: same journal
            # records, wall phases, histogram/trace observations, and
            # bit-identical outputs (per-key oracle escapes included).
            # None = flood below the amortization floor (min-flood):
            # fall through to the oracle below.
            out = ne.drive_finished_flood(finishes)
            if out is not None:
                return out
        client_msgs = {}
        worker_msgs = {}
        tr = self.trace
        t0 = self.clock()
        if tr.journal_enabled and finishes:
            # ONE record per flood, not per event: the flood is the
            # stimulus unit the engine consumes, and per-event records
            # cost more than the engine's own per-event work on the
            # steady-state path durability capture must stay under
            # (kwargs copied now — the loop below pops "metadata")
            tr.record(
                "tasks-finished-batch",
                {"finishes": [
                    [key, worker, sid, dict(kwargs)]
                    for key, worker, sid, kwargs in finishes
                ]},
                finishes[0][2],
            )
        self.wall.push("engine.drain", finishes[0][2] if finishes else "")
        try:
            for key, worker, stimulus_id, kwargs in finishes:
                # per-event fault isolation, same as the per-message path
                # (handle_stream logs one failure and proceeds): a poison
                # event must not discard the flood's already-accumulated
                # messages — transitions behind them are already applied
                try:
                    ts = self.tasks.get(key)
                    if ts is None or ts.state in ("released", "forgotten", "erred"):
                        # stale completion for a cancelled task: tell worker
                        # to drop it (merged per destination at flush time)
                        worker_msgs.setdefault(worker, []).append(
                            {
                                "op": "free-keys",
                                "keys": [key],
                                "stimulus_id": stimulus_id,
                            }
                        )
                        continue
                    if ts.state == "memory":
                        ws = self.workers.get(worker)
                        if ws is not None and ws not in ts.who_has:
                            self.add_replica(ts, ws)
                        continue
                    if ts.state != "processing":
                        continue
                    ts.metadata = kwargs.pop("metadata", None) or ts.metadata
                    recs, cmsgs, wmsgs = self._transition(
                        key, "memory", stimulus_id, worker=worker, **kwargs
                    )
                    _merge_msgs_inplace(client_msgs, cmsgs)
                    _merge_msgs_inplace(worker_msgs, wmsgs)
                    self._transitions(recs, client_msgs, worker_msgs, stimulus_id)
                    if self.queued:
                        # the per-key engine runs this pass per event; it is
                        # a no-op on an empty queue, so gating on ``queued``
                        # folds the common case without changing any outcome
                        recs2 = self.stimulus_queue_slots_maybe_opened(stimulus_id)
                        self._transitions(
                            recs2, client_msgs, worker_msgs, stimulus_id
                        )
                except Exception:
                    logger.exception(
                        "batched task-finished event failed (%s from %s, "
                        "stimulus %s)", key, worker, stimulus_id,
                    )
        finally:
            self.wall.pop()
        if finishes:
            self.hist_engine_batch.observe(len(finishes))
            self.hist_engine_pass.observe(self.clock() - t0)
            tr.emit(
                "engine", "task-finished-batch", finishes[0][2],
                n=len(finishes),
            )
        return client_msgs, worker_msgs

    def stimulus_tasks_erred_batch(
        self,
        errors: Iterable[tuple[Key, str, str, dict]],
    ) -> tuple[dict, dict]:
        """Batched :meth:`stimulus_task_erred` over ``(key, worker,
        stimulus_id, kwargs)`` failure reports; same bit-parity contract
        as :meth:`stimulus_tasks_finished_batch`."""
        client_msgs: dict = {}
        worker_msgs: dict = {}
        if not isinstance(errors, (list, tuple)):
            errors = list(errors)
        tr = self.trace
        t0 = self.clock()
        self.wall.push("engine.drain", errors[0][2] if errors else "")
        try:
            for key, worker, stimulus_id, kwargs in errors:
                if tr.journal_enabled:
                    tr.record(
                        "task-erred",
                        {"key": key, "worker": worker, "kwargs": dict(kwargs)},
                        stimulus_id,
                    )
                try:
                    ts = self.tasks.get(key)
                    if ts is None or ts.state != "processing":
                        continue
                    if ts.processing_on is None or ts.processing_on.address != worker:
                        continue
                    recs, cmsgs, wmsgs = self._transition(
                        key,
                        "erred",
                        stimulus_id,
                        cause=key,
                        worker=worker,
                        **kwargs,
                    )
                    _merge_msgs_inplace(client_msgs, cmsgs)
                    _merge_msgs_inplace(worker_msgs, wmsgs)
                    self._transitions(recs, client_msgs, worker_msgs, stimulus_id)
                    if self.queued:
                        recs2 = self.stimulus_queue_slots_maybe_opened(stimulus_id)
                        self._transitions(
                            recs2, client_msgs, worker_msgs, stimulus_id
                        )
                except Exception:
                    logger.exception(
                        "batched task-erred event failed (%s from %s, "
                        "stimulus %s)", key, worker, stimulus_id,
                    )
        finally:
            self.wall.pop()
        if errors:
            self.hist_engine_batch.observe(len(errors))
            self.hist_engine_pass.observe(self.clock() - t0)
            tr.emit(
                "engine", "task-erred-batch", errors[0][2], n=len(errors)
            )
        return client_msgs, worker_msgs

    def stimulus_release_worker_data(
        self, key: Key, worker: str, stimulus_id: str
    ) -> dict[Key, str]:
        """A worker no longer holds a replica (pure part of the
        ``release-worker-data`` handlers): drop the replica record and
        recommend ``released`` when it was the last one.

        Journaled as its own op: the replica removal is a state mutation
        OUTSIDE the transition engine, so a capture that only recorded
        the engine rounds would replay it un-removed and diverge.  The
        returned recommendations are fed through ``transitions`` /
        ``transitions_batch`` by the caller, which journals that round
        separately — replay applies this op's removal only and lets the
        following ``transitions`` record drive the engine."""
        if self.trace.journal_enabled:
            self.trace.record(
                "release-worker-data",
                {"key": key, "worker": worker},
                stimulus_id,
            )
        # an AMM drop decision for this (key, worker) realizes here
        # (join_amm is a dict-emptiness check when no AMM rows pend)
        self.ledger.join_amm(key, worker, "dropped")
        ts = self.tasks.get(key)
        ws = self.workers.get(worker)
        if ts is None or ws is None:
            return {}
        if ws in ts.who_has:
            self.remove_replica(ts, ws)
        if not ts.who_has:
            return {key: "released"}
        return {}

    def stimulus_retry(self, keys: Iterable[Key], stimulus_id: str) -> tuple[dict, dict]:
        """Re-run erred tasks (reference scheduler.py:5131)."""
        roots: OrderedSet[Key] = OrderedSet()
        for key in keys:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            # walk up the blame chain to the root cause
            seen: set[Key] = set()
            while ts.exception_blame is not None and ts.exception_blame is not ts:
                if ts.key in seen:
                    break
                seen.add(ts.key)
                ts = ts.exception_blame
            if ts.state == "erred":
                roots.add(ts.key)
        # "waiting" routes erred -> released -> waiting (reference :5131)
        return self.transitions({k: "waiting" for k in roots}, stimulus_id)

    # ------------------------------------- worker stream stimuli (pure)
    #
    # Pure bodies of the scheduler server's scalar worker-op handlers
    # (add-keys / long-running / reschedule / missing-data /
    # request-refresh-who-has).  The networked Scheduler wraps each in a
    # thin trace-ingress + send_all shell; the sans-io cluster simulator
    # (distributed_tpu/sim) calls them directly, so both planes run ONE
    # implementation instead of drifting copies.

    def stimulus_add_keys(
        self, keys: Iterable[Key], worker: str, stimulus_id: str
    ) -> tuple[dict, dict]:
        """Worker acquired replicas out-of-band (reference scheduler.py:5855).

        Journaled: replica registration mutates ``who_has`` OUTSIDE the
        transition engine, and placement decisions read it — a journal
        without add-keys replays a dependency graph with drifting
        placements (found by the simulator's record/replay parity
        test; the dep-free bench flood never exercised it)."""
        keys = list(keys)
        if self.trace.journal_enabled:
            self.trace.record(
                "add-keys", {"keys": keys, "worker": worker}, stimulus_id
            )
        ws = self.workers.get(worker)
        if ws is None:
            return {}, {}
        redundant = []
        for key in keys:
            ts = self.tasks.get(key)
            if ts is not None and ts.state == "memory":
                self.add_replica(ts, ws)
                # an AMM replicate decision for this (key, worker)
                # realizes here: acquire -> gather -> add-keys
                self.ledger.join_amm(
                    key, worker, "replicated", telemetry=self.telemetry,
                )
            else:
                redundant.append(key)
        if redundant:
            return {}, {worker: [{
                "op": "remove-replicas", "keys": redundant,
                "stimulus_id": stimulus_id,
            }]}
        return {}, {}

    def stimulus_scatter_data(
        self, key: Key, holders: list[str], nbytes: int,
        client: str | None, stimulus_id: str,
    ) -> tuple[dict, dict]:
        """Pure data landed on workers out-of-band (the pure per-key part
        of ``Scheduler.scatter``; the sim's scatter drives it directly).

        Journaled: scattered data enters ``memory`` through the engine
        but from no worker stimulus, so a journal tail without these
        records replays a cluster whose root partitions never existed."""
        holders = [a for a in holders if a in self.workers]
        if not holders:
            return {}, {}
        if self.trace.journal_enabled:
            self.trace.record(
                "scatter-data",
                {"key": key, "workers": list(holders), "nbytes": int(nbytes),
                 "client": client},
                stimulus_id,
            )
        ts = self.tasks.get(key)
        if ts is None:
            ts = self.new_task(key, None, "released")
        if client is not None:
            # register the client's interest BEFORE entering memory via
            # the engine, or the no-waiters/no-wants GC releases the key
            self.client_desires_keys([key], client)
        if ts.state not in ("released", "memory"):
            # key collides with a task mid-flight: leave the scheduler
            # state machine alone (the worker copy is surplus data)
            logger.warning(
                "scatter ignoring key %r already in state %r", key, ts.state
            )
            return {}, {}
        if ts.priority is None:
            ts.priority = (0, 0, 0)
        client_msgs: dict = {}
        worker_msgs: dict = {}
        if ts.state == "released":
            # through the engine so accounting stays consistent and
            # waiting dependents are recommended onward
            recs, cmsgs, wmsgs = self._transition(
                key, "memory", stimulus_id,
                worker=holders[0], nbytes=int(nbytes),
            )
            _merge_msgs_inplace(client_msgs, cmsgs)
            _merge_msgs_inplace(worker_msgs, wmsgs)
            self._transitions(recs, client_msgs, worker_msgs, stimulus_id)
            extra = holders[1:]
        else:
            self.update_nbytes(ts, int(nbytes))
            extra = holders
        for addr in extra:
            ws = self.workers.get(addr)
            if ws is not None:
                self.add_replica(ts, ws)
        return client_msgs, worker_msgs

    def stimulus_long_running(
        self, key: Key, worker: str, compute_duration: float,
        stimulus_id: str,
    ) -> tuple[dict, dict]:
        """Task seceded from its thread slot (reference scheduler.py:5906)."""
        if self.trace.journal_enabled:
            self.trace.record(
                "long-running",
                {"key": key, "worker": worker,
                 "compute_duration": compute_duration},
                stimulus_id,
            )
        ts = self.tasks.get(key)
        if ts is None or ts.processing_on is None:
            return {}, {}
        ws = ts.processing_on
        if ws.address != worker:
            return {}, {}
        occ = ws.processing.get(ts)
        if occ is not None:
            self._adjust_occupancy(ws, -occ)
            # graft-lint: allow[mirror-parity] row marked by the _adjust_occupancy above and the check_idle_saturated below
            ws.processing[ts] = 0.0
        ws.long_running.add(ts)
        if self.native is not None:
            self.native.mark_task(ts)
        if self.durability is not None:
            self.durability.mark_replica(ts, ws)
        self.check_idle_saturated(ws)
        return {}, {}

    def stimulus_steal_move(
        self, key: Key, victim: str, thief: str, stimulus_id: str,
        kind: str = "steal",
    ) -> tuple[dict, dict]:
        """Re-place a processing task from ``victim`` onto ``thief`` —
        the resolved outcome of a steal confirm (or a speculative move).

        Extracted from ``WorkStealing.move_task_confirm`` so the move is
        journaled as its own replayable op: the confirm path mutates
        ``processing_on`` OUTSIDE the transition engine, and a journal
        tail spanning a confirmed steal would otherwise reconstruct the
        task on the wrong worker (the restart-during-in-flight-steal
        case).  Guards mirror the confirm path; a guard miss is a no-op
        both live and on replay."""
        ts = self.tasks.get(key)
        if ts is None or ts.state != "processing":
            return {}, {}
        victim_ws = self.workers.get(victim)
        thief_ws = self.workers.get(thief)
        if victim_ws is None or ts.processing_on is not victim_ws:
            return {}, {}
        if self.trace.journal_enabled:
            self.trace.record(
                "steal-move",
                {"key": key, "victim": victim, "thief": thief, "kind": kind},
                stimulus_id,
            )
        if thief_ws is None or thief_ws not in self.running:
            # thief died meanwhile: reschedule from scratch
            return self._transitions_observed({key: "released"}, stimulus_id)
        self._exit_processing_common(ts)
        ts.state = "waiting"  # transient; re-enter processing on thief
        victim_ws.long_running.discard(ts)
        worker_msgs = self._add_to_processing(
            ts, thief_ws, stimulus_id, kind=kind
        )
        return {}, worker_msgs

    def stimulus_reschedule(
        self, key: Key, worker: str, stimulus_id: str
    ) -> tuple[dict, dict]:
        """Worker bounced the task back for re-placement (Reschedule)."""
        if self.trace.journal_enabled:
            self.trace.record(
                "reschedule", {"key": key, "worker": worker}, stimulus_id
            )
        ts = self.tasks.get(key)
        if ts is None or ts.processing_on is None:
            return {}, {}
        if ts.processing_on.address != worker:
            return {}, {}
        # _transitions_observed, NOT transitions: this stimulus already
        # journaled itself, and replay re-derives the round from it — a
        # nested "transitions" record would run the round twice
        return self._transitions_observed({key: "released"}, stimulus_id)

    def stimulus_missing_data(
        self, key: Key, errant_worker: str, stimulus_id: str
    ) -> tuple[dict, dict]:
        """A peer did not have data it was supposed to (reference :5869)."""
        if self.trace.journal_enabled:
            self.trace.record(
                "missing-data",
                {"key": key, "errant_worker": errant_worker}, stimulus_id,
            )
        ts = self.tasks.get(key)
        ws = self.workers.get(errant_worker)
        if ts is None:
            return {}, {}
        worker_msgs: dict = {}
        if ws is not None and ws in ts.who_has:
            self.remove_replica(ts, ws)
            # the replica model is authoritative: once this copy is
            # written off, tell the errant worker to drop it too.  If
            # the report was right this is a no-op; if the serve merely
            # FAILED (a partition) the holder would otherwise keep a
            # replica the scheduler no longer tracks — free-keys at
            # forget only reaches who_has members, so the orphan
            # outlives the task forever (census-found: partition chaos
            # left scheduler-untracked memory keys on healed workers)
            worker_msgs[errant_worker] = [{
                "op": "remove-replicas", "keys": [key],
                "stimulus_id": stimulus_id,
            }]
        if not ts.who_has:
            # see stimulus_reschedule: self-journaled, so the round must
            # not journal again
            cm, wm = self._transitions_observed({key: "released"}, stimulus_id)
            return cm, _merge_msgs(worker_msgs, wm)
        return {}, worker_msgs

    def stimulus_request_refresh_who_has(
        self, keys: Iterable[Key], worker: str, stimulus_id: str
    ) -> tuple[dict, dict]:
        """A worker wants fresh replica locations for its missing tasks."""
        who_has = {}
        for key in keys:
            ts = self.tasks.get(key)
            who_has[key] = (
                [ws.address for ws in ts.who_has] if ts is not None else []
            )
        return {}, {worker: [{
            "op": "refresh-who-has", "who_has": who_has,
            "stimulus_id": stimulus_id,
        }]}

    # ------------------------------------------------ worker lifecycle

    def add_worker_state(
        self,
        address: str,
        *,
        nthreads: int = 1,
        memory_limit: int = 0,
        name: object = None,
        resources: dict[str, float] | None = None,
        server_id: str | None = None,
    ) -> WorkerState:
        """Register a worker (pure part of reference add_worker :4308)."""
        if address in self.workers:
            return self.workers[address]
        if self.trace.journal_enabled:
            # worker registration is structural state the engine stimuli
            # assume: a journal tail spanning an autoscale join must
            # replay it or every later placement references a ghost
            self.trace.record(
                "add-worker",
                {"address": address, "nthreads": int(nthreads),
                 "memory_limit": int(memory_limit),
                 "name": name if isinstance(name, (str, int, float, type(None))) else str(name),
                 "resources": dict(resources or {}),
                 "server_id": server_id},
                f"add-worker-{address}",
            )
        ws = WorkerState(
            address, nthreads=nthreads, memory_limit=memory_limit, name=name,
            server_id=server_id,
        )
        # keep the engine's clock domain: WorkerState's constructor
        # stamps the module clock, but inside this engine every
        # timestamp reads the injected clock (virtual in the simulator;
        # the live server overwrites last_seen on each heartbeat)
        ws.last_seen = self.clock()
        if resources:
            ws.resources.update(resources)
            ws.used_resources = dict.fromkeys(resources, 0)
            for r, q in resources.items():
                self.resources[r][address] = q
        self.workers[address] = ws
        self.aliases[ws.name] = address
        self.running.add(ws)
        self.total_nthreads += nthreads
        self.total_nthreads_history.append((self.clock(), self.total_nthreads))
        if self.mirror is not None:
            self.mirror.on_add_worker(ws)
        if self.native is not None:
            self.native.on_add_worker(ws)
        if self.durability is not None:
            self.durability.mark_worker(ws)
        self.check_idle_saturated(ws)
        if self.placement is not None:
            self.placement.on_add_worker(self, ws)
        return ws

    def set_worker_status(
        self, ws: WorkerState, status: str, status_seq: int | None = None
    ) -> None:
        """Mirror-aware status mutation (running/idle membership updates
        stay at the callers — server.handle_worker_status_change owns
        the transition side effects)."""
        ws.status = status
        if status_seq is not None:
            ws.status_seq = status_seq
        if self.mirror is not None:
            self.mirror.mark(ws)
        if self.native is not None:
            self.native.mark_worker(ws)
        if self.durability is not None:
            self.durability.mark_worker(ws)

    def set_worker_nthreads(self, ws: WorkerState, nthreads: int) -> None:
        """Mirror-aware worker resize.  No production message resizes a
        live worker yet (reconnect is remove+add); this is the designated
        funnel for when one does, and the churn property tests drive it
        so the mirror's resize delta path stays proven."""
        self.total_nthreads += nthreads - ws.nthreads
        ws.nthreads = nthreads
        if self.native is not None:
            self.native.mark_worker(ws)
        if self.durability is not None:
            self.durability.mark_worker(ws)
        self.total_nthreads_history.append((self.clock(), self.total_nthreads))
        self.check_idle_saturated(ws)

    def stimulus_worker_status_change(
        self, worker: str, status: str, status_seq: int,
        stimulus_id: str,
    ) -> tuple[dict, dict]:
        """Pure body of the server's worker-status-change handler: the
        running/idle membership flips, homed-task release and parked
        splicing happen OUTSIDE the engine, so the op journals itself
        and the engine rounds it triggers replay from this record."""
        ws = self.workers.get(worker)
        if ws is None:
            return {}, {}
        if status_seq >= 0 and status_seq < ws.status_seq:
            # stale stream message ordered behind a fresher flip
            # (possible after a heartbeat-applied reconciliation)
            return {}, {}
        if self.trace.journal_enabled:
            self.trace.record(
                "worker-status-change",
                {"worker": worker, "status": status,
                 "status_seq": int(status_seq)},
                stimulus_id,
            )
        self.set_worker_status(
            ws, status, status_seq if status_seq >= 0 else None
        )
        ws.status_changed_at = self.clock()
        if status == WORKER_STATUS_PAUSED:
            self.running.discard(ws)
            self.idle.pop(ws.address, None)
            self.idle_task_count.discard(ws)
            # home-stacked tasks on a paused worker become stealable
            # again — nothing else would move them off a stalled home
            steal = self.extensions.get("stealing")
            for ts in ws.processing:
                if ts.homed:
                    ts.homed = False
                    if steal is not None:
                        steal.put_key_in_stealable(ts)
            # a paused home can't pull: return its parked tasks to the
            # global pop heap and let open slots elsewhere take them
            if ws.address in self.parked:
                self.splice_parked(ws.address)
                recs = self.stimulus_queue_slots_maybe_opened(stimulus_id)
                return self._transitions_observed(recs, stimulus_id)
        elif status == WORKER_STATUS_RUNNING:
            self.running.add(ws)
            self.check_idle_saturated(ws)
            recs = self.bulk_schedule_unrunnable_after_adding_worker(ws)
            recs.update(self.stimulus_queue_slots_maybe_opened(stimulus_id))
            return self._transitions_observed(recs, stimulus_id)
        return {}, {}

    def bulk_schedule_unrunnable_after_adding_worker(self, ws: WorkerState) -> dict[Key, str]:
        """Try no-worker tasks on the new worker (reference scheduler.py:3173)."""
        runnable = [
            ts
            for ts in self.unrunnable
            if (valid := self.valid_workers(ts)) is None or ws in valid
        ]
        runnable.sort(key=lambda ts: (ts.priority, ts.key), reverse=True)
        return {ts.key: "processing" for ts in runnable}

    def remove_worker_state(
        self,
        address: str,
        *,
        stimulus_id: str,
        safe: bool = False,
        expected: bool = False,
    ) -> tuple[dict, dict]:
        """Unregister a worker, rescheduling its work and releasing its
        replicas (pure part of reference remove_worker :5180).

        Returns (client_msgs, worker_msgs) after draining all resulting
        transitions.  Lineage recomputation happens here: tasks whose only
        replica lived on the dead worker are recommended back through
        released -> waiting and will be recomputed from run_spec.
        """
        ws = self.workers.get(address)
        if ws is None:
            return {}, {}
        if self.trace.journal_enabled:
            # worker removal rewrites replica truth and reschedules its
            # processing set — a chaos capture replays it as its own op
            self.trace.record(
                "remove-worker", {"worker": address, "safe": bool(safe)},
                stimulus_id,
            )
        del self.workers[address]
        self.aliases.pop(ws.name, None)
        self.telemetry.forget_worker(address)
        # finalize open ledger rows pointing at the departed worker (the
        # PR 7 link-leak lesson): their joins can never come, and the
        # released cascade below must not mis-join them as cancellations
        self.ledger.resolve_worker(address, now=self.clock())
        ws.status = WORKER_STATUS_CLOSED
        self.running.discard(ws)
        self.idle.pop(ws.address, None)
        self.idle_task_count.discard(ws)
        self.saturated.discard(ws)
        self.total_nthreads -= ws.nthreads
        self.total_nthreads_history.append((self.clock(), self.total_nthreads))
        self._total_occupancy -= ws.occupancy
        ws.occupancy = 0.0
        for r in ws.resources:
            self.resources[r].pop(address, None)
        if self.mirror is not None:
            self.mirror.on_remove_worker(ws)
        if self.native is not None:
            self.native.on_remove_worker(ws)
        if self.durability is not None:
            self.durability.on_remove_worker(ws)
        if self.placement is not None:
            self.placement.on_remove_worker(self, ws)
        # tasks parked for the dead worker become globally poppable again
        self.splice_parked(address)
        # drop group co-assignment cursors pointing at the dead worker:
        # decide_worker re-validates membership before using one, so
        # this is behavior-neutral — but the stale reference pinned the
        # whole removed WorkerState object per group (census-found;
        # removals are rare, O(groups) is fine here)
        for tg in self.task_groups.values():
            if tg.last_worker is ws:
                tg.last_worker = None
                tg.last_worker_tasks_left = 0

        recommendations: dict[Key, str] = {}
        client_msgs: dict = {}
        worker_msgs: dict = {}

        for ts in list(ws.processing):
            k = ts.key
            recommendations[k] = "released"
            if not safe:
                ts.suspicious += 1
                ts.erred_on.add(address)
                if ts.suspicious > self.ALLOWED_FAILURES:
                    del recommendations[k]
                    e = KilledWorker(
                        task=k, last_worker=address, allowed_failures=self.ALLOWED_FAILURES
                    )
                    r, c, w = self._transition(
                        k,
                        "erred",
                        stimulus_id,
                        exception=e,
                        cause=k,
                        exception_text=str(e),
                        worker=address,
                    )
                    recommendations.update(r)
                    _merge_msgs_inplace(client_msgs, c)
                    _merge_msgs_inplace(worker_msgs, w)
                    self.log_event(
                        "all",
                        {"action": "killed-worker", "key": k, "worker": address},
                    )

        for ts in list(ws.has_what):
            self.remove_replica(ts, ws)
            if not ts.who_has:
                if ts.run_spec:
                    recommendations[ts.key] = "released"
                else:  # pure data, lost for good
                    recommendations[ts.key] = "forgotten"

        self._transitions(recommendations, client_msgs, worker_msgs, stimulus_id)
        # the departed worker must not receive queued messages
        worker_msgs.pop(address, None)
        recs2 = self.stimulus_queue_slots_maybe_opened(stimulus_id)
        self._transitions(recs2, client_msgs, worker_msgs, stimulus_id)
        return client_msgs, worker_msgs

    # ------------------------------------------------ client lifecycle

    def add_client_state(self, client: str) -> ClientState:
        cs = self.clients.get(client)
        if cs is None:
            cs = self.clients[client] = ClientState(client, self.clock())
        return cs

    def client_desires_keys(self, keys: Iterable[Key], client: str) -> None:
        keys = list(keys)
        if self.trace.journal_enabled:
            # client interest gates the release/forget GC: a tail
            # replayed without it forgets keys the client still holds
            self.trace.record(
                "client-desires-keys", {"keys": keys, "client": client},
                f"client-desires-{client}",
            )
        cs = self.add_client_state(client)
        for key in keys:
            ts = self.tasks.get(key)
            if ts is None:
                ts = self.new_task(key, None, "released")
            ts.who_wants.add(cs)
            cs.wants_what.add(ts)
            if self.native is not None:
                self.native.on_who_wants(ts)
            if self.durability is not None:
                self.durability.mark_task(ts)

    def client_releases_keys(
        self, keys: Iterable[Key], client: str, stimulus_id: str
    ) -> tuple[dict, dict]:
        """Client no longer wants these keys (reference scheduler.py:5441)."""
        cs = self.clients.get(client)
        if cs is None:
            return {}, {}
        keys = list(keys)
        if self.trace.journal_enabled:
            # journaled as its own op (the interest mutation happens
            # OUTSIDE the engine); the engine round below is re-derived
            # on replay, so it must NOT write a nested "transitions"
            # record — the reschedule/missing-data rule
            self.trace.record(
                "client-releases-keys", {"keys": keys, "client": client},
                stimulus_id,
            )
        recommendations: dict[Key, str] = {}
        for key in keys:
            ts = self.tasks.get(key)
            if ts is None or ts not in cs.wants_what:
                continue
            cs.wants_what.discard(ts)
            ts.who_wants.discard(cs)
            if self.native is not None:
                self.native.on_who_wants(ts)
            if self.durability is not None:
                self.durability.mark_task(ts)
            if not ts.who_wants:
                if not ts.dependents:
                    recommendations[key] = "forgotten"
                elif not ts.waiters:
                    recommendations[key] = "released"
        return self._transitions_observed(recommendations, stimulus_id)

    def remove_client_state(self, client: str, stimulus_id: str) -> tuple[dict, dict]:
        cs = self.clients.get(client)
        if cs is None:
            return {}, {}
        out = self.client_releases_keys(
            [ts.key for ts in cs.wants_what], client, stimulus_id
        )
        del self.clients[client]
        return out

    # ------------------------------------------------------ graph intake

    def update_graph_core(
        self,
        tasks: dict[Key, Any],
        dependencies: dict[Key, set[Key]],
        keys: Iterable[Key],
        *,
        client: str | None = None,
        priorities: dict[Key, tuple] | None = None,
        user_priority: int | dict[Key, int] = 0,
        generation: int = 0,
        annotations_by_key: dict[Key, dict] | None = None,
        retries: int | dict[Key, int] | None = None,
        actors: bool | list[Key] = False,
        stimulus_id: str = "update-graph",
    ) -> tuple[dict, dict]:
        """Materialize a graph into TaskStates and kick off transitions.

        Pure equivalent of the reference's update_graph -> _generate_taskstates
        -> _set_priorities -> transitions (scheduler.py:4662-4981).
        ``tasks`` maps key -> run_spec (TaskSpec or literal); ``priorities``
        are static ranks from graph.order (computed by the caller, possibly
        offloaded).
        """
        if priorities is None:
            from distributed_tpu.graph.order import order as order_fn

            # deps on keys submitted in earlier graphs are already-known
            # tasks: exclude them from static ordering of this batch
            known = set(dependencies)
            pruned = {
                k: {d for d in deps if d in known}
                for k, deps in dependencies.items()
            }
            priorities = {k: (r,) for k, r in order_fn(pruned).items()}

        if self.trace.journal_enabled:
            # graph intake is journaled with RESOLVED priorities and
            # per-dependency lists in this call's exact iteration order,
            # so a tail replay materializes bit-identical TaskStates
            # (insertion order of the relation sets included) without
            # re-running graph.order.  run_specs are encoded to a
            # JSON-pure form (scheduler/durability.py) so the record's
            # digest survives a dump/load round trip and a restarted
            # scheduler can still dispatch the tasks.  The engine round
            # at the end of this method is re-derived on replay and
            # must not write a nested "transitions" record.
            from distributed_tpu.scheduler.durability import encode_run_spec

            self.trace.record(
                "update-graph",
                {
                    "tasks": {k: encode_run_spec(v) for k, v in tasks.items()},
                    "dependencies": {
                        k: list(v) for k, v in dependencies.items()
                    },
                    "keys": list(keys),
                    "priorities": {
                        k: list(v) for k, v in priorities.items()
                    },
                    "client": client,
                    "user_priority": user_priority,
                    "generation": generation,
                    "annotations_by_key": annotations_by_key,
                    "retries": retries,
                    "actors": actors,
                },
                stimulus_id,
            )

        # reuse a trailing EMPTY computation: dependency-only or
        # already-known-key submissions must not flush real history out
        # of the bounded deque
        if self.computations and not self.computations[-1].groups:
            computation = self.computations[-1]
        else:
            computation = Computation(self.clock())
            self.computations.append(computation)
        touched: list[TaskState] = []
        created: list[TaskState] = []
        for key, spec in tasks.items():
            ts = self.tasks.get(key)
            fresh = False
            if ts is None:
                # run_spec lives as long as the task: compact opaque
                # specs so a ~100-byte Serialized slice doesn't pin the
                # whole pooled receive buffer it arrived in (docs/wire.md)
                ts = self.new_task(key, compact_frames(spec), "released")
                fresh = spec is not None
                created.append(ts)
            elif ts.run_spec is None and spec is not None:
                ts.run_spec = compact_frames(spec)
                fresh = True
            # only NEWLY runnable tasks attribute their group here: a
            # resubmission of known keys must not clone old groups into
            # a fresh Computation (it would both duplicate history and
            # flush the bounded deque)
            if fresh and ts.group is not None:
                computation.groups.add(ts.group)
            touched.append(ts)

        native = self.native
        for key, deps in dependencies.items():
            ts = self.tasks[key]
            for dkey in deps:
                dts = self.tasks.get(dkey)
                if dts is None:
                    dts = self.new_task(dkey, None, "released")
                ts.add_dependency(dts)
                if native is not None:
                    native.mark_task(dts)
            if native is not None:
                native.mark_task(ts)

        for ts in touched:
            key = ts.key
            if ts.priority is None and key in priorities:
                rank = priorities[key]
                upri = (
                    user_priority.get(key, 0)
                    if isinstance(user_priority, dict)
                    else user_priority
                )
                ts.priority = (-upri, generation) + tuple(rank)
            if isinstance(retries, dict):
                ts.retries = retries.get(key, 0)
            elif retries:
                ts.retries = retries
            if annotations_by_key and key in annotations_by_key:
                ts.annotations = dict(annotations_by_key[key])
                ann = ts.annotations
                if "workers" in ann:
                    w = ann["workers"]
                    ts.worker_restrictions = set([w] if isinstance(w, str) else w)
                if "allow_other_workers" in ann:
                    ts.loose_restrictions = bool(ann["allow_other_workers"])
                if "resources" in ann:
                    ts.resource_restrictions = dict(ann["resources"])
                if "retries" in ann:
                    ts.retries = ann["retries"]
                if "priority" in ann and ts.priority is not None:
                    new_pri = (-ann["priority"],) + ts.priority[1:]
                    if new_pri != ts.priority and ts in self.queued:
                        # HeapSet orders by add-time priority: re-add so
                        # the bump is visible to peekn/pop, not stale
                        self.queued.remove(ts)
                        in_global = ts in self.queued_unparked
                        if in_global:
                            self.queued_unparked.remove(ts)
                        pheap = self.parked.get(
                            self._parked_keys.get(ts.key, "")
                        )
                        if pheap is not None and ts in pheap:
                            pheap.remove(ts)
                        else:
                            pheap = None
                        ts.priority = new_pri
                        self.queued.add(ts)
                        if in_global:
                            self.queued_unparked.add(ts)
                        if pheap is not None:
                            pheap.add(ts)
                    else:
                        ts.priority = new_pri
            if (actors is True) or (isinstance(actors, list) and key in actors):
                ts.actor = True
            if native is not None:
                native.mark_task(ts)

        # fill priorities for tasks created only as dependencies
        for ts in self.tasks.values():
            if ts.priority is None:
                ts.priority = (0, generation, 0)

        if client is not None:
            self.client_desires_keys(keys, client)

        if self.placement is not None and hasattr(self.placement, "plan_graph"):
            # one device call plans the whole incoming graph; consumed as
            # per-task hints by decide_worker_non_rootish.  The graph's
            # stimulus id rides along so the kernel dispatch joins the
            # submission in the flight recorder.
            try:
                self.placement.plan_graph(
                    self, {ts.key: ts for ts in touched},
                    stimulus_id=stimulus_id,
                )
            except Exception:
                logger.exception("placement planning failed")

        recommendations: dict[Key, str] = {}
        # seed transitions from the leaves up: released tasks that are
        # wanted (directly or transitively) go to waiting
        wanted: set[TaskState] = set()
        stack = [self.tasks[k] for k in keys if k in self.tasks]
        while stack:
            ts = stack.pop()
            if ts in wanted:
                continue
            wanted.add(ts)
            stack.extend(ts.dependencies)
        # highest priority inserted last: _transitions pops LIFO, so the
        # best-priority task reaches decide_worker first
        for ts in sorted(wanted, key=lambda ts: ts.priority or (0,), reverse=True):
            if ts.state == "released" and ts.run_spec is not None:
                recommendations[ts.key] = "waiting"
        # _transitions_observed, NOT transitions: the update-graph
        # journal record above replays this round itself
        client_msgs, worker_msgs = self._transitions_observed(
            recommendations, stimulus_id
        )
        # cull unreachable junk at ingest: a task CREATED by this batch
        # that no requested key transitively needs, nothing depends on
        # and no client wants would otherwise sit released forever (the
        # reference relies on client-side culling; at millions-of-users
        # scale a buggy client must not grow the scheduler without
        # bound — found by the state census's quiesce gate).  A second
        # engine round, deliberately: released->forgotten is an
        # uncompiled edge, and folding it into the round above would
        # bounce the WHOLE wanted-set drain off the native engine.
        cull: dict[Key, str] = {}
        for ts in created:
            if (
                ts not in wanted
                and ts.state == "released"
                and not ts.dependents
                and not ts.who_wants
                and not ts.waiters
            ):
                cull[ts.key] = "forgotten"
        if cull:
            cm2, wm2 = self._transitions_observed(cull, stimulus_id)
            client_msgs = _merge_msgs(client_msgs, cm2)
            worker_msgs = _merge_msgs(worker_msgs, wm2)
        # immediately report already-completed keys
        for key in keys:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            if ts.state == "memory":
                for cs in ts.who_wants:
                    client_msgs.setdefault(cs.client_key, []).append(
                        {"op": "key-in-memory", "key": key, "type": ts.type}
                    )
            elif ts.state == "erred":
                for cs in ts.who_wants:
                    client_msgs.setdefault(cs.client_key, []).append(
                        {
                            "op": "task-erred",
                            "key": key,
                            "exception": ts.exception,
                            "traceback": ts.traceback,
                        }
                    )
        return client_msgs, worker_msgs

    # -------------------------------------------------------- validation

    def validate_task_state(self, ts: TaskState) -> None:
        """Invariant check for one task (reference scheduler.py:8596)."""
        try:
            assert ts.state in ALL_TASK_STATES or ts.state == "forgotten", ts

            for dts in ts.waiting_on:
                # replica truth: a dep mid-recompute may be state "memory"
                # transiently, but a task only waits on deps with no
                # stored replica (reference validate_waiting:
                # bool(who_has) != (dts in waiting_on))
                assert not dts.who_has, (ts, dts)
                assert ts in dts.waiters, (ts, dts)
            for dts in ts.dependencies:
                assert ts in dts.dependents, (ts, dts)
                # the real data-safety invariant, checked from the
                # dependent side (reference validate_task_state "dep
                # missing"): an in-play task either still waits on the
                # dep or the dep has a live replica
                if ts.state in ("waiting", "queued", "processing", "no-worker"):
                    assert dts in ts.waiting_on or dts.who_has, (
                        "dep missing", ts, dts,
                    )
            for dts in ts.waiters:
                # waiters = dependents not yet finished (reference
                # scheduler.py:2110): they may be processing against a
                # dep that is memory now — or released mid-cascade, in
                # which case the release has already recommended them
                # back to waiting
                assert dts.state in ("waiting", "queued", "processing", "no-worker"), (
                    ts,
                    dts,
                    dts.state,
                )

            if ts.state == "waiting":
                assert not ts.who_has, ts
                assert not ts.processing_on, ts
            elif ts.state == "queued":
                assert ts in self.queued, ts
                assert not ts.processing_on, ts
                assert not ts.who_has, ts
            elif ts.state == "processing":
                assert ts.processing_on, ts
                assert ts in ts.processing_on.processing, ts
                assert not ts.waiting_on, ts
                assert not ts.who_has, ts
            elif ts.state == "memory":
                assert ts.who_has, ts
                assert not ts.processing_on, ts
                assert not ts.waiting_on, ts
                for ws in ts.who_has:
                    assert ts in ws.has_what, (ts, ws)
            elif ts.state == "no-worker":
                assert ts in self.unrunnable, ts
                assert not ts.processing_on, ts
                assert not ts.who_has, ts
            elif ts.state == "erred":
                assert not ts.processing_on, ts
                assert not ts.who_has, ts
            elif ts.state == "released":
                assert not ts.processing_on, ts
                assert not ts.who_has, ts
                assert not ts.waiting_on, ts
            assert (ts.processing_on is not None) == (ts.state == "processing"), ts
            assert bool(ts.who_has) == (ts.state == "memory"), ts
        except AssertionError as e:
            raise InvalidTaskState(
                f"invalid task state for {ts!r} ({ts.state}): {e}"
            ) from e

    def validate_worker_state(self, ws: WorkerState) -> None:
        for ts in ws.has_what:
            assert ws in ts.who_has, (ws, ts)
        for ts in ws.processing:
            assert ts.processing_on is ws, (ws, ts)
            assert ts.state == "processing", (ws, ts)

    def validate_state(self) -> None:
        """Full invariant check (reference scheduler.py:5544)."""
        for ts in self.tasks.values():
            self.validate_task_state(ts)
        for ws in self.workers.values():
            self.validate_worker_state(ws)
        for ts in self.queued:
            assert ts.state == "queued", ts
        # parked bookkeeping: queued is the disjoint union of the global
        # pop heap and the per-worker parked heaps
        n_parked = 0
        for addr, heap in self.parked.items():
            for ts in heap:
                if ts.state == "queued":
                    n_parked += 1
                    assert ts not in self.queued_unparked, ts
                    assert self._parked_keys.get(ts.key) == addr, ts
        for ts in self.queued_unparked:
            assert ts in self.queued, ts
        for ts in self.queued:
            assert ts in self.queued_unparked or ts.key in self._parked_keys, (
                "queued task reachable by no pop path", ts,
            )
        for ts in self.unrunnable:
            assert ts.state == "no-worker", ts


WORKER_STATUS_CLOSED = "closed"


def _worker_full(ws: WorkerState, saturation_factor: float) -> bool:
    """Is ws at/above its saturation threshold (reference scheduler.py:8750)."""
    if saturation_factor == float("inf"):
        return False
    return len(ws.processing) >= max(math_ceil(ws.nthreads * saturation_factor), 1)


def _merge_msgs(a: dict, b: dict) -> dict:
    out = {k: list(v) for k, v in a.items()}
    _merge_msgs_inplace(out, b)
    return out


def _merge_msgs_inplace(dst: dict, src: dict) -> None:
    for k, v in src.items():
        dst.setdefault(k, []).extend(v)


import math  # noqa: E402

math_isfinite = math.isfinite
math_ceil = math.ceil
