"""JAX placement co-processor: batched decide_worker on device.

The north-star integration (BASELINE.json): instead of running the
python ``decide_worker`` min-loop per task (reference scheduler.py:8550,
~1 ms/task), the scheduler plans a whole incoming graph in one pass at
``update_graph`` time — ``ops.leveled`` packs the DAG into topological
levels with a single O(T+E) native pass and places every wave with
frontier-sized jitted dispatches, one host sync for the whole graph.
The plan is consumed as a per-task hint
by ``decide_worker_non_rootish`` via the ``SchedulerState.placement``
hook; any deviation (worker died, restrictions, occupancy drift) falls
back to the python locality oracle, and WorkStealing rebalances
dynamically — the plan is a speculative hint exactly like the
reference's root-ish ``tg.last_worker`` co-assignment
(reference scheduler.py:2135).

Toggle via ``scheduler.jax.enabled`` / ``scheduler.jax.min-batch``.
"""

from __future__ import annotations

import asyncio
import logging
import math as _math
import threading
from typing import TYPE_CHECKING, Any

from distributed_tpu import config
from distributed_tpu.graph.spec import Key

if TYPE_CHECKING:
    from distributed_tpu.scheduler.state import SchedulerState, TaskState, WorkerState

logger = logging.getLogger("distributed_tpu.jax_placement")

_DEFAULT_NBYTES = 10_000.0  # cost-model guess for unobserved outputs

_MESH_UNSET = object()  # mesh not built yet (vs. None = build failed/off)

import os as _os
_PARK_DEBUG: "list | None" = [] if _os.environ.get("DTPU_PARK_DEBUG") else None


#: atexit grace for an in-flight plan: long enough for a normal XLA-CPU
#: compile/dispatch to drain (seconds), short enough that a WEDGED
#: accelerator tunnel still cannot pin the exit for more than this
_EXIT_DRAIN_S = 15.0


class _DaemonExecutor:
    """Single daemon-thread executor with the tiny slice of the
    concurrent.futures API the planner uses (submit/shutdown).

    ThreadPoolExecutor threads are non-daemon and joined at interpreter
    exit; a jax call blocked on a dead accelerator tunnel would pin the
    process forever.  A daemon thread just dies with the process —
    except that dying INSIDE an XLA compile/dispatch segfaults the
    interpreter teardown (reproduced ~80% with the sharded engine's
    seconds-long compiles in flight at exit), so an atexit hook waits a
    BOUNDED ``_EXIT_DRAIN_S`` for the in-flight job before teardown
    proceeds: normal plans drain, a wedged tunnel costs at most the
    grace period."""

    def __init__(self, name: str):
        import atexit
        import queue
        from concurrent.futures import Future

        self._Future = Future
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._idle = threading.Event()
        self._idle.set()
        self._pending = 0  # queued + running jobs, under _lock
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()
        atexit.register(self._drain_at_exit)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            try:
                if not fut.set_running_or_notify_cancel():
                    continue
                try:
                    fut.set_result(fn(*args))
                except BaseException as exc:  # noqa: BLE001 - to waiter
                    fut.set_exception(exc)
            finally:
                with self._lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    def _drain_at_exit(self) -> None:
        self._idle.wait(_EXIT_DRAIN_S)

    def submit(self, fn, *args):
        fut = self._Future()
        with self._lock:
            self._pending += 1
            self._idle.clear()
        self._q.put((fut, fn, args))
        return fut

    def shutdown(self, wait: bool = False, cancel_futures: bool = False) -> None:
        self._q.put(None)
        if self._idle.is_set():
            # nothing in flight: drop the exit hook so repeated
            # create/close cycles don't accumulate registrations.  With
            # a job still running the hook MUST stay — close-then-exit
            # mid-XLA-dispatch is exactly the teardown segfault the
            # drain exists for.
            import atexit

            try:
                atexit.unregister(self._drain_at_exit)
            except Exception:  # pragma: no cover - interpreter teardown
                pass


def device_dispatch_worthwhile(n_workers: int, n_items: int,
                               min_items: int,
                               periodic: bool = False) -> bool:
    """Shared gate for every scheduler device-kernel path (placement,
    stealing, AMM): the co-processor pays off only with enough workers
    (below ``scheduler.jax.min-workers`` the O(deps) python oracles win)
    and enough items to amortize a dispatch.

    ``periodic``: the caller dispatches on the event loop EVERY cycle
    (stealing balance, AMM, rebalance) rather than once per graph, so it
    keeps its own higher worker floor — forcing ``min-workers`` down to
    study placement hints must not drag a per-tick jax dispatch into
    small clusters (measured: 9x wall blowup at 16 workers)."""
    if not config.get("scheduler.jax.enabled"):
        return False
    floor = max(config.get("scheduler.jax.min-workers"), 2)
    if periodic:
        floor = max(floor, config.get("scheduler.jax.periodic-min-workers"))
    return n_workers >= floor and n_items >= min_items


class JaxPlacement:
    """Whole-graph device planner behind the SchedulerState.placement hook.

    Planning runs OFF the event loop by default: ``plan_graph`` snapshots
    the batch into SoA arrays synchronously (cheap) and hands
    pack+place to a single worker thread, so jit compiles and device
    round-trips never block scheduling.  The plan is only a hint cache —
    tasks that reach ``decide_worker`` before the plan lands simply take
    the python locality oracle, and the plan serves the (much larger)
    tail of waves that become ready as execution proceeds.  Set
    ``scheduler.jax.sync-plan`` for deterministic tests.
    """

    def __init__(self, min_batch: int | None = None,
                 max_batch: int | None = None,
                 min_workers: int | None = None,
                 sync: bool | None = None,
                 min_transfer_ratio: float | None = None):
        self.min_transfer_ratio = (
            min_transfer_ratio if min_transfer_ratio is not None
            else float(config.get("scheduler.jax.min-transfer-ratio"))
        )
        self.min_batch = (
            min_batch if min_batch is not None
            else config.get("scheduler.jax.min-batch")
        )
        self.min_workers = (
            min_workers if min_workers is not None
            else config.get("scheduler.jax.min-workers")
        )
        self.max_batch = max_batch or 1_000_000
        hd = config.get("scheduler.jax.home-depth")
        self.home_depth: int | None = None if hd in ("inf", None) else int(hd)
        self.drift_yield = bool(config.get("scheduler.jax.drift-yield"))
        self.sync = (
            sync if sync is not None
            else bool(config.get("scheduler.jax.sync-plan"))
        )
        # device-mesh sharding (scheduler.jax.mesh subtree): when
        # enabled, the leveled engine runs as ONE partitioned XLA
        # program over the mesh and the fleet half comes from the
        # mirror's workers-axis shards; any failure falls back to the
        # single-device engine, which falls back to the python oracle.
        # "auto" (the default, ROADMAP item 2 leftover): the sharded
        # engine turns on iff MORE THAN ONE device is visible at
        # mesh-build time — a single-device host pays pure collective
        # overhead, so it keeps the single-device -> python fallback
        # chain.  Explicit booleans force it either way.
        mesh_cfg = config.get("scheduler.jax.mesh.enabled")
        self.mesh_enabled: bool | None = (
            mesh_cfg if isinstance(mesh_cfg, bool) else None
        )
        self.mesh_devices = int(config.get("scheduler.jax.mesh.devices"))
        self.mesh_layout = str(config.get("scheduler.jax.mesh.layout"))
        self._mesh: Any = _MESH_UNSET
        self.plan: dict[Key, str] = {}
        # stimulus id of the most recently LANDED plan: the decision
        # ledger stamps it onto every plan-homed placement row
        # (ledger.py ``plan_stim`` field), joining "this task ran on its
        # plan home" back to the flight recorder's ``kernel``
        # placement-plan event that computed the assignment
        self.plan_stim: str = ""
        self.plans_computed = 0
        self.plan_hits = 0
        self.plan_misses = 0
        self.plan_parks = 0
        self.plans_inflight = 0
        # miss breakdown (diagnostics): why CONSULTED hints were refused
        # (these partition plan_misses exactly)
        self.miss_reasons: dict[str, int] = {
            "worker-gone": 0, "restricted": 0, "dep-moved": 0,
            "idle-yield": 0, "park-declined": 0,
        }
        # hints discarded WITHOUT being consulted (not misses): pruned
        # as stale, or landed after the oracle had already placed them
        self.hint_drops: dict[str, int] = {
            "stale-dropped": 0, "landed-late": 0,
        }
        self.enabled = True
        self._executor: _DaemonExecutor | None = None

    # ------------------------------------------------------------- hooks

    def on_add_worker(self, state: "SchedulerState", ws: "WorkerState") -> None:
        pass  # plans stay valid as hints; new workers fill via stealing

    def on_remove_worker(self, state: "SchedulerState", ws: "WorkerState") -> None:
        addr = ws.address
        # follow-dep hints survive a departure (the dep re-resolves
        # against live replicas); only spread hints pinned to the dead
        # worker are dropped
        self.plan = {
            k: a for k, a in self.plan.items()
            if a[0] is not None or a[1] != addr
        }
        # parked-task splicing on worker death lives in
        # SchedulerState.remove_worker (the state owns queue structures)

    def wants(self, ts: "TaskState") -> bool:
        return self.enabled and ts.key in self.plan

    # -------------------------------------------------------- consumption
    #
    # A plan's value is PROSPECTIVE locality: it co-assigns whole
    # subtrees so that once the first task of a tile runs home, every
    # later one finds its inputs local.  Consume-time objective
    # comparisons (occupancy + bytes already in place) cannot see that —
    # at decide time of the EARLY tasks nothing is local anywhere, so
    # "yield to any idle worker" systematically shreds the plan
    # (measured: even a hand-computed comm-optimal tiling lost to the
    # oracle when consumed through idle-yield).  The rules here:
    #
    #   open slot on the home worker  -> place there (hit)
    #   home busy, short backlog      -> PARK: the task queues scheduler-
    #                                    side and the home worker pulls it
    #                                    at its next slot-open
    #   home backlog beyond slack     -> the plan has drifted from live
    #                                    load: yield to the idle worker
    #                                    (objective with transfer latency)

    def resolve(
        self,
        state: "SchedulerState",
        ts: "TaskState",
        valid_workers: "set[WorkerState] | None",
    ) -> "tuple[str, WorkerState | None]":
        """(verdict, ws): ("hit", ws) place now; ("park", ws) defer to
        ws's queue-pull; ("miss", None) hint unusable, use the oracle.

        This is the single consumption point for BOTH transition
        drivers: the per-key engine and the batched flood engine
        (state.py ``stimulus_tasks_finished_batch``) route every ready
        task of a drain round through here against the LIVE occupancy,
        so hint verdicts are identical whichever driver delivered the
        stimulus — the batching lives in message dispatch and send
        coalescing, never in placement semantics (docs/batching.md).
        The plan itself is the batch decision: one ``plan_graph`` device
        call per submitted graph amortizes decide_worker over the whole
        batch, and each resolve is a dict lookup plus backlog math."""
        entry = self.plan.get(ts.key)
        if entry is None:
            return "miss", None
        follow_key, addr = entry
        if follow_key is not None:
            # locality hint: follow the chosen dependency to its LIVE
            # location — robust to upstream drift by construction; when
            # the task is restricted, prefer a holder that satisfies the
            # restriction over the first replica found
            dts = state.tasks.get(follow_key)
            ws = None
            if dts is not None and dts.who_has:
                for cand in dts.who_has:
                    if cand in state.running and (
                        valid_workers is None or cand in valid_workers
                    ):
                        ws = cand
                        break
            if ws is None:
                return self._miss(
                    ts,
                    "restricted"
                    if dts is not None
                    and any(c in state.running for c in dts.who_has)
                    else "dep-moved",
                )
        else:
            ws = state.workers.get(addr)
            if ws is None or ws not in state.running:
                return self._miss(ts, "worker-gone")
            if valid_workers is not None and ws not in valid_workers:
                return self._miss(ts, "restricted")

        # drift check FIRST (even before the open-slot test: a home with
        # a free slot but an hour of occupancy must not absorb more):
        # the plan balanced load GLOBALLY, so during a ready-burst every
        # worker's queue deepens together — the home only loses its
        # claim when it is an OUTLIER vs the cluster-average backlog.
        backlog = ws.occupancy / max(ws.nthreads, 1)
        avg = (
            state.total_occupancy / state.total_nthreads
            if state.total_nthreads
            else 0.0
        )
        if self.home_depth is None:
            # deep-stack mode: tiles become READY at different times, so
            # mid-graph the dispatched load is always concentrated on
            # whichever tiles unblocked first — that is the pipeline
            # working, not drift.  Only an extreme, persistent outlier
            # (a genuinely slow/overloaded home) sheds load.
            slack = 4.0 * avg + max(
                8 * state.transfer_latency,
                2 * state.get_task_duration(ts),
                2.0,
            )
        else:
            slack = avg + max(
                8 * state.transfer_latency, 2 * state.get_task_duration(ts)
            )
        if _PARK_DEBUG is not None:
            _PARK_DEBUG.append((backlog, slack))
        if backlog > slack and state.idle and self.drift_yield:
            idle_ws = next(iter(state.idle.values()))
            bw = state.bandwidth
            lat = state.transfer_latency

            def objective(w: "WorkerState") -> float:
                missing = 0.0
                n_missing = 0
                for dts in ts.dependencies:
                    if w not in dts.who_has:
                        n_missing += 1
                        if dts.nbytes > 0:
                            missing += dts.nbytes
                # same cost model as worker_objective: a fetch pays a
                # fixed RPC latency regardless of payload size, so the
                # hint (zero missing deps) wins ties against "any idle
                # worker" whenever following it avoids real transfers
                return (
                    w.occupancy / max(w.nthreads, 1)
                    + missing / bw
                    + n_missing * lat
                )

            if objective(idle_ws) < objective(ws):
                return self._miss(ts, "idle-yield")

        # home accepts up to a stack beyond the open-slot line: a worker
        # fed exactly one task per slot-open goes dry for a scheduler
        # round trip between tasks (completion -> stimulus -> pull ->
        # compute-task message).  home-depth "inf" stacks everything
        # worker-side (no parking at all) — safe because home-placed
        # tasks are exempt from stealing (ts.homed) and the drift check
        # above still sheds load when the home falls behind.
        if self.home_depth is None:
            depth = float("inf")
        else:
            sat = state.WORKER_SATURATION
            depth = (
                _math.ceil(ws.nthreads * sat) if _math.isfinite(sat)
                else 2 * ws.nthreads
            ) + self.home_depth * ws.nthreads
        if len(ws.processing) < depth:
            del self.plan[ts.key]
            self.plan_hits += 1
            # "plan" provenance: truthy for the steal exemption, and
            # the decision ledger labels the placement row kind "plan"
            # (the shuffle extension pins with "pin" — same exemption,
            # different ledger attribution)
            ts.homed = "plan" if follow_key is None else False
            return "hit", ws
        self.plan_parks += 1
        return "park", ws

    def _get_mesh(self, build: bool = False):
        """The engine mesh when the mesh path is enabled; ``None``
        means off, unavailable, or not built yet.

        Building touches jax backend init (and the jax-availability
        probe, up to 20 s on a wedged accelerator tunnel), so it only
        happens with ``build=True`` — which the plan path passes OFF
        the event loop (the daemon planner thread; sync mode builds
        inline, it is the explicit run-on-loop mode for tests).  Until
        the first async plan lands the mesh, on-loop snapshots see
        ``None`` and that plan runs with a replicated fleet upload —
        the mirror's sharded view joins from the second plan on."""
        if self.mesh_enabled is False:
            return None
        if self._mesh is _MESH_UNSET:
            if not build:
                return None
            from distributed_tpu.ops import partition as part

            mesh = None
            if part.jax_available():
                try:
                    if self.mesh_enabled is None and self._n_visible() < 2:
                        # auto mode on a 1-device host: stay on the
                        # single-device engine (tested: a 1x1 mesh is
                        # bit-identical but pays dispatch overhead)
                        mesh = None
                    else:
                        mesh = part.make_engine_mesh(
                            self.mesh_devices or None, self.mesh_layout
                        )
                except Exception:
                    logger.exception(
                        "engine mesh construction failed; "
                        "falling back to the single-device engine"
                    )
            self._mesh = mesh
        return self._mesh

    @staticmethod
    def _n_visible() -> int:
        """Visible jax device count (0 on import failure) — only called
        behind a successful ``jax_available()`` probe."""
        try:
            import jax

            return len(jax.devices())
        except Exception:
            return 0

    def _miss(self, ts: "TaskState", reason: str):
        self.plan.pop(ts.key, None)
        self.plan_misses += 1
        self.miss_reasons[reason] += 1
        return "miss", None

    def decide_worker(
        self,
        state: "SchedulerState",
        ts: "TaskState",
        valid_workers: "set[WorkerState] | None",
    ) -> "WorkerState | None":
        """Legacy entry (no-worker recovery, opaque control planes):
        hit-or-miss only.  A would-be park is consumed as a miss — the
        caller is about to place the task elsewhere, so keeping the hint
        (and the park tally) would leak plan entries forever."""
        verdict, ws = self.resolve(state, ts, valid_workers)
        if verdict == "park":
            self.plan_parks -= 1
            self._miss(ts, "park-declined")
            return None
        return ws if verdict == "hit" else None


    # ---------------------------------------------------------- planning

    def plan_graph(self, state: "SchedulerState",
                   tasks: "dict[Key, TaskState]",
                   stimulus_id: str = "") -> int:
        """One device call placing the whole batch; returns tasks planned.

        ``stimulus_id`` is the submitting graph's causal id: the kernel
        dispatch is stamped into the flight recorder under it, joining
        the device plan to the ``update-graph`` ingress that caused it."""
        if not self.enabled:
            return 0
        # drop stale hints first: keys gone from the scheduler or no
        # longer pending will never be consulted and would accumulate
        if self.plan:
            before = len(self.plan)
            self.plan = {
                k: a
                for k, a in self.plan.items()
                if (pts := state.tasks.get(k)) is not None
                and pts.state in ("released", "waiting", "queued", "no-worker")
            }
            self.hint_drops["stale-dropped"] += before - len(self.plan)
        # plan only runnable *pending* tasks whose dependencies are inside
        # the batch (external deps already sit on specific workers: the
        # python locality oracle is the right tool for those few).
        # Rootish tasks ARE planned: the partitioner co-assigns a tile's
        # sources with the tile, so inputs are born where they are
        # consumed instead of round-robined by rootish co-assignment.
        batch: list[TaskState] = []
        keyset = set(tasks)
        for ts in tasks.values():
            if ts.run_spec is None or ts.actor or ts.has_restrictions:
                continue
            if ts.state not in ("released", "waiting"):
                continue
            if all(dts.key in keyset for dts in ts.dependencies):
                batch.append(ts)
        if len(batch) < self.min_batch or len(batch) > self.max_batch:
            return 0
        if len(state.workers) < max(self.min_workers, 2):
            return 0
        # PRIORITY order is load-bearing: the partitioner's block init
        # chunks this axis, and scheduler priorities are depth-first
        # graph order (graph/order.py) — adjacent tasks are related
        batch.sort(key=lambda ts: ts.priority or (0,))
        durations, out_bytes, known_frac = self._snapshot_nodes(state, batch)
        ratio = self.min_transfer_ratio
        if (
            ratio
            and known_frac >= 0.5
            and float(out_bytes.mean()) / state.bandwidth
            + state.transfer_latency
            < ratio * float(durations.mean())
        ):
            # transfers are noise next to compute: locality hints cannot
            # pay for themselves on this graph (and occupancy-aware
            # consumption would discard them anyway) — skip the dispatch
            # before paying for the edge snapshot.  Only trustworthy
            # when durations are mostly MEASURED: the 500ms unknown-task
            # default would otherwise veto planning for every
            # first-of-its-kind graph exactly when the plan matters.
            return 0
        snapshot = self._snapshot(state, batch, durations, out_bytes)
        state.trace.emit(
            "kernel", "placement-plan", stimulus_id, n=len(batch)
        )

        try:
            loop = asyncio.get_running_loop() if not self.sync else None
        except RuntimeError:
            loop = None
        if loop is None:
            try:
                # wall-budget seam (diagnostics/selfprofile.py): sync
                # mode dispatches ON the loop thread — bill it there
                state.wall.push("kernel.dispatch", stimulus_id)
                try:
                    plan, engine_shards = self._plan_from_arrays(*snapshot)
                finally:
                    state.wall.pop()
            except Exception:
                logger.exception(
                    "device planning failed; disabling co-processor"
                )
                self.enabled = False
                return 0
            if engine_shards:
                state.observe_engine_shards(engine_shards)
            self.plan.update(plan)
            self.plan_stim = stimulus_id
            self.plans_computed += 1
            return len(plan)

        if self._executor is None:
            # daemon planning thread: jax backend init can block
            # INDEFINITELY when the accelerator tunnel is wedged, and a
            # non-daemon executor thread stuck in make_c_api_client
            # keeps the whole process from exiting (concurrent.futures
            # joins its threads atexit).  The plan simply never lands;
            # the python oracle carries the graph.
            self._executor = _DaemonExecutor("jax-placement")
        self.plans_inflight += 1
        wall = state.wall

        def _plan_job(*args):
            # wall-budget seam: the async plan bills its wall to the
            # PLANNER thread's stack (the budget is per-thread), so the
            # control-plane profiler's planner samples land under
            # phase:kernel.dispatch without touching the loop's stack
            wall.push("kernel.dispatch", stimulus_id)
            try:
                return self._plan_from_arrays(*args)
            finally:
                wall.pop()

        fut = self._executor.submit(_plan_job, *snapshot)

        def _done(f):
            try:
                plan = f.result()
            except BaseException as exc:
                if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                    raise
                plan = None, None
                # a future cancelled by close() is a clean shutdown, not
                # a planning failure
                if not f.cancelled():
                    logger.exception(
                        "device planning failed; disabling co-processor"
                    )
                    self.enabled = False
            try:
                loop.call_soon_threadsafe(
                    self._merge, plan, state, stimulus_id
                )
            except RuntimeError:
                # loop closed before the plan landed: the merge (and its
                # inflight decrement) will never run on-loop
                self.plans_inflight -= 1

        fut.add_done_callback(_done)
        return 0

    def planner_ident(self) -> int | None:
        """Thread ident of the daemon planner thread (None before the
        first async plan spawns it) — the control-plane profiler
        (diagnostics/selfprofile.py) samples it alongside the loop."""
        ex = self._executor
        thread = getattr(ex, "_thread", None) if ex is not None else None
        return thread.ident if thread is not None else None

    def close(self) -> None:
        """Release the planning thread (scheduler shutdown)."""
        self.enabled = False
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    def _merge(self, plan_shards, state: "SchedulerState",
               stimulus_id: str = "") -> None:
        """Land an async plan on the loop thread, keeping only hints for
        tasks still pending — tasks the oracle placed while the plan was
        computing would otherwise accumulate as dead entries forever
        (and, with reused pure keys, serve stale hints to later graphs)."""
        self.plans_inflight -= 1
        plan, engine_shards = plan_shards or (None, None)
        if engine_shards:
            state.observe_engine_shards(engine_shards)
        if plan:
            live = {
                k: v
                for k, v in plan.items()
                if (ts := state.tasks.get(k)) is not None
                and ts.state in ("released", "waiting", "queued", "no-worker")
            }
            self.hint_drops["landed-late"] += len(plan) - len(live)
            if live:
                self.plan.update(live)
                self.plan_stim = stimulus_id
                self.plans_computed += 1
                logger.debug(
                    "planned %d tasks on device (%d already placed)",
                    len(live), len(plan) - len(live),
                )

    @staticmethod
    def _snapshot_nodes(state: "SchedulerState", batch: list):
        """Per-task cost arrays + fraction of MEASURED durations (the
        payoff gate is meaningless against the unknown-task default)."""
        import numpy as np

        n = len(batch)
        durations = np.empty(n, np.float32)
        out_bytes = np.empty(n, np.float32)
        known = 0
        for i, ts in enumerate(batch):
            prefix = ts.prefix
            if prefix is not None and prefix.duration_average >= 0:
                known += 1
            durations[i] = state.get_task_duration(ts)
            nbytes = ts.nbytes
            if nbytes < 0 and prefix is not None and prefix.nbytes_total:
                counts = sum(prefix.state_counts.values()) or 1
                nbytes = prefix.nbytes_total / counts
            out_bytes[i] = nbytes if nbytes and nbytes > 0 else _DEFAULT_NBYTES
        return durations, out_bytes, known / max(n, 1)

    def _snapshot(self, state: "SchedulerState", batch: list,
                  durations, out_bytes):
        """Synchronous SoA snapshot of the batch + worker fleet (the
        TaskState graph must not be touched off-loop).

        The fleet half comes from the persistent mirror when available
        (scheduler/mirror.py): slot-indexed capacity-sized arrays with
        tombstone rows carrying ``running=False``/``nthreads=0`` — both
        device engines already mask on exactly those bits — copied
        because the planner thread reads them while the loop keeps
        mutating the live buffers.  Cost: O(dirty) refresh + numpy
        copies, no per-worker Python loop.  Without a mirror the
        from-scratch pack below remains the oracle path."""
        import numpy as np

        index = {ts.key: i for i, ts in enumerate(batch)}
        keys = [ts.key for ts in batch]
        src: list[int] = []
        dst: list[int] = []
        for i, ts in enumerate(batch):
            for dts in ts.dependencies:
                j = index.get(dts.key)
                if j is not None:
                    src.append(j)
                    dst.append(i)
        mirror = state.mirror
        if mirror is not None:
            fv = mirror.fleet_view()
            nthreads = fv.nthreads.copy()
            occupancy = fv.occupancy.copy()
            running = fv.running.copy()
            addrs = list(fv.addrs)
        else:
            workers = list(state.workers.values())
            nthreads = np.asarray([ws.nthreads for ws in workers], np.int32)
            occupancy = np.asarray(
                [ws.occupancy for ws in workers], np.float32
            )
            running = np.asarray(
                [ws in state.running for ws in workers], bool
            )
            addrs = [ws.address for ws in workers]
        # mesh plan path: grab the mirror's workers-axis device shards
        # ON LOOP (cheap O(dirty) scatter) so the planner thread reads
        # immutable jax arrays the kernel consumes with ZERO fleet H2D;
        # the host copies above still seed the load carry and the
        # uniform/wide decisions.  Building the mesh is jax backend
        # init — on-loop only in sync mode; the async path builds it in
        # the planner thread on its first plan (_plan_from_arrays).
        mesh = self._get_mesh(build=self.sync)
        fleet_dev = None
        if mesh is not None and mirror is not None:
            try:
                fleet_dev = mirror.sharded_device_view(mesh)
            except Exception:
                logger.exception(
                    "sharded mirror view failed; replicated fleet upload"
                )
        return (
            keys, durations, out_bytes,
            np.asarray(src, np.int32), np.asarray(dst, np.int32),
            nthreads, occupancy, running, addrs, state.bandwidth,
            state.transfer_latency, mesh, fleet_dev,
        )

    def _plan_from_arrays(self, keys, durations, out_bytes, src, dst,
                          nthreads, occupancy, running, addrs, bandwidth,
                          transfer_latency=0.0, mesh=None, fleet_dev=None):
        """Plan on pure arrays — safe to run off-loop (the only ``self``
        use is the one-time mesh build, deliberately placed HERE so jax
        backend init happens on the planner thread).  Returns
        ``(plan, engine_shards)`` where ``engine_shards`` is the sharded
        engine's per-shard stat list (None off the mesh path).

        Two device engines compose here (ops/partition.py docstring has
        the measurements):

        - ``ops.partition`` (preferred while T·W fits the dense score
          matrix): comm-volume partitioning over the priority axis,
          emitted as ABSOLUTE home hints ``(None, addr)`` — the park/
          pull consumption keeps whole tiles together, which is the
          point; drift tolerance comes from the backlog checks at
          consume time, not from re-resolution.
        - ``ops.leveled`` (the million-task fallback): wave-synchronous
          placement following heavy dependencies.  A locality choice is
          encoded FOLLOW-THIS-DEPENDENCY, not as an absolute address:
          ``resolve`` finds the dep's CURRENT holder at consume time, so
          a hint survives upstream drift (absolute addresses died with
          the first upstream deviation and the invalidation cascaded —
          measured at 84% of all misses on the rechunk+tensordot bench).
          Spread placements (choice 2) keep the planned address: their
          content IS the global load-balance assignment.
        """
        import numpy as np

        from distributed_tpu.ops import partition as part

        engine = config.get("scheduler.jax.partitioner")
        run_idx = np.flatnonzero(running)
        n_running = len(run_idx)
        T = len(keys)
        # load-balance durations on the nthreads-weighted axis: a
        # 2-thread worker should receive twice the work.  The
        # partitioner treats workers as equal bins, so spread the label
        # space: worker w appears nthreads[w] times and the labels fold
        # back at the end.  The dense-score cap must count LANES (and
        # the pow2 padding of T), not workers — the score matrix is
        # T_padded x lanes.
        lanes: list[int] = []
        for wi in run_idx:
            lanes.extend([int(wi)] * max(int(nthreads[wi]), 1))
        if (
            engine in ("auto", "numpy")
            and n_running >= 2
            and part._bucket(T) * len(lanes) <= part.DENSE_LIMIT
        ):
            weights = (
                out_bytes[src] / bandwidth + transfer_latency
            ).astype(np.float32)
            if engine == "numpy" or not part.jax_available():
                labels = part.partition_numpy(
                    durations, weights, src, dst, len(lanes)
                )
            else:
                try:
                    labels = part.partition_padded(
                        durations, weights, src, dst, len(lanes)
                    )
                except Exception:
                    logger.exception(
                        "jax partitioner failed; numpy fallback"
                    )
                    labels = part.partition_numpy(
                        durations, weights, src, dst, len(lanes)
                    )
            return {
                key: (None, addrs[lanes[int(labels[i])]])
                for i, key in enumerate(keys)
            }, None

        from distributed_tpu.ops.leveled import place_graph_streamed

        # streamed driver: on large graphs the pack fill and H2D upload
        # pipeline, so the plan lands one wire-crossing sooner (falls
        # back to pack+place below the streaming threshold).  With a
        # mesh the same driver dispatches through the SHARDED engine —
        # per-shard H2D tiles, mirror-resident fleet rows — and any
        # failure there degrades to the single-device program (the
        # python oracle stays the final fallback at consume time).
        engine_stats: dict | None = None
        packed = result = None
        if mesh is None:
            # first async plan with the mesh path on: build it here,
            # off the event loop (no-op when the path is disabled)
            mesh = self._get_mesh(build=True)
        if mesh is not None:
            engine_stats = {}
            try:
                packed, result = place_graph_streamed(
                    durations, out_bytes, src, dst, nthreads, occupancy,
                    running, bandwidth=bandwidth, latency=transfer_latency,
                    mesh=mesh, fleet_dev=fleet_dev, stats=engine_stats,
                )
            except Exception:
                logger.exception(
                    "sharded engine failed; single-device fallback"
                )
                engine_stats = None
                packed = result = None
        if result is None:
            packed, result = place_graph_streamed(
                durations, out_bytes, src, dst, nthreads, occupancy,
                running, bandwidth=bandwidth, latency=transfer_latency,
            )
        assignment = result.assignment
        nw = len(addrs)
        n = len(keys)
        inv = np.empty(max(n, 1), np.int32)
        inv[packed.perm] = np.arange(n, dtype=np.int32)
        hs = packed.heavy_s[inv[:n]]
        h2s = packed.heavy2_s[inv[:n]]
        horig = np.where(hs >= 0, packed.perm[np.maximum(hs, 0)], -1)
        h2orig = np.where(h2s >= 0, packed.perm[np.maximum(h2s, 0)], -1)
        follow = np.where(
            result.choice == 0, horig,
            np.where(result.choice == 1, h2orig, -1),
        )
        return {
            key: (
                keys[int(follow[i])] if follow[i] >= 0 else None,
                addrs[int(assignment[i])],
            )
            for i, key in enumerate(keys)
            if 0 <= assignment[i] < nw
        }, (engine_stats or {}).get("shards")

    def __repr__(self) -> str:
        return (
            f"<JaxPlacement plans={self.plans_computed} "
            f"hits={self.plan_hits} misses={self.plan_misses} "
            f"pending={len(self.plan)} enabled={self.enabled}>"
        )
