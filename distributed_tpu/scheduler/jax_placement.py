"""JAX placement co-processor: batched decide_worker on device.

The north-star integration (BASELINE.json): instead of running the
python ``decide_worker`` min-loop per task (reference scheduler.py:8550,
~1 ms/task), the scheduler plans a whole incoming graph in ONE device
call at ``update_graph`` time — ``ops.wavefront.place_graph`` levelizes
the DAG and assigns every task with a masked cost-matrix argmin per
wavefront, entirely inside jit.  The plan is consumed as a per-task hint
by ``decide_worker_non_rootish`` via the ``SchedulerState.placement``
hook; any deviation (worker died, restrictions, occupancy drift) falls
back to the python locality oracle, and WorkStealing rebalances
dynamically — the plan is a speculative hint exactly like the
reference's root-ish ``tg.last_worker`` co-assignment
(reference scheduler.py:2135).

Toggle via ``scheduler.jax.enabled`` / ``scheduler.jax.min-batch``.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

from distributed_tpu import config
from distributed_tpu.graph.spec import Key

if TYPE_CHECKING:
    from distributed_tpu.scheduler.state import SchedulerState, TaskState, WorkerState

logger = logging.getLogger("distributed_tpu.jax_placement")

_DEFAULT_NBYTES = 10_000.0  # cost-model guess for unobserved outputs


class JaxPlacement:
    """Whole-graph device planner behind the SchedulerState.placement hook."""

    def __init__(self, min_batch: int | None = None,
                 max_batch: int | None = None):
        self.min_batch = (
            min_batch if min_batch is not None
            else config.get("scheduler.jax.min-batch")
        )
        self.max_batch = max_batch or 1_000_000
        self.plan: dict[Key, str] = {}
        self.plans_computed = 0
        self.plan_hits = 0
        self.plan_misses = 0
        self.enabled = True

    # ------------------------------------------------------------- hooks

    def on_add_worker(self, state: "SchedulerState", ws: "WorkerState") -> None:
        pass  # plans stay valid as hints; new workers fill via stealing

    def on_remove_worker(self, state: "SchedulerState", ws: "WorkerState") -> None:
        addr = ws.address
        self.plan = {k: a for k, a in self.plan.items() if a != addr}

    def wants(self, ts: "TaskState") -> bool:
        return self.enabled and ts.key in self.plan

    def decide_worker(
        self,
        state: "SchedulerState",
        ts: "TaskState",
        valid_workers: "set[WorkerState] | None",
    ) -> "WorkerState | None":
        addr = self.plan.pop(ts.key, None)
        if addr is None:
            return None
        ws = state.workers.get(addr)
        if ws is None or ws not in state.running:
            self.plan_misses += 1
            return None
        if valid_workers is not None and ws not in valid_workers:
            self.plan_misses += 1
            return None
        self.plan_hits += 1
        return ws

    # ---------------------------------------------------------- planning

    def plan_graph(self, state: "SchedulerState",
                   tasks: "dict[Key, TaskState]") -> int:
        """One device call placing the whole batch; returns tasks planned."""
        if not self.enabled:
            return 0
        # drop stale hints first: keys gone from the scheduler or no
        # longer pending will never be consulted and would accumulate
        if self.plan:
            self.plan = {
                k: a
                for k, a in self.plan.items()
                if (pts := state.tasks.get(k)) is not None
                and pts.state in ("released", "waiting", "queued", "no-worker")
            }
        # plan only runnable *pending* tasks whose dependencies are inside
        # the batch (external deps already sit on specific workers: the
        # python locality oracle is the right tool for those few), and
        # skip root-ish tasks — the rootish co-assignment paths never
        # consult the placement hook
        batch: list[TaskState] = []
        keyset = set(tasks)
        for ts in tasks.values():
            if ts.run_spec is None or ts.actor or ts.has_restrictions:
                continue
            if ts.state not in ("released", "waiting"):
                continue
            if state.is_rootish(ts):
                continue
            if all(dts.key in keyset for dts in ts.dependencies):
                batch.append(ts)
        if len(batch) < self.min_batch or len(batch) > self.max_batch:
            return 0
        workers = [ws for ws in state.workers.values()]
        if len(workers) < 2:
            return 0
        try:
            plan = self._device_plan(state, batch, workers)
        except Exception:
            logger.exception("device planning failed; disabling co-processor")
            self.enabled = False
            return 0
        self.plan.update(plan)
        self.plans_computed += 1
        logger.debug("planned %d tasks on device", len(plan))
        return len(plan)

    def _device_plan(self, state: "SchedulerState", batch: list,
                     workers: list) -> dict[Key, str]:
        import numpy as np

        from distributed_tpu.ops.placement import pad_to_bucket
        from distributed_tpu.ops.wavefront import GraphArrays, place_graph

        n = len(batch)
        index = {ts.key: i for i, ts in enumerate(batch)}
        durations = np.empty(n, np.float32)
        out_bytes = np.empty(n, np.float32)
        src: list[int] = []
        dst: list[int] = []
        for i, ts in enumerate(batch):
            durations[i] = state.get_task_duration(ts)
            nbytes = ts.nbytes
            if nbytes < 0 and ts.prefix is not None and ts.prefix.nbytes_total:
                counts = sum(ts.prefix.state_counts.values()) or 1
                nbytes = ts.prefix.nbytes_total / counts
            out_bytes[i] = nbytes if nbytes and nbytes > 0 else _DEFAULT_NBYTES
            for dts in ts.dependencies:
                j = index.get(dts.key)
                if j is not None:
                    src.append(j)
                    dst.append(i)

        import jax.numpy as jnp

        g = GraphArrays.from_arrays(
            durations,
            out_bytes,
            np.asarray(src, np.int64),
            np.asarray(dst, np.int64),
            pad_tasks=pad_to_bucket(n),
            pad_edges=pad_to_bucket(max(len(src), 1)),
        )
        nthreads = jnp.asarray(
            [ws.nthreads for ws in workers], jnp.int32
        )
        occupancy = jnp.asarray(
            [ws.occupancy for ws in workers], jnp.float32
        )
        running = jnp.asarray(
            [ws in state.running for ws in workers], bool
        )
        result = place_graph(
            g, nthreads, occupancy, running, bandwidth=state.bandwidth
        )
        assignment = np.asarray(result.assignment)[:n]
        addrs = [ws.address for ws in workers]
        return {
            ts.key: addrs[int(assignment[i])]
            for i, ts in enumerate(batch)
            if 0 <= assignment[i] < len(addrs)
        }

    def __repr__(self) -> str:
        return (
            f"<JaxPlacement plans={self.plans_computed} "
            f"hits={self.plan_hits} misses={self.plan_misses} "
            f"pending={len(self.plan)} enabled={self.enabled}>"
        )
