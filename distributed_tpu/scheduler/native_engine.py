"""Bridge to the native (C++) transition core — docs/native_engine.md.

``native/engine.cpp`` owns a struct-of-arrays mirror of the scheduler's
task/worker/prefix/group state and executes the four dominant
transition arms (~80% of engine wall per
``docs/state_machine/engine_wall.json``) entirely in C++: decisions,
drain control flow (exact CPython ``dict.popitem`` rec semantics),
occupancy floats and idle/saturated membership flips.  It emits a TAPE;
this bridge replays the tape onto the real ``TaskState``/``WorkerState``
objects with slim per-arm appliers that perform the SAME mutation
sequence the scalar oracle would — the relation fields are
insertion-ordered (``OrderedSet``), so "same sequence" is well-defined
and the C++ vectors mirror it exactly.  Messages, story rows, journal
records, ledger rows and plugin calls are all built from python truth,
which is what makes the output bit-identical to the oracle.

Replay is DEFERRED (the "authoritative SoA" contract,
docs/native_engine.md): a completed native segment stashes its tape
plus context on ``self._pending`` instead of replaying immediately, so
the flood's timed path is just prep + flush + the C++ call.  The SoA is
the source of truth until ``sync()`` replays every pending segment in
original order — triggered by the SoA-backed TaskState/WorkerState
property accessors (``state._NATIVE_PENDING``), the ledger/telemetry
read barriers, the lazy message dicts the drives return, and every
python-side mutation hook below.  Deferral changes WHEN the python
objects materialize, never what they materialize to: replay runs the
same appliers against unchanged starting state, with the flood's
hoisted clock stamp threaded through so ledger digests stay
bit-identical.

Anything an arm needs that the core does not model ESCAPES to the
python oracle per key: the drain stops at a transition boundary, the
tape so far is applied, and the popped transition plus the pending
rec-dict are handed to the real ``_transition``/``_transitions``.
Python-side mutations (escapes, scalar stimuli, steal/AMM, graph
intake) mark rows dirty at the existing mutation helpers; dirty rows
resync into the SoA before the next native segment.

Compiled arm set — graft-lint's ``state-machine`` rule asserts this
stays a subset of the extracted scheduler transition table, so a new
arm added in python but missing from C++ is a lint finding, not a perf
cliff:
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import TYPE_CHECKING, Any

from distributed_tpu import native
from distributed_tpu.protocol.serialize import wrap_opaque
from distributed_tpu.scheduler.state import (
    _NATIVE_PENDING,
    _merge_msgs_inplace as _merge,
)
from distributed_tpu.utils.collections import OrderedSet

if TYPE_CHECKING:  # pragma: no cover
    from distributed_tpu.scheduler.state import (
        SchedulerState, TaskState, WorkerState,
    )

logger = logging.getLogger("distributed_tpu.scheduler.native")

#: the (start, finish) pairs engine.cpp compiles — checked against the
#: extracted scheduler table by analysis/rules/state_machine.py
COMPILED_ARMS = (
    ("released", "waiting"),
    ("waiting", "processing"),
    ("processing", "memory"),
    ("memory", "released"),
)

#: state name <-> enum (must match engine.cpp's State)
STATE_NAMES = (
    "released", "waiting", "no-worker", "queued", "processing", "memory",
    "erred", "forgotten",
)
STATE_IDX = {name: i for i, name in enumerate(STATE_NAMES)}

#: worker status name -> enum (engine.cpp WStatus)
WSTATUS_IDX = {
    "running": 0, "paused": 1, "closing": 2, "closing_gracefully": 3,
    "init": 4, "closed": 5,
}

# task flag bits (engine.cpp Flag)
F_ACTOR, F_RESTRICTED, F_NO_RUNSPEC, F_BLAMED, F_LONG_RUNNING = (
    1, 2, 4, 8, 16,
)

# tape opcodes (engine.cpp Op)
(OP_FREEKEYS_STALE, OP_ADD_REPLICA, OP_PM, OP_WP, OP_MR, OP_RW, OP_FLIP,
 OP_META) = range(8)

R_DONE, R_ESCAPE, R_TAPE_FULL = 0, 1, 2

#: escape-reason names, indexed by engine.cpp EscapeWhy (metrics label)
ESCAPE_WHY = (
    "uncompiled-edge", "actor", "restricted", "rootish", "placement-ext",
    "bare-dep", "no-worker", "forgotten-dep", "event-shape",
)

_COMPILED_SET = frozenset(COMPILED_ARMS)

#: max events handed to one native segment call
SEG_MAX = 65536

#: scheduler.native-engine.min-flood: floods smaller than this run the
#: oracle directly.  Default 0 — the SoA maintenance hooks are paid
#: while the engine is attached regardless, so skipping small floods
#: only ADDS relative overhead (measured: 0.78x at 12 vs 1.11x at 0 on
#: the 1000-worker sim).  The knob exists for experiments that want the
#: bridge inert outside the batch plane.
MIN_FLOOD_DEFAULT = 0

_i32 = ctypes.c_int32
_i64 = ctypes.c_int64
_u8 = ctypes.c_uint8
_f64 = ctypes.c_double


def _arr(ctype, values):
    return (ctype * len(values))(*values)


class _Buf:
    """Growable persistent ctypes buffer filled by slice assignment."""

    __slots__ = ("ctype", "cap", "arr")

    def __init__(self, ctype, cap=1024):
        self.ctype = ctype
        self.cap = cap
        self.arr = (ctype * cap)()

    def fill(self, values):
        n = len(values)
        if n > self.cap:
            cap = self.cap
            while cap < n:
                cap *= 2
            self.cap = cap
            self.arr = (self.ctype * cap)()
        self.arr[:n] = values
        return self.arr


class _LazyMsgs(dict):
    """Per-destination message dict returned by the deferred drives.

    Deferred native segments hold a reference to this dict and append
    their message rows only at ``sync()`` — so every READ materializes
    pending segments first, keeping per-destination message order
    identical to the oracle's.  The writer path (``setdefault``) stays
    non-syncing on purpose: the appliers and the post-sync oracle
    escape paths write through it, and a sync from inside the applier
    would recurse.
    """

    __slots__ = ("_eng",)

    def __init__(self, eng):
        super().__init__()
        self._eng = eng

    def _sync(self):
        eng = self._eng
        if eng._pending:
            eng.sync()

    def __iter__(self):
        self._sync()
        return dict.__iter__(self)

    def __len__(self):
        self._sync()
        return dict.__len__(self)

    def __contains__(self, k):
        self._sync()
        return dict.__contains__(self, k)

    def __getitem__(self, k):
        self._sync()
        return dict.__getitem__(self, k)

    def __eq__(self, other):
        self._sync()
        return dict.__eq__(self, other)

    def __ne__(self, other):
        self._sync()
        return dict.__ne__(self, other)

    __hash__ = None

    def __repr__(self):
        self._sync()
        return dict.__repr__(self)

    def get(self, k, default=None):
        self._sync()
        return dict.get(self, k, default)

    def keys(self):
        self._sync()
        return dict.keys(self)

    def values(self):
        self._sync()
        return dict.values(self)

    def items(self):
        self._sync()
        return dict.items(self)

    def copy(self):
        self._sync()
        return dict(self)

    def pop(self, *a):
        self._sync()
        return dict.pop(self, *a)

    def popitem(self):
        self._sync()
        return dict.popitem(self)


class NativeEngine:
    """Per-SchedulerState bridge to one C++ engine instance."""

    def __init__(self, state: "SchedulerState", lib: ctypes.CDLL):
        self.state = state
        self.lib = lib
        self.h = ctypes.c_void_p(lib.eng_new())
        self.ok = True
        # DTPU_NATIVE_CHECK: per-flood SoA<->python audit (dual-run
        # parity gate; the property tests do full oracle dual-state
        # parity on top of this)
        self.check = os.environ.get("DTPU_NATIVE_CHECK", "") not in ("", "0")
        # row/slot registries.  Rows park on the objects (ts.nrow /
        # ws.nidx) so the hot path pays no dict hash.
        self._rows: list[Any] = []
        self._row_free: list[int] = []
        self._wslots: list[Any] = []
        self._prefix_ids: dict[str, int] = {}
        self._group_ids: dict[str, int] = {}
        # dirty sets (python-side mutations pending resync)
        # insertion-ordered: flush visit order assigns first-sight
        # prefix/group ids and fills the SoA relation vectors, so it
        # must not be hash-seed order
        self._dirty: OrderedSet = OrderedSet()
        self._dirty_workers: OrderedSet = OrderedSet()
        # row indices allocated but never yet flushed into the SoA:
        # lets the census walk compare python rows against the C++
        # live count without forcing a flush (fresh ⊆ dirty always)
        self._fresh: set = set()
        # the applier replays native mutations through the real helpers
        # (add_replica & co) for their mirror marks — the native dirty
        # hooks must NOT re-dirty rows the engine itself just wrote
        self._applying = False
        # lifetime counters (python-side halves; native halves live in
        # the engine): oracle_transitions counts transitions executed by
        # escapes/fallbacks while the engine was attached
        self.oracle_transitions = 0
        self.floods = 0
        self.segments = 0
        from distributed_tpu import config as _config

        self.min_flood = int(
            _config.get("scheduler.native-engine.min-flood")
        )
        # deferred materialization: completed native segments stash
        # (tape, n, events, round_stim, stim, now, cmsgs, wmsgs) here
        # instead of replaying immediately; sync() replays in order.
        # Invariant: self is in state._NATIVE_PENDING iff _pending is
        # non-empty (outside an in-flight sync).
        self._pending: list = []
        self._syncing = False
        # tape buffers come from a free-list pool so a deferred tape is
        # never overwritten by the next segment's native call
        self._tape_pool: list = [self._alloc_tape(1 << 14)]
        # hydration counters (dtpu_engine_hydration* metric families):
        # tape rows materialized by deferred replay, and sync() probes
        # that found everything already materialized
        self.hydrations = 0
        self.hyd_cache_hits = 0
        # persistent flush/prep buffers (ctypes array CONSTRUCTION is
        # ~2us each; 19 fresh arrays per flood was the dominant fixed
        # cost — slice-assignment into persistent buffers is a C loop).
        # Event buffers live in their own dict: flush() keys its lazy
        # init on its OWN dict being empty (reviewer-found: sharing one
        # dict let a flood seed it first and flush raise KeyError)
        self._bufs: dict = {}
        self._ev_bufs: dict = {}
        # scratch for touched-worker write-back
        self._tw_cap = 1024
        self._tw_slots = (_i32 * self._tw_cap)()
        self._tw_occ = (_f64 * self._tw_cap)()
        # scratch for pending-rec handoff
        self._pr_cap = 4096
        self._pr_rows = (_i32 * self._pr_cap)()
        self._pr_tgts = (_i32 * self._pr_cap)()
        self._scratch8 = (_i64 * 8)()

    # ------------------------------------------------------------ attach

    @classmethod
    def attach(cls, state: "SchedulerState", *,
               build: bool = False) -> "NativeEngine | None":
        """A bridge over the loaded native library, or None when the
        library is unavailable (no toolchain, DTPU_NATIVE_DISABLE, not
        yet prebuilt).  ``build=True`` compiles on demand (bench/sim
        contexts); the default never blocks on g++ — servers call
        ``native.prebuild_async`` and re-attach on the ready callback.
        """
        lib = native.load() if build else native.load_nowait()
        if lib is None:
            return None
        ne = cls(state, lib)
        # adopt the current world: every live task and worker
        for ws in state.workers.values():
            ne.on_add_worker(ws)
        for ts in state.tasks.values():
            ne.on_new_task(ts)
        # deferred-materialization read barriers: ledger and telemetry
        # reads must fold pending native file/join rows first
        state.ledger.barrier = ne.sync
        if getattr(state.telemetry, "barrier", None) is None:
            state.telemetry.barrier = ne.sync
        return ne

    def close(self) -> None:
        self._drop_pending()
        s = self.state
        if s.ledger.barrier == self.sync:
            s.ledger.barrier = None
        if getattr(s.telemetry, "barrier", None) == self.sync:
            s.telemetry.barrier = None
        if self.h:
            self.lib.eng_free(self.h)
            self.h = ctypes.c_void_p()
        self.ok = False

    def _drop_pending(self) -> None:
        """Forget deferred segments WITHOUT replaying (teardown/degrade
        paths only — the normal path is sync())."""
        self._pending.clear()
        try:
            _NATIVE_PENDING.remove(self)
        except ValueError:
            pass

    def detach(self) -> None:
        """Tear down fully: free the C++ engine AND clear the row/slot
        markers parked on the python objects, so a later attach_native
        starts from a clean world instead of adopting stale nrow/nidx
        ids into a fresh engine (reviewer-found)."""
        if self._pending and not self._syncing:
            try:
                self.sync()
            except Exception:
                logger.exception(
                    "deferred native segments lost at detach"
                )
                self._drop_pending()
        for ts in self._rows:
            if ts is not None:
                ts.nrow = -1
        for ws in self._wslots:
            if ws is not None:
                ws.nidx = -1
        self._rows = []
        self._row_free = []
        self._wslots = []
        self._dirty.clear()
        self._dirty_workers.clear()
        self._fresh.clear()
        self.close()

    # ----------------------------------------------------------- gating

    def active(self) -> bool:
        """May the next flood/round run natively?  (Cheap; evaluated
        per flood.)  validate / per-arm wall attribution / a transition
        counter cap / non-tape-safe plugins all force the oracle."""
        s = self.state
        if not self.ok:
            return False
        if s.validate or s.WALL_ARMS or s.transition_counter_max:
            return False
        if s.plugins:
            for p in s.plugins.values():
                if not getattr(p, "tape_safe", False):
                    return False
        return True

    # ------------------------------------------------------------- hooks
    #
    # Called from SchedulerState's mutation helpers (the delta-
    # consistency seam, same discipline as scheduler/mirror.py).
    #
    # Every hook is SYNC-FIRST under deferral (_materialize): a python
    # mutation is about to land, so pending native segments must replay
    # before it — which gives flush() its invariant that anything in
    # the dirty sets was marked while python truth was current.

    def _materialize(self) -> None:
        if self._pending and not self._syncing:
            self.sync()

    def on_new_task(self, ts: "TaskState") -> None:
        self._materialize()
        if ts.nrow < 0:
            if self._row_free:
                row = self._row_free.pop()
                self._rows[row] = ts
            else:
                row = len(self._rows)
                self._rows.append(ts)
            ts.nrow = row
            self._fresh.add(row)
        self._dirty.add(ts)

    def on_forget_task(self, ts: "TaskState") -> None:
        self._materialize()  # pending tapes reference rows by index
        row = ts.nrow
        if row < 0:
            return
        self.lib.eng_task_forget(self.h, row)
        self._rows[row] = None
        self._row_free.append(row)
        ts.nrow = -1
        self._dirty.discard(ts)
        self._fresh.discard(row)

    def mark_task(self, ts: "TaskState") -> None:
        if self._applying:
            return
        self._materialize()
        if ts.nrow >= 0:
            self._dirty.add(ts)

    def mark_transition(self, ts: "TaskState") -> None:
        """An oracle transition ran for ts: its own row plus both
        relation neighborhoods may have changed."""
        if self._applying:  # pragma: no cover - applier never transitions
            return
        self._materialize()
        d = self._dirty
        if ts.nrow >= 0:
            d.add(ts)
        for dts in ts.dependencies:
            if dts.nrow >= 0:
                d.add(dts)
        for dts in ts.dependents:
            if dts.nrow >= 0:
                d.add(dts)

    # incremental deltas — the frequent between-flood mutations come
    # through here as ONE ctypes call instead of a full-row resync
    # (safe on already-dirty rows: the authoritative resync overwrites)

    def on_replica(self, ts: "TaskState", ws: "WorkerState",
                   add: bool) -> None:
        if self._applying:
            return
        self._materialize()
        if ts.nrow < 0 or ws.nidx < 0:
            return
        if add:
            self.lib.eng_replica_add(self.h, ts.nrow, ws.nidx)
        else:
            self.lib.eng_replica_remove(self.h, ts.nrow, ws.nidx)

    def on_nbytes(self, ts: "TaskState", nbytes: int) -> None:
        if self._applying:
            return
        self._materialize()
        if ts.nrow >= 0:
            self.lib.eng_task_nbytes(self.h, ts.nrow, nbytes)

    def on_who_wants(self, ts: "TaskState") -> None:
        if self._applying:
            return
        self._materialize()
        if ts.nrow >= 0:
            self.lib.eng_task_who_wants(self.h, ts.nrow,
                                        len(ts.who_wants))

    def mark_worker(self, ws: "WorkerState") -> None:
        if self._applying:
            return
        self._materialize()
        if ws.nidx >= 0:
            self._dirty_workers.add(ws)

    def on_add_worker(self, ws: "WorkerState") -> None:
        self._materialize()
        if ws.nidx < 0:
            ws.nidx = len(self._wslots)
            self._wslots.append(ws)
            # eager upsert: every python slot has a live SoA twin from
            # registration on (the census walk-vs-counter audit on
            # native.soa-workers relies on this; adds are rare)
            self._upsert_worker(ws)
        self._dirty_workers.add(ws)

    def on_remove_worker(self, ws: "WorkerState") -> None:
        self._materialize()  # pending tapes reference wslots by index
        # slots are never reused (removals are rare; a rejoining
        # address gets a fresh WorkerState and a fresh slot)
        if ws.nidx >= 0:
            # the caller's replica/processing cleanup runs AFTER this
            # hook, and its on_replica deltas will no-op once nidx is
            # -1: mark every task referencing the dead worker dirty NOW
            # so the next flush rebuilds their who_has/processing_on
            # from python truth (reviewer-found: the stale slot
            # otherwise survives in the SoA and trips the
            # DTPU_NATIVE_CHECK audit as a false divergence)
            for ts in ws.has_what:
                self.mark_task(ts)
            for ts in ws.processing:
                self.mark_task(ts)
            self.lib.eng_worker_close(self.h, ws.nidx)
            self._dirty_workers.discard(ws)
            self._wslots[ws.nidx] = None
            ws.nidx = -1

    def reset(self) -> None:
        """_clear_task_state: drop every task row (workers survive)."""
        self._materialize()
        self._dirty.clear()
        self._fresh.clear()
        for row, ts in enumerate(self._rows):
            if ts is not None:
                self.lib.eng_task_forget(self.h, row)
                ts.nrow = -1
        self._rows = []
        self._row_free = []

    # ------------------------------------------------------------ flush

    def _prefix_id(self, name: str) -> int:
        pid = self._prefix_ids.get(name)
        if pid is None:
            pid = self._prefix_ids[name] = len(self._prefix_ids)
        return pid

    def _group_id(self, name: str) -> int:
        gid = self._group_ids.get(name)
        if gid is None:
            gid = self._group_ids[name] = len(self._group_ids)
        return gid

    def _task_flags(self, ts: "TaskState", ws_long) -> int:
        f = 0
        if ts.actor:
            f |= F_ACTOR
        if ts.host_restrictions or ts.worker_restrictions \
                or ts.resource_restrictions:
            f |= F_RESTRICTED
        if not ts.run_spec:
            f |= F_NO_RUNSPEC
        if ts.exception_blame is not None:
            f |= F_BLAMED
        if ws_long is not None and ts in ws_long:
            f |= F_LONG_RUNNING
        return f

    def flush(self) -> None:
        """Resync every dirty row into the SoA (bulk, authoritative
        vector order) plus the prefixes/groups/workers they touch."""
        lib, h = self.lib, self.h
        if self._dirty_workers:
            for ws in self._dirty_workers:
                if ws.nidx < 0:
                    continue
                self._upsert_worker(ws)
            self._dirty_workers.clear()
        if not self._dirty:
            return
        tasks = [ts for ts in self._dirty if ts.nrow >= 0]
        self._dirty.clear()
        self._fresh.clear()
        if not tasks:
            return
        prefixes: set = set()
        groups: set = set()
        rows, state_a, flags_a, prefix_a, group_a = [], [], [], [], []
        nbytes_a, whowants_a, procon_a, occ_a = [], [], [], []
        dep_off, dep_flat, depw_flat = [0], [], []
        wtr_off, wtr_flat = [0], []
        who_off, who_flat = [0], []
        dept_off, dept_flat = [0], []
        for ts in tasks:
            rows.append(ts.nrow)
            state_a.append(STATE_IDX.get(ts.state, 0))
            pws = ts.processing_on
            flags_a.append(self._task_flags(
                ts, pws.long_running if pws is not None else None
            ))
            tp = ts.prefix
            if tp is not None:
                prefix_a.append(self._prefix_id(tp.name))
                prefixes.add(tp)
            else:
                prefix_a.append(-1)
            tg = ts.group
            if tg is not None:
                group_a.append(self._group_id(tg.name))
                groups.add(tg)
            else:
                group_a.append(-1)
            nbytes_a.append(ts.nbytes)
            whowants_a.append(len(ts.who_wants))
            procon_a.append(pws.nidx if pws is not None else -1)
            occ_a.append(
                pws.processing.get(ts, 0.0) if pws is not None else 0.0
            )
            waiting = ts.waiting_on
            for dts in ts.dependencies:
                dep_flat.append(dts.nrow)
                depw_flat.append(1 if dts in waiting else 0)
            dep_off.append(len(dep_flat))
            for dts in ts.waiters:
                wtr_flat.append(dts.nrow)
            wtr_off.append(len(wtr_flat))
            for hws in ts.who_has:
                who_flat.append(hws.nidx)
            who_off.append(len(who_flat))
            for dts in ts.dependents:
                dept_flat.append(dts.nrow)
            dept_off.append(len(dept_flat))
        for tp in prefixes:
            lib.eng_prefix_set(h, self._prefix_id(tp.name),
                               _f64(tp.duration_average))
        for tg in groups:
            dep_gids = _arr(_i32, [
                self._group_id(dg.name) for dg in tg.dependencies
            ])
            lib.eng_group_upsert(h, self._group_id(tg.name),
                                 tg.n_tasks, len(dep_gids), dep_gids)
        B = self._bufs
        if not B:
            for name, ct in (
                ("rows", _i32), ("state", _u8), ("flags", _u8),
                ("prefix", _i32), ("group", _i32), ("nbytes", _i64),
                ("whowants", _i32), ("procon", _i32), ("occ", _f64),
                ("dep_off", _i64), ("dep_flat", _i32), ("depw", _u8),
                ("wtr_off", _i64), ("wtr_flat", _i32),
                ("who_off", _i64), ("who_flat", _i32),
                ("dept_off", _i64), ("dept_flat", _i32),
            ):
                B[name] = _Buf(ct)
        lib.eng_task_sync_bulk(
            h, len(rows), B["rows"].fill(rows),
            B["state"].fill(state_a), B["flags"].fill(flags_a),
            B["prefix"].fill(prefix_a), B["group"].fill(group_a),
            B["nbytes"].fill(nbytes_a), B["whowants"].fill(whowants_a),
            B["procon"].fill(procon_a), B["occ"].fill(occ_a),
            B["dep_off"].fill(dep_off), B["dep_flat"].fill(dep_flat),
            B["depw"].fill(depw_flat),
            B["wtr_off"].fill(wtr_off), B["wtr_flat"].fill(wtr_flat),
            B["who_off"].fill(who_off), B["who_flat"].fill(who_flat),
            B["dept_off"].fill(dept_off), B["dept_flat"].fill(dept_flat),
        )

    def _upsert_worker(self, ws: "WorkerState") -> None:
        s = self.state
        self.lib.eng_worker_upsert(
            self.h, ws.nidx, WSTATUS_IDX.get(ws.status, 0), ws.nthreads,
            ws.nbytes, _f64(ws.occupancy), len(ws.processing),
            1 if ws.address in s.idle else 0,
            1 if ws in s.idle_task_count else 0,
            1 if ws in s.saturated else 0,
            ws.address.encode(),
        )

    def _params(self) -> None:
        s = self.state
        if self._pending:
            # the python-side incremental total is stale while segments
            # are deferred (its write-back runs at replay): the SoA
            # total is authoritative, read it back before pushing
            s._total_occupancy = self.lib.eng_total_occupancy(self.h)
        self.lib.eng_params(
            self.h, _f64(s.bandwidth), _f64(s.transfer_latency),
            _f64(s.UNKNOWN_TASK_DURATION), _f64(s.WORKER_SATURATION),
            _f64(s._total_occupancy), s.total_nthreads,
            len(s.workers), len(s.running),
            1 if s.placement is not None else 0,
        )

    # tape pool: a tape set is (cap, op, a, b, c, f1, f2).  Deferred
    # segments own their tape until sync() returns it to the pool, so
    # the next segment's native call can never overwrite pending rows.

    @staticmethod
    def _alloc_tape(cap: int):
        return (cap, (_i32 * cap)(), (_i32 * cap)(), (_i32 * cap)(),
                (_i32 * cap)(), (_f64 * cap)(), (_f64 * cap)())

    def _acquire_tape(self, n_events: int):
        # generous sizing keeps R_TAPE_FULL out of steady state: a
        # finished-task chain is a handful of rows plus flips
        cap = min(max(32 * n_events + 4096, 1 << 14), 1 << 22)
        pool = self._tape_pool
        if pool:
            tape = pool.pop()
            if tape[0] >= cap:
                return tape
            # too small for this flood: replace (steady-state flood
            # sizes converge, so the pool reaches zero-alloc reuse)
        return self._alloc_tape(cap)

    def _set_tape(self, tape) -> None:
        self.lib.eng_set_tape(
            self.h, tape[1], tape[2], tape[3], tape[4], tape[5],
            tape[6], tape[0],
        )

    # ------------------------------------------- deferred materialization

    def _defer_tape(self, tape, events, round_stim: str, stim: str,
                    now: float, client_msgs: dict,
                    worker_msgs: dict) -> None:
        """Park one completed native segment for later replay."""
        n = self.lib.eng_tape_len(self.h)
        self._pending.append(
            (tape, n, events, round_stim, stim, now, client_msgs,
             worker_msgs)
        )
        if len(self._pending) == 1:
            _NATIVE_PENDING.append(self)

    def sync(self) -> None:
        """Materialize python truth: replay every deferred segment in
        original order through the tape appliers.  This is the single
        hydration point — SoA-backed property reads, ledger/telemetry
        barriers, lazy message dicts and the mutation hooks all land
        here.  A probe that finds nothing pending is a hydration-cache
        hit."""
        if self._syncing:
            return
        pending = self._pending
        if not pending:
            self.hyd_cache_hits += 1
            return
        self._syncing = True
        s = self.state
        try:
            _NATIVE_PENDING.remove(self)
        except ValueError:  # pragma: no cover - invariant guard
            pass
        s.wall.push("engine.hydrate", pending[0][4])
        try:
            while pending:
                (tape, n, events, round_stim, _stim, now, cm,
                 wm) = pending.pop(0)
                self._applying = True
                try:
                    self._apply_tape_inner(
                        tape, n, events, round_stim, cm, wm, now
                    )
                finally:
                    self._applying = False
                self.hydrations += n
                self._tape_pool.append(tape)
        finally:
            # a replay exception leaves the remainder pending: restore
            # the registry invariant so reads keep forcing (and the
            # drives' degrade path can still detach cleanly)
            if pending and self not in _NATIVE_PENDING:
                _NATIVE_PENDING.append(self)
            s.wall.pop()
            self._syncing = False

    # ----------------------------------------------------- public drives

    def drive_finished_flood(
        self, finishes
    ) -> "tuple[dict, dict] | None":
        """The native twin of stimulus_tasks_finished_batch: same
        journal records, same wall phases, same histogram/trace
        observations, bit-identical outputs.  None = the flood is
        below the min-flood amortization floor and the caller must run
        the oracle."""
        s = self.state
        if not isinstance(finishes, (list, tuple)):
            finishes = list(finishes)
        if len(finishes) < self.min_flood:
            return None  # below the amortization floor: oracle flood
        # lazy message dicts: deferred segments append into these at
        # sync(), and any read of them forces the sync — callers (the
        # server's send_all, the parity tests' canonicalizers) iterate,
        # which materializes first
        client_msgs: dict = _LazyMsgs(self)
        worker_msgs: dict = _LazyMsgs(self)
        tr = s.trace
        t0 = s.clock()
        stim0 = finishes[0][2] if finishes else ""
        self.floods += 1
        s.wall.push("engine.drain", stim0)
        try:
            if tr.journal_enabled and finishes:
                # journal records are the engine's INPUTS: the same
                # single per-flood record the oracle arm writes (same
                # empty-flood guard) — streams stay bit-identical
                # across engines
                tr.record(
                    "tasks-finished-batch",
                    {"finishes": [
                        [key, worker, sid, dict(kwargs)]
                        for key, worker, sid, kwargs in finishes
                    ]},
                    stim0,
                )
            i, n = 0, len(finishes)
            while i < n:
                if s.queued or not self.active():
                    # queue-slot passes are per-event: the oracle owns
                    # the rest of the flood.  Materialize first — the
                    # oracle writes messages directly, and deferred
                    # rows must land ahead of them per destination.
                    if self._pending:
                        self.sync()
                    for j in range(i, n):
                        self._oracle_finished_event(
                            finishes[j], client_msgs, worker_msgs
                        )
                    break
                try:
                    i = self._segment_finished(
                        finishes, i, t0, client_msgs, worker_msgs
                    )
                except AssertionError:
                    raise  # DTPU_NATIVE_CHECK audit: must bite
                except Exception:
                    # a bridge bug must degrade, not wedge the
                    # scheduler: disable native and let the oracle
                    # finish the flood.  DETACH so a long-lived
                    # scheduler stops paying the SoA-maintenance hooks
                    # for a dead engine (reviewer-found).
                    logger.exception(
                        "native segment failed; disabling native engine"
                    )
                    if s.native is self:
                        s.native = None
                    self.detach()
            if s.plugins and self._pending:
                # tape-safe plugins (stealing, sim digest, diagnostics)
                # read their own structures between floods: their hooks
                # must have run by flood end.  Deferral across floods is
                # a pluginless (batch-plane/bench) property.
                try:
                    self.sync()
                except AssertionError:
                    raise
                except Exception:
                    logger.exception(
                        "flood-end sync failed; disabling native engine"
                    )
                    if s.native is self:
                        s.native = None
                    self.detach()
        finally:
            s.wall.pop()
        if finishes:
            s.hist_engine_batch.observe(n)
            s.hist_engine_pass.observe(s.clock() - t0)
            tr.emit("engine", "task-finished-batch", stim0, n=n)
        return client_msgs, worker_msgs

    def drive_recs_round(self, recommendations: dict, stimulus_id: str,
                         client_msgs: dict, worker_msgs: dict) -> None:
        """One recommendations round (the transitions /
        transitions_batch seam) through the native drain."""
        s = self.state
        if len(recommendations) == 1:
            # common scalar rounds (forgotten cascades, released pops):
            # when the single seed rec is not a compiled arm the native
            # call would escape immediately — skip its fixed cost
            key, finish = next(iter(recommendations.items()))
            ts0 = s.tasks.get(key)
            if ts0 is None or (ts0.state, finish) not in _COMPILED_SET:
                before = s.transition_counter
                s._transitions(dict(recommendations), client_msgs,
                               worker_msgs, stimulus_id)
                self.oracle_transitions += s.transition_counter - before
                return
        now = s.clock()
        rows, tgts = [], []
        for key, finish in recommendations.items():
            ts = s.tasks.get(key)
            tgt = STATE_IDX.get(finish)
            if ts is None or ts.nrow < 0 or tgt is None:
                # unknown key: the oracle's _transition silently
                # returns for it, producing nothing — drop it here;
                # unknown target names only arise from plugins, which
                # gate native off
                continue
            rows.append(ts.nrow)
            tgts.append(tgt)
        self.flush()
        self._params()
        tape = self._acquire_tape(len(rows))
        self._set_tape(tape)
        events: list = []
        s.wall.push("engine.native", stimulus_id)
        try:
            r = self.lib.eng_drain_recs(
                self.h, len(rows), _arr(_i32, rows), _arr(_i32, tgts)
            )
        finally:
            s.wall.pop()
        self.segments += 1
        # recs rounds stay eager (defer + immediate sync): their
        # callers consume plain message dicts, and rounds are small
        self._defer_tape(tape, events, stimulus_id, stimulus_id, now,
                         client_msgs, worker_msgs)
        self.sync()
        if r != R_DONE:
            self._oracle_continue(
                stimulus_id, client_msgs, worker_msgs,
                escaped=(r == R_ESCAPE),
            )
        if self.check:
            self._audit()

    # -------------------------------------------------- segment driving

    def _segment_finished(self, finishes, i: int, now: float,
                          client_msgs: dict, worker_msgs: dict) -> int:
        s = self.state
        seg = finishes[i:i + SEG_MAX]
        m = len(seg)
        l_task, l_slot, l_nbytes, l_dur, l_flags = [], [], [], [], []
        tasks_get = s.tasks.get
        workers_get = s.workers.get
        for key, worker, sid, kwargs in seg:
            ts = tasks_get(key)
            l_task.append(ts.nrow if ts is not None else -1)
            ws = workers_get(worker)
            l_slot.append(ws.nidx if ws is not None else -1)
            nb = kwargs.get("nbytes")
            l_nbytes.append(nb if nb is not None else -1)
            flags = 0
            dur = None
            startstops = kwargs.get("startstops")
            if startstops:
                for ss in startstops:
                    try:
                        if ss.get("action") == "compute":
                            if dur is None:
                                dur = ss["stop"] - ss["start"]
                            else:
                                flags |= 2  # >1 compute entries: oracle
                    except (AttributeError, KeyError, TypeError):
                        flags |= 2  # malformed startstops: oracle
                if dur is not None:
                    flags |= 1
            l_dur.append(dur if dur is not None else 0.0)
            l_flags.append(flags)
        E = self._ev_bufs
        if not E:
            E["task"] = _Buf(_i32); E["slot"] = _Buf(_i32)
            E["nbytes"] = _Buf(_i64); E["dur"] = _Buf(_f64)
            E["flags"] = _Buf(_u8)
        ev_task = E["task"].fill(l_task)
        ev_slot = E["slot"].fill(l_slot)
        ev_nbytes = E["nbytes"].fill(l_nbytes)
        ev_dur = E["dur"].fill(l_dur)
        ev_flags = E["flags"].fill(l_flags)
        self.flush()
        self._params()
        tape = self._acquire_tape(m)
        self._set_tape(tape)
        consumed = _i64(0)
        stim0 = seg[0][2] if seg else ""
        s.wall.push("engine.native", stim0)
        try:
            r = self.lib.eng_drain_finished(
                self.h, m, ev_task, ev_slot, ev_nbytes, ev_dur, ev_flags,
                ctypes.byref(consumed),
            )
        finally:
            s.wall.pop()
        self.segments += 1
        c = consumed.value
        self._defer_tape(tape, seg, "", stim0, now, client_msgs,
                         worker_msgs)
        if r == R_DONE:
            # the steady-state fast path: the segment stays DEFERRED —
            # no python object is touched until something reads one.
            # Check mode audits python-vs-SoA, so it materializes first
            # (i.e. DTPU_NATIVE_CHECK effectively disables deferral).
            if self.check:
                self._audit()
            return i + m
        # every escape hands control to the oracle: materialize first
        # so the oracle reads and writes fully-ordered python truth
        self.sync()
        if r == R_ESCAPE and self.lib.eng_escape_row(self.h) < 0:
            # event-shape escape: event c untouched natively
            self._oracle_finished_event(seg[c], client_msgs, worker_msgs)
            if self.check:
                self._audit()
            return i + c + 1
        # mid-chain escape or tape-full: event c-1's chain finishes in
        # the oracle (pending recs + the popped transition), then the
        # per-event queue-slots pass runs exactly like the oracle arm
        sid = seg[c - 1][2] if c > 0 else ""
        self._oracle_continue(
            sid, client_msgs, worker_msgs, escaped=(r == R_ESCAPE),
        )
        if s.queued:
            recs2 = s.stimulus_queue_slots_maybe_opened(sid)
            before = s.transition_counter
            s._transitions(recs2, client_msgs, worker_msgs, sid)
            self.oracle_transitions += s.transition_counter - before
        if self.check:
            self._audit()
        return i + c

    def _oracle_continue(self, stimulus_id: str, client_msgs: dict,
                         worker_msgs: dict, *, escaped: bool) -> None:
        """Hand the pending rec-dict (and, on escape, the popped
        transition) to the real engine.  This IS the oracle: from here
        to quiescence the chain runs the exact scalar path."""
        if self._pending:
            self.sync()
        s = self.state
        lib, h = self.lib, self.h
        npend = lib.eng_pending_recs(h, self._pr_rows, self._pr_tgts,
                                     self._pr_cap)
        while npend == self._pr_cap:
            self._pr_cap *= 2
            self._pr_rows = (_i32 * self._pr_cap)()
            self._pr_tgts = (_i32 * self._pr_cap)()
            npend = lib.eng_pending_recs(h, self._pr_rows, self._pr_tgts,
                                         self._pr_cap)
        recommendations: dict = {}
        rows = self._rows
        for j in range(npend):
            ts = rows[self._pr_rows[j]]
            if ts is not None:
                recommendations[ts.key] = STATE_NAMES[self._pr_tgts[j]]
        before = s.transition_counter
        if escaped:
            row = lib.eng_escape_row(h)
            ts = rows[row] if row >= 0 else None
            if ts is not None:
                finish = STATE_NAMES[lib.eng_escape_target(h)]
                r, c, w = s._transition(ts.key, finish, stimulus_id)
                _merge(client_msgs, c)
                _merge(worker_msgs, w)
                recommendations.update(r)
        s._transitions(recommendations, client_msgs, worker_msgs,
                       stimulus_id)
        self.oracle_transitions += s.transition_counter - before

    def _oracle_finished_event(self, event, client_msgs: dict,
                               worker_msgs: dict) -> None:
        """One whole task-finished event through the oracle — the exact
        per-event body of the batched arm (journal already written)."""
        if self._pending:
            self.sync()
        s = self.state
        key, worker, stimulus_id, kwargs = event
        before = s.transition_counter
        try:
            ts = s.tasks.get(key)
            if ts is None or ts.state in ("released", "forgotten", "erred"):
                worker_msgs.setdefault(worker, []).append({
                    "op": "free-keys",
                    "keys": [key],
                    "stimulus_id": stimulus_id,
                })
                return
            if ts.state == "memory":
                ws = s.workers.get(worker)
                if ws is not None and ws not in ts.who_has:
                    s.add_replica(ts, ws)
                return
            if ts.state != "processing":
                return
            ts.metadata = kwargs.pop("metadata", None) or ts.metadata
            recs, cmsgs, wmsgs = s._transition(
                key, "memory", stimulus_id, worker=worker, **kwargs
            )
            _merge(client_msgs, cmsgs)
            _merge(worker_msgs, wmsgs)
            s._transitions(recs, client_msgs, worker_msgs, stimulus_id)
            if s.queued:
                recs2 = s.stimulus_queue_slots_maybe_opened(stimulus_id)
                s._transitions(recs2, client_msgs, worker_msgs,
                               stimulus_id)
        except Exception:
            logger.exception(
                "batched task-finished event failed (%s from %s, "
                "stimulus %s)", key, worker, stimulus_id,
            )
        finally:
            self.oracle_transitions += s.transition_counter - before

    # ------------------------------------------------------ the applier

    def _apply_tape_inner(self, tape, n: int, events, round_stim: str,
                          client_msgs: dict, worker_msgs: dict,
                          now: float) -> None:
        """Replay one tape onto python truth (always via sync()).
        Mutation ORDER mirrors the oracle arms statement for statement;
        decisions and floats come from the tape.  ``now`` is the
        drive-hoisted clock stamp (ledger digests fold it verbatim, so
        a deferred replay must stamp what the eager path would have)."""
        s = self.state
        lib, h = self.lib, self.h
        if n:
            t_op = tape[1][:n]
            t_a = tape[2][:n]
            t_b = tape[3][:n]
            t_c = tape[4][:n]
            t_f1 = tape[5][:n]
            t_f2 = tape[6][:n]
            rows = self._rows
            wslots = self._wslots
            tr = s.trace
            tr_enabled = tr.enabled
            plugins = list(s.plugins.values()) if s.plugins else None
            dtrack = s.durability
            led = s.ledger
            led_on = led.enabled
            log = s._transition_log.append
            shadow_on = s.telemetry.enabled
            unknown = s.unknown_durations
            cur_stim = round_stim
            idle, idle_tc, saturated = s.idle, s.idle_task_count, s.saturated
            for j in range(n):
                op = t_op[j]
                if op == OP_WP:
                    ts = rows[t_a[j]]
                    ws = wslots[t_b[j]]
                    duration = t_f1[j]
                    comm = t_f2[j]
                    key = ts.key
                    if t_c[j] & 1:
                        unknown.setdefault(ts.prefix.name, set()).add(ts)
                    if shadow_on:
                        s.shadow_comm_cost(ts, ws, comm, "placement",
                                           cur_stim)
                    if led_on:
                        if ts.dependencies or ts.homed:
                            s.ledger_file_decision(
                                ts, ws, cur_stim, None, duration, comm,
                                now=now,
                            )
                        else:
                            prefix = ts.prefix
                            ts.ledger_row = led.file(
                                "placement", key,
                                prefix.name if prefix is not None else "",
                                ws.address, cur_stim, comm, comm, False,
                                0, 0, duration, "", "",
                                supersede=ts.ledger_row, now=now,
                            )
                    # graft-lint: allow[mirror-parity] every touched worker is mirror-marked in the segment write-back below
                    ws.processing[ts] = duration + comm
                    ts.processing_on = ws
                    ts.state = "processing"
                    if ts.actor:  # pragma: no cover - actor escapes
                        ws.actors.add(ts)
                    s._count_transition(ts, "waiting", "processing")
                    worker_msgs.setdefault(ws.address, []).append({
                        "op": "compute-task",
                        "key": key,
                        "priority": ts.priority,
                        "stimulus_id": cur_stim,
                        "who_has": {
                            dts.key: [w.address for w in dts.who_has]
                            for dts in ts.dependencies
                        },
                        "nbytes": {
                            dts.key: dts.nbytes for dts in ts.dependencies
                        },
                        "run_spec": wrap_opaque(ts.run_spec),
                        "duration": duration,
                        "resource_restrictions": ts.resource_restrictions,
                        "actor": ts.actor,
                        "annotations": ts.annotations or {},
                        "span_id": ts.group.span_id if ts.group else None,
                    })
                    s.transition_counter += 1
                    log((key, "waiting", "processing", {}, cur_stim,
                         now))
                    if tr_enabled:
                        t = tr._tick + 1
                        tr._tick = t
                        if not t % tr.sample:
                            tr.emit("transition", "processing", cur_stim,
                                    key=key, dest="waiting")
                    if plugins:
                        for plugin in plugins:
                            try:
                                plugin.transition(
                                    key, "waiting", "processing",
                                    stimulus_id=cur_stim,
                                )
                            except Exception:
                                logger.exception(
                                    "Plugin %r failed in transition",
                                    plugin,
                                )
                    if dtrack is not None:
                        # the worker's processing mirror mutated inline
                        # above (not through a marking helper): its
                        # order lists must ride the next delta snapshot
                        dtrack.mark_transition(ts)
                        dtrack.mark_worker(ws)
                elif op == OP_PM:
                    ts = rows[t_a[j]]
                    ws = wslots[t_b[j]]
                    key, worker, cur_stim, kwargs = events[t_c[j]]
                    ts.metadata = kwargs.pop("metadata", None) or ts.metadata
                    nbytes = kwargs.get("nbytes")
                    typename = kwargs.get("typename")
                    startstops = kwargs.get("startstops")
                    recs: dict = {}
                    realized = 0.0
                    if startstops:
                        prefix = ts.prefix
                        group = ts.group
                        for ss in startstops:
                            if ss.get("action") == "compute":
                                d = ss["stop"] - ss["start"]
                                realized += d
                                prefix.add_duration(d)
                                s.unknown_durations.pop(prefix.name, None)
                                group.duration += d
                                if not group.start:
                                    group.start = ss["start"]
                                group.stop = max(group.stop, ss["stop"])
                    lrow = ts.ledger_row
                    if lrow >= 0:
                        ts.ledger_row = -1
                        led.join_row(lrow, "memory", worker, now,
                                     realized, s.telemetry)
                    # _exit_processing_common (occupancy floats come
                    # from the native write-back at segment end)
                    ts.processing_on = None
                    ts.homed = False
                    # graft-lint: allow[mirror-parity] touched write-back marks the mirror row
                    ws.processing.pop(ts, None)
                    ws.long_running.discard(ts)
                    ws.executing.pop(ts, None)
                    if ts.resource_restrictions:
                        for rname, quantity in \
                                ts.resource_restrictions.items():
                            if rname in ws.used_resources:
                                ws.used_resources[rname] -= quantity
                    if nbytes is not None:
                        s.update_nbytes(ts, nbytes)
                    # inline add_replica (the native arm already proved
                    # ws not in who_has; mirror mark rides the touched
                    # write-back below, native marks are suppressed)
                    # graft-lint: allow[mirror-parity] touched write-back marks the mirror row
                    ws.nbytes += ts.get_nbytes()
                    # graft-lint: allow[mirror-parity] touched write-back marks the mirror row
                    ws.has_what[ts] = None
                    ts.who_has.add(ws)
                    if len(ts.who_has) == 2:
                        s.replicated_tasks.add(ts)
                    ts.state = "memory"
                    ts.type = typename
                    group = ts.group
                    if typename and group is not None:
                        group.types.add(typename)
                    if group is not None:
                        gs = group.states
                        gs["processing"] -= 1
                        gs["memory"] += 1
                    prefix = ts.prefix
                    if prefix is not None:
                        prefix.state_counts["memory"] += 1
                    for dts in list(ts.dependents):
                        if ts in dts.waiting_on:
                            dts.waiting_on.discard(ts)
                            if not dts.waiting_on and dts.state == "waiting":
                                recs[dts.key] = "processing"
                    for dts in ts.dependencies:
                        dts.waiters.discard(ts)
                        if not dts.waiters and not dts.who_wants:
                            recs[dts.key] = "released"
                    if not ts.waiters and not ts.who_wants:
                        recs[key] = "released"
                    else:
                        report = {
                            "op": "key-in-memory",
                            "key": key,
                            "type": ts.type,
                        }
                        for cs in ts.who_wants:
                            client_msgs.setdefault(
                                cs.client_key, []
                            ).append(report)
                    s.transition_counter += 1
                    log((key, "processing", "memory", recs, cur_stim,
                         now))
                    if tr_enabled:
                        t = tr._tick + 1
                        tr._tick = t
                        if not t % tr.sample:
                            tr.emit("transition", "memory", cur_stim,
                                    key=key, dest="processing")
                    if plugins:
                        for plugin in plugins:
                            try:
                                plugin.transition(
                                    key, "processing", "memory",
                                    stimulus_id=cur_stim, worker=worker,
                                    **kwargs,
                                )
                            except Exception:
                                logger.exception(
                                    "Plugin %r failed in transition",
                                    plugin,
                                )
                    if dtrack is not None:
                        # has_what/processing mutated inline (the
                        # add_replica/_exit_processing twins above)
                        dtrack.mark_transition(ts)
                        dtrack.mark_worker(ws)
                elif op == OP_MR:
                    ts = rows[t_a[j]]
                    key = ts.key
                    recs = {}
                    for dts in ts.waiters:
                        st = dts.state
                        if st in ("no-worker", "processing", "queued"):
                            recs[dts.key] = "waiting"
                        elif st == "waiting":
                            dts.waiting_on.add(ts)
                    freed = [hws.address for hws in ts.who_has]
                    for hws in list(ts.who_has):
                        s.remove_replica(ts, hws)
                    for addr in freed:
                        if addr in s.workers:
                            worker_msgs.setdefault(addr, []).append({
                                "op": "free-keys",
                                "keys": [key],
                                "stimulus_id": cur_stim,
                            })
                    ts.state = "released"
                    s._count_transition(ts, "memory", "released")
                    report = {"op": "lost-data", "key": key}
                    for cs in ts.who_wants:
                        client_msgs.setdefault(cs.client_key, []).append(
                            report
                        )
                    if not ts.run_spec:
                        recs[key] = "forgotten"
                    elif not ts.exception_blame and (
                            ts.who_wants or ts.waiters):
                        recs[key] = "waiting"
                    if recs.get(key) == "waiting":
                        for dts in ts.dependencies:
                            dts.waiters.add(ts)
                    else:
                        s._deregister_waiter(ts, recs)
                    s.transition_counter += 1
                    log((key, "memory", "released", recs, cur_stim,
                         now))
                    if tr_enabled:
                        t = tr._tick + 1
                        tr._tick = t
                        if not t % tr.sample:
                            tr.emit("transition", "released", cur_stim,
                                    key=key, dest="memory")
                    if plugins:
                        for plugin in plugins:
                            try:
                                plugin.transition(
                                    key, "memory", "released",
                                    stimulus_id=cur_stim,
                                )
                            except Exception:
                                logger.exception(
                                    "Plugin %r failed in transition",
                                    plugin,
                                )
                    if dtrack is not None:
                        dtrack.mark_transition(ts)
                elif op == OP_RW:
                    ts = rows[t_a[j]]
                    key = ts.key
                    recs = {}
                    for dts in ts.dependencies:
                        if not dts.who_has:
                            ts.waiting_on.add(dts)
                            if dts.state == "released":
                                recs[dts.key] = "waiting"
                            elif dts.state == "memory":
                                recs[dts.key] = "released"
                        dts.waiters.add(ts)
                    ts.state = "waiting"
                    s._count_transition(ts, "released", "waiting")
                    if not ts.waiting_on:
                        recs[key] = "processing"
                    s.transition_counter += 1
                    log((key, "released", "waiting", recs, cur_stim,
                         now))
                    if tr_enabled:
                        t = tr._tick + 1
                        tr._tick = t
                        if not t % tr.sample:
                            tr.emit("transition", "waiting", cur_stim,
                                    key=key, dest="released")
                    if plugins:
                        for plugin in plugins:
                            try:
                                plugin.transition(
                                    key, "released", "waiting",
                                    stimulus_id=cur_stim,
                                )
                            except Exception:
                                logger.exception(
                                    "Plugin %r failed in transition",
                                    plugin,
                                )
                    if dtrack is not None:
                        dtrack.mark_transition(ts)
                elif op == OP_FLIP:
                    ws = wslots[t_a[j]]
                    which = t_b[j]
                    if which == 0:
                        if t_c[j]:
                            idle[ws.address] = ws
                        else:
                            idle.pop(ws.address, None)
                    elif which == 1:
                        if t_c[j]:
                            idle_tc.add(ws)
                        else:
                            idle_tc.discard(ws)
                    else:
                        if t_c[j]:
                            saturated.add(ws)
                        else:
                            saturated.discard(ws)
                elif op == OP_FREEKEYS_STALE:
                    key, worker, cur_stim, _kw = events[t_a[j]]
                    worker_msgs.setdefault(worker, []).append({
                        "op": "free-keys",
                        "keys": [key],
                        "stimulus_id": cur_stim,
                    })
                elif op == OP_ADD_REPLICA:
                    ts = rows[t_a[j]]
                    ws = wslots[t_b[j]]
                    cur_stim = events[t_c[j]][2]
                    s.add_replica(ts, ws)
                elif op == OP_META:
                    # misrouted completion for a still-processing task:
                    # the oracle pops metadata BEFORE the arm's worker
                    # guard answers free-keys — replay both (the
                    # reporter's unaccounted copy must drop, or it
                    # outlives the task; see
                    # _transition_processing_memory's fence)
                    ts = rows[t_a[j]]
                    key, worker, cur_stim, kwargs = events[t_c[j]]
                    ts.metadata = kwargs.pop("metadata", None) \
                        or ts.metadata
                    worker_msgs.setdefault(worker, []).append({
                        "op": "free-keys",
                        "keys": [key],
                        "stimulus_id": cur_stim,
                    })
        if n == 0:
            return  # no arms ran: nothing touched, totals unchanged
        # occupancy write-back for every touched worker (python reads
        # occupancy only AFTER this — at escapes and between floods)
        k = lib.eng_touched(h, self._tw_slots, self._tw_occ, self._tw_cap)
        while k == self._tw_cap:
            self._tw_cap *= 2
            self._tw_slots = (_i32 * self._tw_cap)()
            self._tw_occ = (_f64 * self._tw_cap)()
            k = lib.eng_touched(h, self._tw_slots, self._tw_occ,
                                self._tw_cap)
        mirror = s.mirror
        wslots = self._wslots
        for j in range(k):
            ws = wslots[self._tw_slots[j]]
            if ws is None:
                continue
            # graft-lint: allow[mirror-parity] this IS the mirror-marked write-back
            ws.occupancy = self._tw_occ[j]
            if mirror is not None:
                mirror.mark(ws)
        s._total_occupancy = lib.eng_total_occupancy(h)

    # ---------------------------------------------------------- metrics

    def counters(self) -> dict:
        """The dtpu_engine_native_* metric families (http server)."""
        lib, h = self.lib, self.h
        out = {
            "transitions": int(lib.eng_transitions(h)),
            "escapes": int(lib.eng_escapes(h)),
            "oracle_transitions": self.oracle_transitions,
            "floods": self.floods,
            "segments": self.segments,
        }
        for i, name in enumerate(ESCAPE_WHY):
            c = int(lib.eng_escape_count(h, i))
            if c:
                out[f"escape_{name}"] = c
        out["hydrations"] = self.hydrations
        out["hydration_cache_hits"] = self.hyd_cache_hits
        rows_live = sum(1 for ts in self._rows if ts is not None)
        pend = sum(p[1] for p in self._pending)
        out["hydration_cache_rows"] = (
            rows_live - pend if rows_live > pend else 0
        )
        return out

    # ------------------------------------------------------------ audit

    def _audit(self) -> None:
        """DTPU_NATIVE_CHECK: assert the SoA agrees with python truth
        for every registered task and worker — the per-flood dual-run
        parity gate (cheap relative to check mode's purpose; property
        tests run full oracle dual-state parity on top)."""
        if self._pending:
            self.sync()
        s = self.state
        lib, h = self.lib, self.h
        out = self._scratch8
        for row, ts in enumerate(self._rows):
            if ts is None or ts in self._dirty:
                continue
            lib.eng_task_read(h, row, out)
            want = (
                1, STATE_IDX.get(ts.state, -9),
                ts.processing_on.nidx if ts.processing_on is not None
                else -1,
                len(ts.waiting_on), len(ts.waiters), len(ts.who_has),
                ts.nbytes, len(ts.who_wants),
            )
            got = tuple(out[:8])
            if got != want:
                raise AssertionError(
                    f"native SoA diverged for task {ts.key!r}: "
                    f"native={got} python={want}"
                )
        occ = _f64(0.0)
        for slot, ws in enumerate(self._wslots):
            if ws is None or ws in self._dirty_workers:
                continue
            lib.eng_worker_read(h, slot, ctypes.byref(occ), out)
            want_w = (
                1, WSTATUS_IDX.get(ws.status, -9), len(ws.processing),
                ws.nbytes,
                1 if ws.address in s.idle else 0,
                1 if ws in s.idle_task_count else 0,
                1 if ws in s.saturated else 0,
            )
            got_w = tuple(out[:7])
            if got_w != want_w or occ.value != ws.occupancy:
                raise AssertionError(
                    f"native SoA diverged for worker {ws.address}: "
                    f"native={got_w}/occ={occ.value} "
                    f"python={want_w}/occ={ws.occupancy}"
                )



