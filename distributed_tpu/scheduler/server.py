"""Scheduler server: the async shell around ``SchedulerState``.

Equivalent of the reference's ``Scheduler`` (scheduler.py:3453) =
``SchedulerState`` + ``ServerNode``: RPC handler table
(scheduler.py:3794), batched streams to every worker and client, and
``send_all`` routing the (client_msgs, worker_msgs) produced by the pure
state machine onto those streams.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, Iterable

from distributed_tpu import config
from distributed_tpu.comm.core import Comm
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.graph.spec import Key
from distributed_tpu.protocol.serialize import (
    OPAQUE_TYPES,
    Serialize,
    unwrap,
    wrap_opaque,
)
from distributed_tpu.rpc.batched import BatchedSend
from distributed_tpu.rpc.core import (
    PeriodicCallback,
    Server,
    Status,
    error_message,
)
from distributed_tpu.scheduler.state import SchedulerState, WorkerState
from distributed_tpu.utils.comm import gather_from_workers, scatter_to_workers
from distributed_tpu.utils.misc import seq_name, time

logger = logging.getLogger("distributed_tpu.scheduler")


def default_extensions() -> dict[str, Any]:
    """The DEFAULT_EXTENSIONS table (reference scheduler.py:178-193)."""
    from distributed_tpu.coordination.extensions import coordination_extensions
    from distributed_tpu.scheduler.amm import ActiveMemoryManagerExtension
    from distributed_tpu.scheduler.stealing import WorkStealing
    from distributed_tpu.shuffle.scheduler_ext import ShuffleSchedulerExtension

    return {
        "stealing": WorkStealing,
        "amm": ActiveMemoryManagerExtension,
        "shuffle": ShuffleSchedulerExtension,
        **coordination_extensions(),
    }


class _ThreadedSink:
    """Durability sink wrapper that runs every write on ONE executor
    thread: the event loop encodes snapshot/journal bytes and returns
    immediately; the fsync'd file IO (durability.FileSink) happens
    off-loop, in submission order — so a crash loses only a suffix of
    the write sequence, which is exactly the crash model the loader's
    epoch/watermark contract tolerates.  Reads are start-up-only
    (restore precedes the first write) and pass straight through."""

    def __init__(self, inner: Any):
        from concurrent.futures import ThreadPoolExecutor

        self.inner = inner
        # stats to bill journal bytes to, set after the manager exists:
        # segment serialization (digest stamping included) happens on
        # the writer thread, so the byte count is only known there
        self.stats: Any | None = None
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="dtpu-durability"
        )

    def _submit(self, fn: Any, *args: Any) -> None:
        def run() -> None:
            try:
                fn(*args)
            except Exception:
                logger.exception("durability sink write failed")

        self._pool.submit(run)

    def write_snapshot(self, epoch: int, blob: bytes) -> int:
        self._submit(self.inner.write_snapshot, epoch, blob)
        return len(blob)

    def append_journal(self, epoch: int, records: list) -> int:
        def run() -> None:
            try:
                n = self.inner.append_journal(epoch, records)
                if self.stats is not None:
                    self.stats.journal_bytes += n
            except Exception:
                logger.exception("durability sink write failed")

        self._pool.submit(run)
        return 0

    def drain(self) -> None:
        """Block until every queued write hit disk (graceful close)."""
        self._pool.shutdown(wait=True)

    def read_snapshot(self, epoch: int) -> bytes:
        return self.inner.read_snapshot(epoch)

    def read_journal(self, epoch: int) -> bytes:
        return self.inner.read_journal(epoch)

    def snapshot_epochs(self) -> list[int]:
        return self.inner.snapshot_epochs()

    def journal_epochs(self) -> list[int]:
        return self.inner.journal_epochs()


class Scheduler(Server):
    """Central control plane (reference scheduler.py:3453)."""

    default_port = 8786
    preload_config_prefix = "scheduler"

    def __init__(
        self,
        *,
        listen_addr: str | None = None,
        validate: bool | None = None,
        transition_counter_max: int | None = None,
        placement: Any | None = None,
        extensions: dict[str, Any] | None = None,
        worker_ttl: float | None = None,
        idle_timeout: float | None = None,
        http_port: int | None = 0,
        security: Any | None = None,
        **server_kwargs: Any,
    ):
        self._http_port = http_port
        self.http_server = None
        self.monitor = None
        self._listen_addr = listen_addr
        self.security = security
        if security is not None:
            server_kwargs.setdefault(
                "connection_args", security.get_connection_args("scheduler")
            )
        if placement is None and config.get("scheduler.jax.enabled"):
            from distributed_tpu.scheduler.jax_placement import JaxPlacement

            placement = JaxPlacement()
        elif placement is False:
            placement = None
        self.state = SchedulerState(
            validate=validate,
            transition_counter_max=transition_counter_max,
            placement=placement,
        )
        self.generation = 0
        # address -> BatchedSend for workers; client key -> BatchedSend
        self.stream_comms: dict[str, BatchedSend] = {}
        self.client_comms: dict[str, BatchedSend] = {}
        self.worker_ttl = (
            worker_ttl
            if worker_ttl is not None
            else config.parse_timedelta(config.get("scheduler.worker-ttl")) or 0
        )
        self.idle_timeout = (
            idle_timeout
            if idle_timeout is not None
            else config.parse_timedelta(config.get("scheduler.idle-timeout"))
        )
        self.idle_since: float | None = time()
        self._last_worker_seen: dict[str, float] = {}

        handlers = {
            "register-worker": self.add_worker,
            "register-client": self.add_client,
            "heartbeat_worker": self.heartbeat_worker,
            "gather": self.gather,
            "scatter": self.scatter,
            "cancel": self.stimulus_cancel,
            "retry": self.stimulus_retry,
            "who_has": self.get_who_has,
            "has_what": self.get_has_what,
            "ncores": self.get_ncores,
            "nbytes": self.get_nbytes,
            "processing": self.get_processing,
            "identity": self.identity,
            "broadcast": self.broadcast,
            "run_function": self.run_function_on_scheduler,
            "restart": self.restart,
            "get_logs": self.get_events_handler,
            "log_event": self.log_event_handler,
            "events": self.get_events_handler,
            "missing_workers": self.get_missing_workers,
            "retire_workers": self.retire_workers,
            "adaptive_target": self.adaptive_target,
            "remove_worker": self.remove_worker_handler,
            "rebalance": self.rebalance,
            "replicate": self.replicate,
            "register_scheduler_plugin": self.register_scheduler_plugin,
            "unregister_scheduler_plugin": self.unregister_scheduler_plugin,
            "register_worker_plugin": self.register_worker_plugin,
            "register_nanny_plugin": self.register_nanny_plugin,
            "unregister_nanny_plugin": self.unregister_nanny_plugin,
            "unregister_worker_plugin": self.unregister_worker_plugin,
            "get_cluster_state": self.get_cluster_state,
            "get_telemetry": self.get_telemetry,
            "get_ledger": self.get_ledger,
            "get_census": self.get_census,
            "get_runspec": self.get_runspec,
            "versions": self.versions,
            "worker_versions": self.worker_versions,
            "benchmark_hardware": self.benchmark_hardware,
            "performance_report_html": self.performance_report_html,
        }
        stream_handlers = {
            # from workers
            "task-finished": self.handle_task_finished,
            "task-erred": self.handle_task_erred,
            "release-worker-data": self.handle_release_data,
            "add-keys": self.handle_add_keys,
            "long-running": self.handle_long_running,
            "reschedule": self.handle_reschedule,
            "missing-data": self.handle_missing_data,
            "request-refresh-who-has": self.handle_request_refresh_who_has,
            "log-event": self.handle_worker_log_event,
            "worker-status-change": self.handle_worker_status_change,
            # from clients
            "update-graph": self.update_graph,
            "client-desires-keys": self.handle_client_desires_keys,
            "client-releases-keys": self.handle_client_releases_keys,
            "heartbeat-client": self.handle_heartbeat_client,
            "close-client": self.handle_close_client,
        }
        # deserialize=False: the scheduler NEVER unpickles user payloads
        # (run_specs, scattered data, results, exceptions) — they pass
        # through as opaque Serialized frames, so the scheduler process
        # needs no user code and pays no pickle cost on the hot path
        # (reference scheduler.py:3453 Server(deserialize=False)).
        # Handlers that genuinely consume content (run_function, plugin
        # registration) deserialize explicitly via unwrap().
        server_kwargs.setdefault("deserialize", False)
        super().__init__(
            handlers=handlers, stream_handlers=stream_handlers, **server_kwargs
        )
        # one causal timeline for the role: the server's flight recorder
        # IS the state machine's (ingress/egress hops land next to the
        # engine's transition events; /trace and get_trace serve both)
        self.trace = self.state.trace
        self._close_begun = False
        self.extensions: dict[str, Any] = {}
        if extensions is None:
            extensions = default_extensions()
        for name, ext_cls in extensions.items():
            self.extensions[name] = ext_cls(self)
        self.state.extensions = self.extensions
        from distributed_tpu.diagnostics.spans import SpansSchedulerExtension
        from distributed_tpu.diagnostics.task_stream import TaskStreamPlugin

        self.task_stream = TaskStreamPlugin(self)
        from distributed_tpu.diagnostics.group_timing import GroupTimingPlugin

        self.group_timing = GroupTimingPlugin(self)
        self.handlers["get_group_timing"] = (
            lambda **kw: self.group_timing.collect()
        )
        self.spans = SpansSchedulerExtension(self)
        self._topic_subscribers: dict[str, set[str]] = {}
        # eventstream refcounting: total starts minus stops, plus a
        # per-client breakdown so a consumer that crashes without
        # calling eventstream_stop releases its references when its
        # comm closes (remove-client path) instead of pinning the
        # per-completion plugin forever
        self._eventstream_refs = 0
        self._eventstream_clients: dict[str, int] = {}
        self._eventstream_anon = 0  # starts not tied to any client
        self.state.events_subscriber_hook = self._fan_out_event
        self.worker_plugins: dict[str, Any] = {}  # shipped to joining workers
        self._nanny_plugins: dict[str, Any] = {}  # shipped to joining nannies
        self.handlers["get_task_stream"] = self.get_task_stream
        from distributed_tpu.diagnostics.memory_sampler import (
            memory_sample_handler,
        )

        self.handlers["memory_sample"] = (
            lambda **kw: memory_sample_handler(self, **kw)
        )
        self.handlers["get_profile"] = self.get_profile
        self.handlers["eventstream_start"] = self.eventstream_start
        self.handlers["eventstream_stop"] = self.eventstream_stop
        self.handlers["get_computations"] = self.get_computations
        self.stream_handlers["subscribe-topic"] = self.subscribe_topic
        self.stream_handlers["unsubscribe-topic"] = self.unsubscribe_topic
        self.stream_handlers["log-event-client"] = self.handle_client_log_event
        # same-op floods within one stream payload fold into a single
        # batched state-machine pass (rpc/core.py handle_stream;
        # docs/batching.md) — the per-message handlers above remain the
        # oracle path for lone messages and direct calls
        self.stream_batch_handlers["task-finished"] = self.handle_tasks_finished
        self.stream_batch_handlers["task-erred"] = self.handle_tasks_erred
        self.stream_batch_handlers["release-worker-data"] = (
            self.handle_release_data_batch
        )
        # send_all output is staged per stream payload and flushed once
        # at the payload boundary (handle_stream calls
        # stream_payload_flush) with per-destination coalescing; the
        # call_soon backstop covers non-stream callers (RPC handlers,
        # periodic callbacks) at zero added latency — BatchedSend only
        # writes from its background task anyway
        self._pending_client_msgs: dict[str, list] = {}
        self._pending_worker_msgs: dict[str, list] = {}
        self._pending_flush_scheduled = False
        self._loop: asyncio.AbstractEventLoop | None = None  # set at start
        # control-plane self-profiling (diagnostics/selfprofile.py):
        # wired at start_unsafe when scheduler.profile.enabled — the
        # sampler watches the loop + planner threads, the watchdog
        # catches loop stalls with a traceback
        self.cp_profiler: Any | None = None
        self.watchdog: Any | None = None
        # scheduler durability (scheduler/durability.py;
        # docs/durability.md): armed at start_unsafe when
        # scheduler.durability.directory is set — restore from
        # snapshot + journal tail, then capture snapshots/segments
        self.durability: Any | None = None
        # re-registration window after a restore: restored worker
        # addresses still expected back, and the absolute (monotonic)
        # deadline after which the missing ones are removed and their
        # tasks rescheduled
        self._recovery: dict | None = None

    # ----------------------------------------------------------- lifecycle

    async def start_unsafe(self) -> "Scheduler":
        from distributed_tpu import native

        self._loop = asyncio.get_running_loop()
        # async prebuild so the first flood never pays the g++ compile
        # on the event loop; once the library lands, attach the native
        # transition engine (state init could not — load_nowait returns
        # None until the build exists)
        loop = self._loop

        def _native_ready() -> None:  # runs in the build thread
            # same gate as SchedulerState.__init__: a validate=True
            # scheduler must not pay SoA maintenance for an engine
            # active() will never admit
            if (config.get("scheduler.native-engine.enabled")
                    and not self.state.validate):
                loop.call_soon_threadsafe(self.state.attach_native)

        native.prebuild_async(on_ready=_native_ready)
        # durability restore + capture arm BEFORE the listener exists:
        # nothing can register or submit against a half-restored state
        if config.get("scheduler.durability.directory"):
            self._durability_start()
        addr = self._listen_addr or "tcp://127.0.0.1:0"
        listen_args = (
            self.security.get_listen_args("scheduler")
            if self.security is not None else {}
        )
        await self.listen(addr, **listen_args)
        # observability: SystemMonitor sampling + HTTP routes
        from distributed_tpu.diagnostics.system_monitor import SystemMonitor
        from distributed_tpu.http.server import HTTPServer, scheduler_metrics

        self.monitor = SystemMonitor(
            maxlen=int(config.get("admin.system-monitor.log-length"))
        )
        self.periodic_callbacks["monitor"] = PeriodicCallback(
            self.monitor.update,
            config.parse_timedelta(
                config.get("admin.system-monitor.interval")
            ),
        )
        # control-plane self-profiling (diagnostics/selfprofile.py;
        # docs/observability.md "Self-profiling"): sample the event-loop
        # thread + the jax-placement planner thread at a low rate, and
        # watch the loop for stalls.  Wired BEFORE the HTTP server so
        # /profile serves real trees from its first request.
        if config.get("scheduler.profile.enabled", True):
            from distributed_tpu.diagnostics.selfprofile import (
                ControlPlaneProfiler,
                LoopWatchdog,
            )

            loop_ident = threading.get_ident()  # we run ON the loop here
            placement = self.state.placement

            def _cp_idents() -> list[int]:
                ids = [loop_ident]
                if placement is not None:
                    pid = getattr(placement, "planner_ident", None)
                    pid = pid() if callable(pid) else None
                    if pid is not None:
                        ids.append(pid)
                return ids

            self.cp_profiler = ControlPlaneProfiler(
                idents=_cp_idents, wall=self.state.wall
            )
            self.cp_profiler.start()
            self.watchdog = LoopWatchdog(
                trace=self.trace, wall=self.state.wall
            )
            self.periodic_callbacks["loop-watchdog"] = PeriodicCallback(
                self.watchdog.tick, self.watchdog.interval
            )
            self.watchdog.start(loop_ident)
        # retention sentinel over the state census (diagnostics/
        # census.py; docs/observability.md "State census & retention"):
        # a low-cadence tick folds per-family growth slopes and runs
        # the census-vs-empty diff on every quiesce edge.  Fresh
        # findings get their bounded gc.get_referrers holder sample
        # OFF the loop.  The durability dirty sets are exempt from
        # LIVE quiesce diffs only — they drain on snapshot cadence
        # (the sim/bench teardown gates snapshot first and exempt
        # nothing).
        if config.get("scheduler.census.enabled", True):
            from distributed_tpu.diagnostics.census import RetentionSentinel

            census = self.state.census
            census.sentinel = sentinel = RetentionSentinel(
                census, trace=self.trace,
                quiesce_allow=(
                    "durability.dirty-tasks", "durability.removed-tasks",
                    "durability.dirty-workers", "durability.removed-workers",
                ),
            )

            def _enriched(fut: Any) -> None:
                exc = fut.exception()
                if exc is not None:
                    logger.warning(
                        "census finding enrichment failed: %r", exc
                    )

            def _census_tick() -> None:
                fresh = sentinel.tick()
                if fresh:
                    asyncio.get_running_loop().run_in_executor(
                        None, census.enrich_findings, fresh
                    ).add_done_callback(_enriched)

            self.periodic_callbacks["census-sentinel"] = PeriodicCallback(
                _census_tick,
                config.parse_timedelta(
                    config.get("scheduler.census.interval")
                ),
            )
        if self._http_port is not None:
            from distributed_tpu.diagnostics.selfprofile import profile_jsonl
            from distributed_tpu.http.dashboard import json_api_routes

            from distributed_tpu.tracing import to_jsonl

            routes: dict = {
                    "/health": lambda: "ok",
                    "/info": self.identity,
                    "/metrics": lambda: scheduler_metrics(self),
                    "/json/counts.json": self._counts_json,
                    "/sysmon": lambda: self.monitor.range_query(),
                    # flight-recorder tail as JSON Lines
                    # (docs/observability.md; schema-versioned records)
                    "/trace": lambda: (
                        to_jsonl(self.trace.tail()),
                        "application/x-ndjson",
                    ),
                    # fleet telemetry snapshot: per-link EWMAs +
                    # t-digest quantiles, prefix priors, heartbeat
                    # RTTs, divergence summary (telemetry.py)
                    "/telemetry": lambda: (
                        to_jsonl(self.state.telemetry.snapshot()),
                        "application/x-ndjson",
                    ),
                    # control-plane self-profile: wall budget + sampled
                    # loop/planner trees + recent stalls as JSONL
                    # (docs/observability.md "Self-profiling")
                    "/profile": lambda: (
                        profile_jsonl(
                            "scheduler", self.cp_profiler,
                            self.state.wall, self.watchdog,
                        ),
                        "application/x-ndjson",
                    ),
                    # decision–outcome ledger: summary head + resident
                    # row tail as JSONL (ledger.py;
                    # docs/observability.md "Decision ledger")
                    "/ledger": lambda: (
                        to_jsonl(self.state.ledger.snapshot()),
                        "application/x-ndjson",
                    ),
                    # state census: per-family resident counts + recent
                    # findings as JSONL (cheap families; the get_census
                    # RPC adds the O(n) walk families on demand —
                    # diagnostics/census.py, docs/observability.md)
                    "/census": lambda: (
                        to_jsonl(self.state.census.snapshot()),
                        "application/x-ndjson",
                    ),
                    **json_api_routes(self),
            }
            # route index at "/": observability discoverability — one
            # GET lists every route this role serves (/metrics, /trace,
            # /telemetry, /profile, /ledger, ...)
            routes["/"] = lambda: {
                "role": "scheduler",
                "id": self.id,
                "routes": sorted(r for r in routes if r != "/"),
            }
            self.http_server = HTTPServer(routes, port=self._http_port)
            await self.http_server.start()
        if self.worker_ttl:
            self.periodic_callbacks["worker-ttl"] = PeriodicCallback(
                self.check_worker_ttl, max(self.worker_ttl / 4, 0.25)
            )
        no_workers_timeout = config.parse_timedelta(
            config.get("scheduler.no-workers-timeout") or "0"
        )
        if no_workers_timeout:
            def _check_no_workers() -> None:
                cm, wm = self.state.stimulus_no_workers_timeout(
                    no_workers_timeout, seq_name("no-workers-timeout")
                )
                self.send_all(cm, wm)

            self.periodic_callbacks["no-workers-timeout"] = PeriodicCallback(
                _check_no_workers, max(no_workers_timeout / 4, 0.25)
            )
        if self.idle_timeout:
            self.periodic_callbacks["idle-timeout"] = PeriodicCallback(
                self.check_idle, max(self.idle_timeout / 4, 0.25)
            )
        if self.durability is not None:
            snap_iv = config.parse_timedelta(
                config.get("scheduler.durability.snapshot-interval")
            )
            flush_iv = config.parse_timedelta(
                config.get("scheduler.durability.flush-interval")
            )
            self.periodic_callbacks["durability-snapshot"] = PeriodicCallback(
                self._durability_snapshot, snap_iv
            )
            self.periodic_callbacks["durability-flush"] = PeriodicCallback(
                self._durability_flush, flush_iv
            )
            if self._recovery is not None:
                grace = self._recovery["grace"]
                self.periodic_callbacks["recovery-grace"] = PeriodicCallback(
                    self._check_recovery_grace, max(grace / 4, 0.05)
                )
        self.start_periodic_callbacks()
        logger.info("scheduler listening at %s", self.address)
        return self

    async def close(self, timeout: float | None = None) -> None:
        # status may already read "closing" (deploy layers flag shutdown
        # before retiring workers so per-departure recovery stands down);
        # only an actually-started close short-circuits
        if self.status == Status.closed or self._close_begun:
            await self.finished()
            return
        # the flag flips BEFORE the first await below: a concurrent
        # close() arriving while a dtpu_teardown hook runs must not
        # re-enter the body and double-close comms/extensions
        self._close_begun = True
        # dtpu_teardown hooks run against a LIVE cluster (same ordering
        # as the CLI flag path); idempotent backstop in Server.close
        await self._teardown_config_preloads()
        self.status = Status.closing
        logger.info("closing scheduler %s", self.id)
        for pc in self.periodic_callbacks.values():
            pc.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.cp_profiler is not None:
            self.cp_profiler.stop()  # flushes the in-flight cycle
        placement = self.state.placement
        if placement is not None and hasattr(placement, "close"):
            placement.close()
        for ext in self.extensions.values():
            close = getattr(ext, "close", None)
            if close is not None:
                try:
                    res = close()
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    logger.exception("extension close failed")
        self.stream_payload_flush()  # staged sends must not die buffered
        # tell workers to shut down
        for addr, bs in list(self.stream_comms.items()):
            try:
                bs.send({"op": "close-worker"})
            except CommClosedError:
                pass
            await bs.close(timeout=0.5)
        for client, bs in list(self.client_comms.items()):
            await bs.close(timeout=0.5)
        if self.http_server is not None:
            await self.http_server.stop()
        if self.durability is not None:
            # graceful close ends the epoch cleanly: one final snapshot
            # + segment flush, then drain the write thread so the image
            # on disk is complete before the process exits
            try:
                self.durability.snapshot()
                self.durability.flush_journal()
                self.durability.sink.drain()
            except Exception:
                logger.exception("final durability snapshot failed")
        await super().close()

    # ----------------------------------------------------------- durability

    def _durability_start(self) -> None:
        """Restore from the durable image (when one exists) and arm
        capture: the recovery sequence of docs/durability.md.  Runs
        synchronously before the listener starts — a worker cannot
        register, and a client cannot submit, against a half-restored
        state."""
        from distributed_tpu.diagnostics.flight_recorder import (
            replay_stimulus_trace,
        )
        from distributed_tpu.scheduler import durability as dur

        directory = config.get("scheduler.durability.directory")
        sink = dur.FileSink(directory)
        state = self.state
        next_epoch = 0
        restore_info = None
        t0 = time()
        if sink.snapshot_epochs():
            folded, tail, info = dur.DurabilityManager.load(sink)
            dur.restore_state(state, folded)
            want = info.get("state_digest")
            if want:
                got = dur.state_digest(state)
                if got != want:
                    raise dur.SnapshotCorruptError(
                        f"restored state digest {got} != snapshot's "
                        f"{want}: refusing to continue from a divergent "
                        "state"
                    )
            # per-worker extension structures the live add_worker path
            # would have built, then the recorded cross-payload steal
            # truth (in-flight confirm windows, stealable levels)
            steal = self.extensions.get("stealing")
            if steal is not None:
                for ws in state.workers.values():
                    if ws.address not in steal.stealable:
                        steal.add_worker_state(ws)
                dur.restore_stealing(steal, folded.get("ext") or None)
            replay_stimulus_trace(state, tail, verify_digests=False)
            restore_info = info
            next_epoch = int(info["epoch"]) + 1
            grace = config.parse_timedelta(
                config.get("scheduler.durability.grace")
            )
            awaiting = {
                ws.address for ws in state.workers.values()
            }
            self._recovery = {
                "awaiting": awaiting,
                "deadline": time() + grace,
                "grace": grace,
                "restored_workers": len(awaiting),
            }
            logger.info(
                "restored scheduler state from %s: epoch %s (+%s deltas), "
                "%d tail records, %d tasks, %d workers awaiting "
                "re-registration (grace %.1fs)",
                directory, info["epoch"], info["deltas"], len(tail),
                len(state.tasks), len(awaiting), grace,
            )
        tsink = _ThreadedSink(sink)
        mgr = dur.DurabilityManager(state, tsink)
        tsink.stats = mgr.stats
        mgr.epoch = next_epoch
        mgr.attach()
        self.durability = mgr
        if restore_info is not None:
            st = mgr.stats
            st.replay_records = int(restore_info["tail_records"])
            st.torn_records = int(restore_info["torn_records"])
            st.restore_seconds = time() - t0

    def _durability_snapshot(self) -> None:
        mgr = self.durability
        if mgr is None:
            return
        # encode on-loop (O(changed rows) between payloads), write
        # off-loop through the single-thread sink
        info = mgr.snapshot()
        self.trace.emit(
            "durability", "snapshot", f"epoch-{info['epoch']}",
            n=info["task_rows"], dest="sink",
        )

    def _durability_flush(self) -> None:
        if self.durability is not None:
            self.durability.flush_journal()

    async def _check_recovery_grace(self) -> None:
        """Bounded re-registration window: when the grace expires,
        restored workers that never came back are removed through the
        engine — their tasks reschedule exactly like a live departure."""
        rec = self._recovery
        if rec is None:
            return
        if not rec["awaiting"]:
            self._finish_recovery()
            return
        if time() < rec["deadline"]:
            return
        missing = sorted(rec["awaiting"])
        logger.warning(
            "recovery grace expired: removing %d workers that never "
            "re-registered: %s", len(missing), missing[:5],
        )
        for address in missing:
            if address not in rec["awaiting"]:
                # re-registered while an earlier removal awaited: the
                # handshake discarded it — must not strip a live worker
                continue
            rec["awaiting"].discard(address)
            try:
                await self.remove_worker(address, "recovery-grace-expired")
            except Exception:
                logger.exception(
                    "grace-expiry removal failed for %s", address
                )
        self._finish_recovery()

    def _finish_recovery(self) -> None:
        self._recovery = None
        pc = self.periodic_callbacks.pop("recovery-grace", None)
        if pc is not None:
            pc.stop()

    # ------------------------------------------------------------ messaging

    def send_all(self, client_msgs: dict, worker_msgs: dict) -> None:
        """Route state-machine output onto the batched streams
        (reference scheduler.py:6067).

        Messages are STAGED, not written: everything produced while one
        stream payload is being processed (often a whole task-finished
        flood) flushes in a single pass at the payload boundary, where
        per-destination runs coalesce (compute-task batches, merged
        free-keys).  Order per destination is strictly preserved."""
        for client, msgs in client_msgs.items():
            self._pending_client_msgs.setdefault(client, []).extend(msgs)
        for worker, msgs in worker_msgs.items():
            self._pending_worker_msgs.setdefault(worker, []).extend(msgs)
        if self._pending_flush_scheduled:
            return
        if not (self._pending_client_msgs or self._pending_worker_msgs):
            return
        self._pending_flush_scheduled = True
        loop = self._loop
        if loop is None or loop.is_closed():
            # not started / no running loop (sync tests, teardown):
            # write through now
            self._pending_flush_scheduled = False
            self.stream_payload_flush()
        else:
            loop.call_soon(self.stream_payload_flush)

    def stream_payload_flush(self) -> None:
        """Write staged messages to the batched streams — called by
        ``handle_stream`` at every payload boundary, by the ``call_soon``
        backstop one tick after a non-stream send, and synchronously
        before anything that writes to the same streams out-of-band
        (``report``, ``restart``, ``close``) so ordering never inverts."""
        self._pending_flush_scheduled = False
        if not (self._pending_client_msgs or self._pending_worker_msgs):
            return
        client_msgs, self._pending_client_msgs = self._pending_client_msgs, {}
        worker_msgs, self._pending_worker_msgs = self._pending_worker_msgs, {}
        tr = self.trace
        wall = self.state.wall
        wall.push("egress.flush")
        try:
            self._flush_payloads(client_msgs, worker_msgs, tr)
        finally:
            wall.pop()

    def _flush_payloads(self, client_msgs: dict, worker_msgs: dict,
                        tr: Any) -> None:
        for client, msgs in client_msgs.items():
            bs = self.client_comms.get(client)
            if bs is None:
                continue
            tr.emit("egress", "client-report", "", n=len(msgs), dest=client)
            try:
                bs.send(*[self._wrap_payload(m) for m in msgs])
            except CommClosedError:
                logger.info("lost connection to client %s", client)
        for worker, msgs in worker_msgs.items():
            bs = self.stream_comms.get(worker)
            if bs is None:
                continue
            coalesced = _coalesce_worker_stream_msgs(msgs)
            # egress hop: one event per coalesced envelope, stamped with
            # the envelope's (first) stimulus id so a flood's
            # compute-tasks fan-out joins the engine pass that produced
            # it.  Envelope fold size feeds dtpu_egress_* regardless of
            # trace.enabled — the histogram is a documented /metrics
            # family, not trace output.
            hist = self.state.hist_egress
            for m in coalesced:
                op = m.get("op", "")
                if op == "compute-tasks":
                    n = len(m["tasks"])
                    stim = m["tasks"][0].get("stimulus_id", "")
                else:
                    keys = m.get("keys")
                    n = (
                        len(keys)
                        if isinstance(keys, (list, tuple))
                        else 1
                    )
                    stim = m.get("stimulus_id", "")
                hist.observe(n)
                tr.emit("egress", op, stim, n=n, dest=worker)
            try:
                bs.send(*[self._wrap_payload(m) for m in coalesced])
            except CommClosedError:
                logger.info("lost connection to worker %s", worker)
                self._ongoing_background_tasks.call_soon(
                    self.remove_worker, worker, "comm-closed"
                )

    @staticmethod
    def _wrap_payload(msg: dict) -> dict:
        """Ensure non-msgpackable payloads cross the wire pickled.

        Exceptions from workers are already opaque wrappers (this server
        never deserialized them) and pass through; scheduler-raised ones
        (KilledWorker, ...) are raw objects and get wrapped here."""
        for field in ("exception", "traceback"):
            v = msg.get(field)
            if v is not None and not isinstance(v, (*OPAQUE_TYPES, str, bytes)):
                msg = dict(msg)
                msg[field] = Serialize(v)
        return msg

    def report(self, msg: dict, *, client: str | None = None) -> None:
        """Send a message to one or all clients."""
        # report() writes the stream directly: flush staged sends first
        # so a direct message can never overtake state-machine output
        self.stream_payload_flush()
        if client is not None:
            targets = [client] if client in self.client_comms else []
        else:
            targets = list(self.client_comms)
        for c in targets:
            try:
                self.client_comms[c].send(self._wrap_payload(msg))
            except CommClosedError:
                pass

    # -------------------------------------------------------------- workers

    async def add_worker(self, comm: Comm, **kwargs: Any) -> Any:
        """Worker registration handshake; the comm becomes the dual stream
        (reference scheduler.py:4308)."""
        address = kwargs["address"]
        existing = self.state.workers.get(address)
        reregister = False
        if existing is not None:
            server_id = kwargs.get("server_id")
            stream = self.stream_comms.get(address)
            if server_id is not None and existing.server_id == server_id:
                # the SAME worker process registering again: a restored
                # scheduler's re-registration window, or a retried
                # handshake whose first reply was lost.  Idempotent by
                # server_id — the state row is reused, so replicas and
                # occupancy are never double-counted; only the stream
                # is replaced.
                reregister = True
                if stream is not None:
                    self.stream_comms.pop(address, None)
                    stream.abort()
            elif stream is None or stream.closed():
                # a NEW process took the address and the old one's
                # stream is already dead: retire the stale row first
                await self.remove_worker(address, "superseded-by-new-registration")
            else:
                await comm.write({"status": "error", "message": "worker already exists"})
                return Status.dont_reply
        if reregister:
            ws = existing
        else:
            ws = self.state.add_worker_state(
                address,
                nthreads=kwargs.get("nthreads", 1),
                memory_limit=kwargs.get("memory_limit", 0),
                name=kwargs.get("name"),
                resources=kwargs.get("resources"),
                server_id=kwargs.get("server_id"),
            )
        if kwargs.get("versions"):
            ws.extra["versions"] = kwargs["versions"]
        if kwargs.get("jax_devices") is not None:
            # global mesh device indices this worker's process owns —
            # the device-plane shuffle pins partitions to their owners
            ws.extra["jax_devices"] = list(kwargs["jax_devices"])
        if kwargs.get("nanny"):
            ws.extra["nanny"] = kwargs["nanny"]
            # late-joining nanny gets the already-registered nanny plugins
            for pname, pblob in self._nanny_plugins.items():
                self._ongoing_background_tasks.call_soon(
                    self._push_nanny_plugin, kwargs["nanny"], pname, pblob
                )
        self._last_worker_seen[address] = time()
        logger.info("register worker %s (%d threads)", address, ws.nthreads)

        # publish the (unstarted, buffering) BatchedSend before any await so
        # concurrent send_all never drops messages for this worker, but only
        # start its flush loop AFTER the registration reply is on the wire —
        # otherwise a flushed batch could precede the handshake response
        bs = BatchedSend()
        self.stream_comms[address] = bs
        await comm.write({"status": "OK", "time": time()})
        bs.start(comm)

        stimulus_id = seq_name("add-worker")
        recs = self.state.bulk_schedule_unrunnable_after_adding_worker(ws)
        client_msgs, worker_msgs = self.state.transitions(recs, stimulus_id)
        recs2 = self.state.stimulus_queue_slots_maybe_opened(stimulus_id)
        cm2, wm2 = self.state.transitions(recs2, stimulus_id)
        for d, extra in ((client_msgs, cm2), (worker_msgs, wm2)):
            for k, v in extra.items():
                d.setdefault(k, []).extend(v)
        self.send_all(client_msgs, worker_msgs)
        if self._recovery is not None:
            self._recovery["awaiting"].discard(address)
        if kwargs.get("held_keys") is not None:
            # recovery reconciliation (scheduler/durability.py): the
            # worker's reported data keys rebuild / cross-check who_has
            # — every correction routed through the engine.  Idempotent:
            # a retried registration reports the same keys and the
            # second pass finds nothing to correct.  An EMPTY list still
            # reconciles: it strips every stale restored replica this
            # worker no longer holds.
            from distributed_tpu.scheduler.durability import reconcile_worker

            (cm3, wm3), counts = reconcile_worker(
                self.state, address, kwargs["held_keys"],
                seq_name("reconcile"),
            )
            corrections = (
                counts["added"] + counts["finished"] + counts["stripped"]
            )
            if corrections:
                logger.info(
                    "reconciled %s on (re)registration: %s", address, counts
                )
                if self.durability is not None:
                    self.durability.stats.reconcile_corrections += corrections
            self.send_all(cm3, wm3)
        for ext in self.extensions.values():
            cb = getattr(ext, "add_worker", None)
            if cb is not None:
                try:
                    cb(self, address)
                except Exception:
                    logger.exception("extension add_worker failed")
        for pname, plugin in self.worker_plugins.items():
            self._ongoing_background_tasks.call_soon(
                self._send_plugin_to_worker, address, pname, plugin
            )

        try:
            await self.handle_stream(comm, extra={"worker": address})
        finally:
            # remove only while THIS registration still owns the stream:
            # an idempotent re-registration (same server_id) replaces the
            # stream and aborts this one — the superseded handler waking
            # up here must not strip the freshly re-registered worker
            if self.stream_comms.get(address) is bs:
                try:
                    await self.remove_worker(address, "stream-closed")
                except Exception:
                    # a failed removal must be loud: half-applied
                    # reschedules strand tasks on a dead worker
                    logger.exception("remove_worker failed for %s", address)
        return Status.dont_reply

    async def remove_worker(self, address: str, reason: str = "", *,
                            safe: bool = False) -> None:
        """Worker left or died: reschedule its work (reference scheduler.py:5180)."""
        if address not in self.state.workers:
            return
        logger.info("remove worker %s (%s)", address, reason)
        stimulus_id = seq_name("remove-worker")
        bs = self.stream_comms.pop(address, None)
        if bs is not None:
            bs.abort()
        self._last_worker_seen.pop(address, None)
        client_msgs, worker_msgs = self.state.remove_worker_state(
            address, stimulus_id=stimulus_id, safe=safe
        )
        self.send_all(client_msgs, worker_msgs)
        for ext in self.extensions.values():
            cb = getattr(ext, "remove_worker", None)
            if cb is not None:
                try:
                    cb(self, address)
                except Exception:
                    logger.exception("extension remove_worker failed")

    async def remove_worker_handler(self, address: str = "", reason: str = "") -> str:
        await self.remove_worker(address, reason or "rpc")
        return "OK"

    async def heartbeat_worker(
        self, address: str = "", now: float = 0.0, metrics: dict | None = None,
        fine_metrics: list | None = None, executing_status: str = "",
        status_seq: int = -1, link_telemetry: list | None = None,
        rtt: float = 0.0, **kwargs: Any,
    ) -> dict:
        ws = self.state.workers.get(address)
        if ws is None:
            return {"status": "missing"}
        self._last_worker_seen[address] = time()
        ws.last_seen = time()
        if metrics:
            ws.metrics = metrics
        if fine_metrics and self.spans is not None:
            self.spans.collect_fine_metrics(fine_metrics)
        # measured-truth telemetry plane (telemetry.py): per-link
        # transfer deltas + the worker-measured heartbeat RTT fold into
        # the fleet aggregate, and the same fine-metric stream feeds the
        # per-prefix priors
        tel = self.state.telemetry
        if tel.enabled:
            with self.state.wall.phase("telemetry.fold"):
                if link_telemetry:
                    # fold only links between CURRENTLY registered
                    # workers: a row naming a peer that already left
                    # (or never completed registration) would re-create
                    # a LinkStats entry forget_worker(PR 7) just pruned
                    # — nothing re-prunes it, so with worker churn the
                    # link table grew without bound (the census's
                    # telemetry.links.stale family walks this to zero)
                    workers = self.state.workers
                    rows = [
                        r for r in link_telemetry
                        if r[0] in workers and r[1] in workers
                    ]
                    if rows:
                        tel.fold_rows(rows, reporter=address)
                if rtt:
                    tel.record_rtt(address, rtt)
                if fine_metrics:
                    tel.fold_fine_rows(fine_metrics)
        # reconcile pause state: the event message can be lost at
        # startup (see Worker.heartbeat) and a stale "running" view
        # pins the paused worker's tasks out of stealing forever.
        # A heartbeat that raced a fresher stream-delivered change must
        # NOT win (its snapshot predates the RPC; the spurious paused
        # flip un-homes tasks irreversibly): the worker stamps every
        # status flip AND every heartbeat with a monotonic status_seq,
        # and the heartbeat's view is applied only when provably at
        # least as new as the last flip this scheduler has seen.  (A
        # pre-seq worker — status_seq < 0 — falls back to a wall-clock
        # quiet window, the old racy heuristic.)
        if executing_status and executing_status != ws.status:
            if (
                status_seq >= ws.status_seq
                if status_seq >= 0
                else time() - ws.status_changed_at > 1.0
            ):
                self.handle_worker_status_change(
                    status=executing_status, worker=address,
                    stimulus_id=seq_name("heartbeat-status"),
                    status_seq=status_seq,
                )
        return {"status": "OK", "time": time(),
                "heartbeat-interval": self.heartbeat_interval()}

    def heartbeat_interval(self) -> float:
        """Scale heartbeat cadence with cluster size (reference scheduler.py:8749)."""
        n = len(self.state.workers)
        if n <= 10:
            return 0.5
        if n < 50:
            return 1.0
        return n / 200 + 1

    async def check_worker_ttl(self) -> None:
        """Evict workers that stopped heartbeating (reference scheduler.py:8312)."""
        now = time()
        for address, seen in list(self._last_worker_seen.items()):
            if now - seen > self.worker_ttl:
                logger.warning("worker %s missed its ttl; removing", address)
                await self.remove_worker(address, "ttl-expired")

    async def check_idle(self) -> None:
        s = self.state
        # task activity only — a connected-but-inactive client must not
        # keep an idle cluster alive forever (reference idle-timeout
        # semantics, scheduler.py:8326).  Also reset whenever the
        # transition counter advanced since the last check: bursts of
        # short tasks that start AND finish between two checks are
        # activity, not idleness (reference scheduler.py:8330).
        busy = any(ws.processing for ws in s.workers.values()) or s.queued or s.unrunnable
        if s.transition_counter != getattr(self, "_idle_transition_counter", -1):
            self._idle_transition_counter = s.transition_counter
            busy = True
        if busy:
            self.idle_since = None
            return
        if self.idle_since is None:
            self.idle_since = time()
        elif self.idle_timeout and time() - self.idle_since > self.idle_timeout:
            logger.info("scheduler idle for %.0fs; closing", time() - self.idle_since)
            self._ongoing_background_tasks.call_soon(self.close)

    # -------------------------------------------------------------- clients

    async def add_client(self, comm: Comm, client: str = "", **kwargs: Any) -> Any:
        """Client registration; the comm becomes the report stream
        (reference scheduler.py:5550)."""
        logger.info("register client %s", client)
        self.state.add_client_state(client)
        # same ordering as add_worker: publish the buffering BatchedSend
        # before any await (no dropped reports), start it only after the
        # handshake reply (no batch ahead of the handshake)
        bs = BatchedSend()
        self.client_comms[client] = bs
        await comm.write({"status": "OK", "time": time(),
                          "id": self.id, "type": type(self).__name__})
        bs.start(comm)
        try:
            await self.handle_stream(comm, extra={"client": client})
        finally:
            self.client_comms.pop(client, None)
            for subs in self._topic_subscribers.values():
                subs.discard(client)
            # a consumer that died without eventstream_stop must not pin
            # the per-completion plugin forever: drop every reference it
            # still holds now that its comm is gone
            held = self._eventstream_clients.pop(client, 0)
            if held:
                self._release_eventstream_refs(held)
            stimulus_id = seq_name("remove-client")
            client_msgs, worker_msgs = self.state.remove_client_state(
                client, stimulus_id
            )
            self.send_all(client_msgs, worker_msgs)
            logger.info("remove client %s", client)
        return Status.dont_reply

    def handle_heartbeat_client(self, client: str = "", **kwargs: Any) -> None:
        cs = self.state.clients.get(client)
        if cs is not None:
            cs.last_seen = time()

    async def handle_close_client(self, client: str = "", **kwargs: Any) -> None:
        # direct stream write: flush staged sends first or stream-closed
        # (terminal for the client's listen loop) overtakes final reports
        self.stream_payload_flush()
        bs = self.client_comms.get(client)
        if bs is not None and not bs.closed():
            try:
                bs.send({"op": "stream-closed"})
            except CommClosedError:
                pass  # the client hung up first — that's the point

    # ----------------------------------------------------------- graph intake

    async def update_graph(
        self,
        client: str = "",
        tasks: Any = None,
        dependencies: dict | None = None,
        keys: Iterable[Key] = (),
        priorities: dict | None = None,
        user_priority: Any = 0,
        annotations_by_key: dict | None = None,
        retries: Any = None,
        actors: Any = False,
        stimulus_id: str | None = None,
        **kwargs: Any,
    ) -> None:
        """Receive a task graph from a client (reference scheduler.py:4662)."""
        stimulus_id = stimulus_id or seq_name("update-graph")
        try:
            tasks = unwrap(tasks) or {}
            self._trace_ingress("update-graph", len(tasks), stimulus_id)
            deps = {
                k: set(v) for k, v in (dependencies or {}).items()
            }
            self.generation += 1
            client_msgs, worker_msgs = self.state.update_graph_core(
                tasks,
                deps,
                list(keys),
                client=client,
                priorities=priorities,
                user_priority=user_priority,
                generation=self.generation,
                annotations_by_key=annotations_by_key,
                retries=retries,
                actors=actors,
                stimulus_id=stimulus_id,
            )
            self.send_all(client_msgs, worker_msgs)
        except Exception as e:
            logger.exception("update_graph failed")
            for key in keys:
                self.report(
                    {
                        "op": "task-erred",
                        "key": key,
                        "exception": Serialize(e),
                        "traceback": None,
                    },
                    client=client,
                )

    def handle_client_desires_keys(self, keys: Iterable[Key] = (),
                                   client: str = "", **kw: Any) -> None:
        self.state.client_desires_keys(keys, client)
        for key in keys:
            ts = self.state.tasks.get(key)
            if ts is None:
                continue
            if ts.state == "memory":
                self.report({"op": "key-in-memory", "key": key}, client=client)
            elif ts.state == "erred":
                self.report(
                    {
                        "op": "task-erred",
                        "key": key,
                        "exception": ts.exception,
                        "traceback": ts.traceback,
                    },
                    client=client,
                )

    def handle_client_releases_keys(self, keys: Iterable[Key] = (),
                                    client: str = "", **kw: Any) -> None:
        stimulus_id = seq_name("client-releases-keys")
        keys = list(keys)
        self._trace_ingress("client-releases-keys", len(keys), stimulus_id)
        client_msgs, worker_msgs = self.state.client_releases_keys(
            keys, client, stimulus_id
        )
        self.send_all(client_msgs, worker_msgs)

    # ----------------------------------------------------- worker stream ops

    def _trace_ingress(self, op: str, n: int, stimulus_id: str) -> None:
        """Flight-recorder ingress hop: a stream op entered the control
        loop.  Every op on the batched plane (``stream_batch_handlers``)
        and its scalar twin MUST pass through here — enforced by the
        handler-parity lint's trace-parity pass (docs/analysis.md)."""
        self.trace.emit("ingress", op, stimulus_id, n=n)

    def handle_task_finished(self, key: Key = "", worker: str = "",
                             stimulus_id: str = "", **kwargs: Any) -> None:
        kwargs.pop("op", None)
        stimulus_id = stimulus_id or seq_name("task-finished")
        self._trace_ingress("task-finished", 1, stimulus_id)
        client_msgs, worker_msgs = self.state.stimulus_task_finished(
            key, worker, stimulus_id, **kwargs
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_task_erred(self, key: Key = "", worker: str = "",
                          stimulus_id: str = "", exception: Any = None,
                          traceback: Any = None, **kwargs: Any) -> None:
        kwargs.pop("op", None)
        stimulus_id = stimulus_id or seq_name("task-erred")
        self._trace_ingress("task-erred", 1, stimulus_id)
        client_msgs, worker_msgs = self.state.stimulus_task_erred(
            key,
            worker,
            stimulus_id,
            # opaque: user exceptions may be classes this process cannot
            # import; they are stored and forwarded as-is, and the
            # worker-supplied exception_text covers scheduler-side logs
            exception=exception,
            traceback=traceback,
            **kwargs,
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_tasks_finished(self, msgs: list, worker: str = "",
                              **kw: Any) -> None:
        """Batched ``task-finished`` flood: one state-machine pass, one
        staged send (rpc/core.py batch dispatch)."""
        finishes = []
        for m in msgs:
            key = m.pop("key", "")
            w = m.pop("worker", "") or worker
            stimulus_id = m.pop("stimulus_id", "") or seq_name("task-finished")
            finishes.append((key, w, stimulus_id, m))
        self._trace_ingress(
            "task-finished", len(finishes),
            finishes[0][2] if finishes else "",
        )
        client_msgs, worker_msgs = self.state.stimulus_tasks_finished_batch(
            finishes
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_tasks_erred(self, msgs: list, worker: str = "",
                           **kw: Any) -> None:
        """Batched ``task-erred`` flood (a worker death mid-tile erring a
        whole co-assigned batch)."""
        errors = []
        for m in msgs:
            key = m.pop("key", "")
            w = m.pop("worker", "") or worker
            stimulus_id = m.pop("stimulus_id", "") or seq_name("task-erred")
            errors.append((key, w, stimulus_id, m))
        self._trace_ingress(
            "task-erred", len(errors), errors[0][2] if errors else ""
        )
        client_msgs, worker_msgs = self.state.stimulus_tasks_erred_batch(errors)
        self.send_all(client_msgs, worker_msgs)

    def handle_release_data_batch(self, msgs: list, worker: str = "",
                                  **kw: Any) -> None:
        """Batched ``release-worker-data`` flood (AMM drop rounds).  The
        generator interleaves replica removal with each key's transition
        round exactly like sequential per-message handling, while all
        rounds drain into one shared message pair."""
        state = self.state
        self._trace_ingress(
            "release-worker-data", len(msgs),
            (msgs[0].get("stimulus_id") or "") if msgs else "",
        )

        def rounds():
            for m in msgs:
                key = m.get("key", "")
                w = m.get("worker", "") or worker
                stimulus_id = m.get("stimulus_id") or seq_name("release-data")
                recs = state.stimulus_release_worker_data(key, w, stimulus_id)
                if recs:
                    yield (recs, stimulus_id)

        client_msgs, worker_msgs = state.transitions_batch(rounds())
        self.send_all(client_msgs, worker_msgs)

    def handle_release_data(self, key: Key = "", worker: str = "",
                            stimulus_id: str = "", **kwargs: Any) -> None:
        stimulus_id = stimulus_id or seq_name("release-data")
        self._trace_ingress("release-worker-data", 1, stimulus_id)
        recs = self.state.stimulus_release_worker_data(
            key, worker, stimulus_id
        )
        if recs:
            client_msgs, worker_msgs = self.state.transitions(
                recs, stimulus_id
            )
            self.send_all(client_msgs, worker_msgs)

    # the pure bodies of these scalar worker-op handlers live on
    # SchedulerState (stimulus_add_keys & co): the sans-io cluster
    # simulator (distributed_tpu/sim) drives the same implementations
    # directly, so the live stream plane and the simulated one cannot
    # drift apart.

    def handle_add_keys(self, keys: Iterable[Key] = (), worker: str = "",
                        stimulus_id: str = "", **kwargs: Any) -> None:
        """Worker acquired replicas out-of-band (reference scheduler.py:5855)."""
        client_msgs, worker_msgs = self.state.stimulus_add_keys(
            keys, worker, stimulus_id or seq_name("add-keys")
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_long_running(self, key: Key = "", worker: str = "",
                            compute_duration: float = 0.0,
                            stimulus_id: str = "", **kwargs: Any) -> None:
        """Task seceded from its thread slot (reference scheduler.py:5906)."""
        client_msgs, worker_msgs = self.state.stimulus_long_running(
            key, worker, compute_duration,
            stimulus_id or seq_name("long-running"),
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_reschedule(self, key: Key = "", worker: str = "",
                          stimulus_id: str = "", **kwargs: Any) -> None:
        client_msgs, worker_msgs = self.state.stimulus_reschedule(
            key, worker, stimulus_id or seq_name("reschedule")
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_missing_data(self, key: Key = "", errant_worker: str = "",
                            stimulus_id: str = "", **kwargs: Any) -> None:
        """A peer did not have data it was supposed to (reference :5869)."""
        client_msgs, worker_msgs = self.state.stimulus_missing_data(
            key, errant_worker, stimulus_id or seq_name("missing-data")
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_request_refresh_who_has(self, keys: Iterable[Key] = (),
                                       worker: str = "",
                                       stimulus_id: str = "", **kw: Any) -> None:
        client_msgs, worker_msgs = self.state.stimulus_request_refresh_who_has(
            keys, worker, stimulus_id or seq_name("refresh-who-has")
        )
        self.send_all(client_msgs, worker_msgs)

    def handle_worker_log_event(self, topic: Any = None, msg: Any = None,
                                worker: str = "", **kw: Any) -> None:
        self.log_event(topic or "all", {"worker": worker, "msg": msg})

    def handle_worker_status_change(self, status: str = "", worker: str = "",
                                    stimulus_id: str = "",
                                    status_seq: int = -1, **kw: Any) -> None:
        # pure twin on SchedulerState (journals itself for the
        # durability tail; the sans-io simulator drives it directly)
        cm, wm = self.state.stimulus_worker_status_change(
            worker, status, status_seq,
            stimulus_id or seq_name("worker-status"),
        )
        self.send_all(cm, wm)

    # ------------------------------------------------------------- data ops

    async def gather(self, keys: Iterable[Key] = (), **kwargs: Any) -> dict:
        """Collect data from workers for a client (reference scheduler.py:6150)."""
        data: dict[Key, Any] = {}
        missing: set[Key] = set()
        busy: set[Key] = set()
        failed: list[str] = []
        pending: list[Key] = list(keys)
        for _attempt in range(3):
            who_has = {}
            for key in pending:
                ts = self.state.tasks.get(key)
                who_has[key] = [ws.address for ws in ts.who_has] if ts else []
            d, m, busy, f = await gather_from_workers(who_has, rpc=self.rpc)
            data.update(d)
            missing |= m
            failed.extend(w for w in f if w not in failed)
            if not busy:
                break
            # busy holders still HAVE the data: refresh who_has from
            # current state (the key may have gained replicas or moved)
            # and retry just those keys instead of reporting data that
            # exists as lost (ADVICE.md #1)
            logger.info("gather retrying %d busy key(s)", len(busy))
            pending = sorted(busy)
        if missing or busy:
            if missing:
                logger.warning("gather couldn't find %s", sorted(missing))
            if busy:
                logger.warning("gather gave up on busy holders of %s",
                               sorted(busy))
            return {
                "status": "error",
                "keys": sorted(missing | busy),
                "busy": sorted(busy),
                "workers": failed,
            }
        return {
            "status": "OK",
            # worker payloads are already opaque frames on this server:
            # forward without a deserialize/re-serialize round-trip
            "data": {k: wrap_opaque(v) for k, v in data.items()},
        }

    async def scatter(
        self,
        data: Any = None,
        client: str | None = None,
        workers: list[str] | None = None,
        broadcast: bool = False,
        timeout: float = 2.0,
        **kwargs: Any,
    ) -> list[Key]:
        """Place client data onto workers (reference scheduler.py:6103)."""
        # values stay opaque: forwarded to workers as the frames the
        # client sent; sizes come from the frames, not from unpickling
        data = dict(unwrap(data) or {})
        start = time()
        while not self.state.running:
            if time() - start > timeout:
                raise TimeoutError("no workers available for scatter")
            await asyncio.sleep(0.01)
        if workers:
            targets = [w for w in workers if w in self.state.workers]
        else:
            targets = sorted(ws.address for ws in self.state.running)
        who_has = await scatter_to_workers(targets, data, rpc=self.rpc)
        from distributed_tpu.protocol.serialize import payload_nbytes

        stimulus_id = seq_name("scatter")
        for key, holders in who_has.items():
            # a holder may have left during scatter_to_workers: only live
            # workers count, and the memory transition needs a live one
            holders = [a for a in holders if a in self.state.workers]
            if not holders:
                logger.warning("scatter: all holders of %r left; data lost", key)
                continue
            # through the journaled engine twin (the sim drives the same
            # code): scattered data enters memory from no worker
            # stimulus, so a durable journal tail without these records
            # replays a cluster whose root partitions never existed
            cm, wm = self.state.stimulus_scatter_data(
                key, holders, payload_nbytes(data[key]), client,
                stimulus_id,
            )
            self.send_all(cm, wm)
        if broadcast:
            await self.replicate(keys=list(who_has), n=len(targets) if broadcast is True else broadcast)
        return list(who_has)

    async def replicate(self, keys: Iterable[Key] = (), n: int | None = None,
                        workers: list[str] | None = None, **kwargs: Any) -> None:
        """Copy keys onto additional workers (reference scheduler.py:6854)."""
        if workers:
            unknown = [w for w in workers if w not in self.state.workers]
            if len(unknown) == len(workers):
                # every requested target is unknown: error, don't
                # silently fan the data out to the whole cluster instead
                raise ValueError(
                    f"replicate: none of the requested workers are known: "
                    f"{sorted(workers)}"
                )
            if unknown:
                # partial typo: replicate to the known subset but say so
                # instead of silently dropping addresses
                logger.warning(
                    "replicate: ignoring unknown workers %s", sorted(unknown)
                )
        candidates = [
            self.state.workers[w] for w in (workers or [])
            if w in self.state.workers
        ] or list(self.state.running)
        if not candidates:
            return
        n = len(candidates) if n is None else n  # explicit 0 = no-op
        stimulus_id = seq_name("replicate")
        for key in keys:
            ts = self.state.tasks.get(key)
            if ts is None or not ts.who_has:
                continue
            need = min(n, len(candidates)) - len(ts.who_has)
            if need <= 0:
                continue
            holders = [ws.address for ws in ts.who_has]
            targets = [ws for ws in candidates if ws not in ts.who_has][:need]
            for ws in targets:
                self.send_all({}, {ws.address: [{
                    "op": "acquire-replicas",
                    "who_has": {key: holders},
                    "nbytes": {key: ts.nbytes},
                    "stimulus_id": stimulus_id,
                }]})

    # ---------------------------------------------------------- control ops

    async def stimulus_cancel(self, keys: Iterable[Key] = (), client: str = "",
                              force: bool = False, **kwargs: Any) -> None:
        """Client cancels futures (reference scheduler.py:5161)."""
        stimulus_id = seq_name("cancel")
        keys = list(keys)
        if keys:
            # one batched report, and for EVERY requested key (known or
            # not): the client registered a _cancel_expected entry per
            # key and consumes it on this confirmation
            self.report(
                {"op": "cancelled-keys", "keys": keys}, client=client
            )
        cancelled = [key for key in keys if key in self.state.tasks]
        client_msgs, worker_msgs = self.state.client_releases_keys(
            cancelled, client, stimulus_id
        )
        self.send_all(client_msgs, worker_msgs)

    async def stimulus_retry(self, keys: Iterable[Key] = (),
                             client: str | None = None, **kwargs: Any) -> list:
        client_msgs, worker_msgs = self.state.stimulus_retry(
            keys, seq_name("retry")
        )
        self.send_all(client_msgs, worker_msgs)
        return list(keys)

    async def restart(self, client: str = "", **kwargs: Any) -> str:
        """Forget all tasks; clear cluster state (reference scheduler.py:6193).

        The report carries the initiating client's id so that client can
        ignore its own echo (it cancels its futures synchronously)."""
        stimulus_id = seq_name("restart")
        self.stream_payload_flush()  # direct stream writes below
        for cs in list(self.state.clients.values()):
            if cs.client_key in self.client_comms:
                # snapshot THIS client's wanted keys: its echo cancels
                # exactly these — futures submitted after the restart was
                # processed here (but before the unordered echo reached
                # the client) must survive
                self.client_comms[cs.client_key].send(
                    {"op": "restart", "stimulus_id": stimulus_id,
                     "initiator": client,
                     "keys": [ts.key for ts in cs.wants_what]}
                )
        for addr in list(self.state.workers):
            self.send_all({}, {addr: [{"op": "free-keys",
                                       "keys": list(self.state.tasks),
                                       "stimulus_id": stimulus_id}]})
        self.state._clear_task_state()
        # workers under a nanny additionally CYCLE their process: the
        # reference's restart clears worker-side module/memory state too
        # (reference scheduler.py:6193 restart -> nanny.restart); bounded
        # best-effort — a dead nanny must not wedge the restart
        nannies = [
            ws.extra["nanny"]
            for ws in self.state.workers.values()
            if ws.extra.get("nanny")
        ]

        async def _cycle(addr: str) -> None:
            try:
                await asyncio.wait_for(self.rpc(addr).restart(), 10)
            except Exception:
                logger.warning("nanny %s did not restart its worker", addr)

        if nannies:
            await asyncio.gather(*(_cycle(a) for a in nannies),
                                 return_exceptions=True)
        return "OK"

    async def broadcast(self, msg: dict | None = None,
                        workers: list[str] | None = None,
                        hosts: list[str] | None = None,
                        nanny: bool = False, **kwargs: Any) -> dict:
        """Send an RPC to many workers, gather replies (reference :6331)."""
        msg = dict(unwrap(msg) or {})
        targets = workers if workers is not None else list(self.state.workers)
        if nanny:
            # route to the workers' nannies (reference scheduler.py:6331)
            targets = [
                ws.extra["nanny"]
                for a in targets
                if (ws := self.state.workers.get(a)) is not None
                and ws.extra.get("nanny")
            ]
        op = msg.pop("op")

        async def one(addr: str):
            try:
                return addr, await getattr(self.rpc(addr), op)(**msg)
            except Exception as e:
                return addr, error_message(e)

        results = await asyncio.gather(*(one(a) for a in targets))
        return dict(results)

    async def run_function_on_scheduler(self, function: Any = None,
                                        args: Any = None,
                                        kwargs: Any = None, **kw: Any) -> Any:
        from distributed_tpu.rpc.core import run_user_function

        return await run_user_function(
            self, "dtpu_scheduler", function, args, kwargs, True
        )

    def adaptive_target(self, target_duration: float = 5.0) -> int:
        """Desired worker count to drain current load in ``target_duration``
        seconds (reference scheduler.py:8400).  Served over RPC so
        out-of-process clusters (Subprocess/SSH) can adapt."""
        import math

        s = self.state
        occupancy = sum(ws.occupancy for ws in s.workers.values())
        queued = len(s.queued) + len(s.unrunnable)
        avg_nthreads = (
            max(1, s.total_nthreads // max(1, len(s.workers)))
            if s.workers
            else 1
        )
        cpu = 0
        if occupancy > 0 or queued:
            cpu = math.ceil(
                (occupancy / target_duration + queued) / avg_nthreads
            )
        if s.unrunnable and not s.workers:
            cpu = max(1, cpu)
        return cpu

    async def retire_workers(self, workers: list[str] | None = None,
                             n: int | None = None, **kwargs: Any) -> list[str]:
        """Gracefully drain workers: replicate unique data away first
        (reference scheduler.py:7144, simplified)."""
        s = self.state
        if workers is None:
            if n is None:
                return []
            by_occ = sorted(s.workers.values(), key=lambda ws: ws.occupancy)
            workers = [ws.address for ws in by_occ[:n]]
        retired = []
        for addr in workers:
            ws = s.workers.get(addr)
            if ws is None:
                continue
            # move unique replicas to surviving workers
            survivors = [w for w in s.running if w.address != addr]
            if survivors:
                for ts in list(ws.has_what):
                    if len(ts.who_has) == 1:
                        # address tiebreak: survivors come from the
                        # ``running`` set, so equal nbytes must not fall
                        # back to hash-seed order
                        target = min(
                            survivors, key=lambda w: (w.nbytes, w.address)
                        )
                        resp = await self.rpc(target.address).gather(
                            who_has={ts.key: [addr]}
                        )
                        # re-validate after the await: while the transfer
                        # ran, the task may have been released/forgotten
                        # (a replica record would resurrect it as a
                        # phantom peers fetch forever) and the recipient
                        # may have left the cluster (found by the
                        # await-atomicity lint, rule 10)
                        if (
                            resp.get("status") == "OK"
                            and s.tasks.get(ts.key) is ts
                            and ts.state == "memory"
                            and s.workers.get(target.address) is target
                        ):
                            s.add_replica(ts, target)
            await self.remove_worker(addr, "retired", safe=True)
            retired.append(addr)
            # tell the worker process to shut down
            try:
                await self.rpc(addr).terminate()
            except (CommClosedError, OSError):
                pass
        return retired

    # ------------------------------------------------------------- queries

    async def get_who_has(self, keys: Iterable[Key] | None = None) -> dict:
        s = self.state
        if keys is None:
            keys = list(s.tasks)
        return {
            k: [ws.address for ws in s.tasks[k].who_has] if k in s.tasks else []
            for k in keys
        }

    async def get_has_what(self, workers: Iterable[str] | None = None) -> dict:
        s = self.state
        if workers is None:
            workers = list(s.workers)
        return {
            w: [ts.key for ts in s.workers[w].has_what] if w in s.workers else []
            for w in workers
        }

    async def get_ncores(self, workers: Iterable[str] | None = None) -> dict:
        s = self.state
        if workers is None:
            workers = list(s.workers)
        return {w: s.workers[w].nthreads for w in workers if w in s.workers}

    async def get_nbytes(self, keys: Iterable[Key] | None = None,
                         summary: bool = True) -> dict:
        s = self.state
        if keys is not None:
            return {k: s.tasks[k].nbytes for k in keys if k in s.tasks}
        return {k: ts.nbytes for k, ts in s.tasks.items() if ts.nbytes >= 0}

    async def get_processing(self, workers: Iterable[str] | None = None) -> dict:
        s = self.state
        if workers is None:
            workers = list(s.workers)
        return {
            w: [ts.key for ts in s.workers[w].processing]
            for w in workers if w in s.workers
        }

    async def get_missing_workers(self) -> list:
        return []

    # ---------------------------------------------------- plugins / state ops

    async def _send_plugin_to_worker(self, address: str, name: str,
                                     plugin: Any) -> None:
        try:
            await self.rpc(address).plugin_add(plugin=plugin, name=name)
        except (CommClosedError, OSError):
            pass

    async def register_scheduler_plugin(self, plugin: Any = None,
                                        name: str | None = None,
                                        idempotent: bool = False) -> str:
        """Install a live SchedulerPlugin (reference scheduler.py:5699)."""
        plugin = unwrap(plugin)
        name = name or getattr(plugin, "name", None) or f"plugin-{len(self.state.plugins)}"
        if idempotent and name in self.state.plugins:
            return "OK"
        start = getattr(plugin, "start", None)
        if start is not None:
            res = start(self)
            if asyncio.iscoroutine(res):
                await res
        self.state.plugins[name] = plugin
        return "OK"

    async def unregister_scheduler_plugin(self, name: str = "") -> str:
        plugin = self.state.plugins.pop(name, None)
        if plugin is not None:
            close = getattr(plugin, "close", None)
            if close is not None:
                res = close()
                if asyncio.iscoroutine(res):
                    await res
        return "OK"

    async def register_worker_plugin(self, plugin: Any = None,
                                     name: str | None = None) -> dict:
        """Install a WorkerPlugin on every current and future worker
        (reference scheduler.py:7425)."""
        if name is None:
            import itertools

            if not hasattr(self, "_plugin_counter"):
                self._plugin_counter = itertools.count()
            name = f"worker-plugin-{next(self._plugin_counter)}"
        # re-wrap: over tcp the comm already deserialized the plugin, and
        # it must cross the scheduler->worker wire pickled again
        plugin = Serialize(unwrap(plugin))
        self.worker_plugins[name] = plugin
        out = await self.broadcast(
            msg={"op": "plugin_add", "plugin": plugin, "name": name}
        )
        return out

    async def register_nanny_plugin(self, plugin: Any = None,
                                    name: str | None = None) -> dict:
        """Install a NannyPlugin on every current and future nanny
        (reference scheduler.py register_nanny_plugin)."""
        if name is None:
            name = f"nanny-plugin-{seq_name('np')}"
        plugin = wrap_opaque(plugin)
        self._nanny_plugins[name] = plugin
        return await self.broadcast(
            msg={"op": "plugin_add", "plugin": plugin, "name": name},
            nanny=True,
        )

    async def unregister_nanny_plugin(self, name: str = "") -> dict:
        self._nanny_plugins.pop(name, None)
        return await self.broadcast(
            msg={"op": "plugin_remove", "name": name}, nanny=True
        )

    async def _push_nanny_plugin(self, nanny_addr: str, name: str,
                                 plugin: Any) -> None:
        try:
            await self.rpc(nanny_addr).plugin_add(plugin=plugin, name=name)
        except Exception:
            logger.warning(
                "could not ship nanny plugin %r to %s", name, nanny_addr,
                exc_info=True,
            )

    async def unregister_worker_plugin(self, name: str = "") -> dict:
        self.worker_plugins.pop(name, None)
        return await self.broadcast(
            msg={"op": "plugin_remove", "name": name}
        )

    async def rebalance(self, keys: Iterable[Key] | None = None,
                        workers: list[str] | None = None, **kwargs: Any) -> dict:
        """Even out managed memory across workers (reference scheduler.py:6501).

        Two-phase like the reference: compute sender->recipient moves from
        the memory distribution (:6605), then enact them (:6795): the
        recipient gathers the key from the sender, then the sender drops
        its replica.
        """
        s = self.state
        mirror = s.mirror
        if mirror is not None and workers is None:
            # whole-fleet rebalance: the worker list and the projected-
            # memory vector come from the persistent mirror (slot-order
            # live list, O(dirty) refresh + one numpy gather) instead of
            # a per-call Python pack.  Explicit worker subsets (admin
            # RPC) keep the from-scratch path below.
            import numpy as np

            fv = mirror.fleet_view()
            wss = fv.live_list
            mem = fv.nbytes[fv.slots].astype(np.float32, copy=True)
        else:
            wss = [
                s.workers[w] for w in (workers or list(s.workers))
                if w in s.workers
            ]
            mem = None
            if mirror is not None:
                mirror.oracle_packs += 1
        if len(wss) < 2:
            return {"status": "OK", "moves": 0}
        keyset = set(keys) if keys is not None else None

        from distributed_tpu.scheduler.jax_placement import (
            device_dispatch_worthwhile,
        )

        # gate on MOVABLE candidates, not raw key count (a keys=[...]
        # call or replicated data would otherwise dispatch the kernel
        # for a handful of items); the filter is O(keys) either way
        cand: list = []
        owner: list[int] = []
        for wi, ws in enumerate(wss):
            for ts in ws.has_what:
                if ts.actor or len(ts.who_has) != 1 or ts.state != "memory":
                    continue
                if keyset is not None and ts.key not in keyset:
                    continue
                cand.append(ts)
                owner.append(wi)
        if device_dispatch_worthwhile(len(wss), len(cand), min_items=512,
                                      periodic=True):
            moves = self._rebalance_plan_device(wss, cand, owner, mem)
        else:
            moves = self._rebalance_plan_python(wss, keyset)

        # enact concurrently, one batched gather per (sender, recipient)
        # pair (reference _rebalance_move_data :6795 batches the same way)
        by_pair: dict[tuple, list] = {}
        for ts, sender, recipient in moves:
            if ts.state != "memory" or sender not in ts.who_has:
                continue
            by_pair.setdefault((sender, recipient), []).append(ts)

        async def move_batch(sender, recipient, tss) -> int:
            try:
                resp = await self.rpc(recipient.address).gather(
                    who_has={ts.key: [sender.address] for ts in tss}
                )
            except (CommClosedError, OSError):
                return 0
            if resp.get("status") != "OK":
                return 0
            for ts in tss:
                if recipient not in ts.who_has:
                    s.add_replica(ts, recipient)
            self.send_all({}, {sender.address: [{
                "op": "remove-replicas", "keys": [ts.key for ts in tss],
                "stimulus_id": seq_name("rebalance"),
            }]})
            return len(tss)

        counts = await asyncio.gather(
            *(move_batch(snd, rcp, tss) for (snd, rcp), tss in by_pair.items())
        )
        return {"status": "OK", "moves": sum(counts)}

    @staticmethod
    def _rebalance_plan_python(wss: list, keyset: set | None) -> list[tuple]:
        """Sequential greedy move selection (reference scheduler.py:6605):
        fullest senders shed their largest movable keys onto the emptiest
        recipients until everyone sits inside the 5% band."""
        mean = sum(ws.nbytes for ws in wss) / len(wss)
        senders = sorted(
            (ws for ws in wss if ws.nbytes > mean * 1.05),
            key=lambda ws: -ws.nbytes,
        )
        recipients = sorted(
            (ws for ws in wss if ws.nbytes < mean * 0.95),
            key=lambda ws: ws.nbytes,
        )
        moves: list[tuple] = []  # (ts, sender, recipient)
        projected = {ws: ws.nbytes for ws in wss}
        for sender in senders:
            for ts in sorted(sender.has_what, key=lambda t: -t.get_nbytes()):
                if projected[sender] <= mean:
                    break
                if keyset is not None and ts.key not in keyset:
                    continue
                if ts.actor or len(ts.who_has) != 1 or ts.state != "memory":
                    continue
                if not recipients:
                    break
                recipient = recipients[0]
                if projected[recipient] + ts.get_nbytes() > mean:
                    recipients.sort(key=lambda ws: projected[ws])
                    recipient = recipients[0]
                    if projected[recipient] + ts.get_nbytes() > mean * 1.05:
                        continue
                moves.append((ts, sender, recipient))
                projected[sender] -= ts.get_nbytes()
                projected[recipient] += ts.get_nbytes()
                recipients.sort(key=lambda ws: projected[ws])
        return moves

    @staticmethod
    def _rebalance_plan_device(
        wss: list, cand: list, owner: list[int], mem=None
    ) -> list[tuple]:
        """Vectorized move selection via the device kernel
        (ops/rebalance.py): same invariants, Jacobi rounds instead of
        the sequential greedy loop.  ``mem`` is the mirror's projected-
        memory gather when available; the list-comprehension pack stays
        as the no-mirror oracle."""
        import numpy as np

        from distributed_tpu.ops.rebalance import (
            RebalanceBatch,
            plan_rebalance,
        )

        if not cand:
            return []
        if mem is None:
            mem = np.asarray([ws.nbytes for ws in wss], np.float32)
        batch = RebalanceBatch(
            owner=np.asarray(owner, np.int32),
            nbytes=np.asarray([ts.get_nbytes() for ts in cand], np.float32),
            eligible=np.ones(len(cand), bool),
            mem=mem,
        )
        return [
            (cand[key_idx], wss[src], wss[dst])
            for key_idx, src, dst in plan_rebalance(batch)
        ]

    async def versions(self) -> dict:
        from distributed_tpu.versions import get_versions

        return get_versions()

    async def worker_versions(self) -> dict:
        return {
            addr: ws.extra.get("versions", {})
            for addr, ws in self.state.workers.items()
        }

    async def benchmark_hardware(self) -> dict:
        """Memory/disk micro-benchmarks on workers (reference :7590)."""
        resp = await self.broadcast(msg={"op": "benchmark_hardware"})
        return {
            a: unwrap(v.get("result")) if isinstance(v, dict) else v
            for a, v in resp.items()
        }

    async def performance_report_html(self) -> str:
        """Self-contained HTML snapshot (reference scheduler.py:8077)."""
        import html as _html
        import json as _json

        s = self.state
        counts = self._counts_json()
        stream = self.task_stream.collect(count=2000)
        rows = "".join(
            f"<tr><td>{_html.escape(addr)}</td><td>{ws.nthreads}</td>"
            f"<td>{len(ws.has_what)}</td><td>{ws.nbytes}</td>"
            f"<td>{ws.occupancy:.2f}</td></tr>"
            for addr, ws in s.workers.items()
        )
        spans = [sp for sp in self.spans.spans.values() if len(sp.name) == 1]
        span_rows = "".join(
            f"<tr><td>{_html.escape('/'.join(sp.name))}</td>"
            f"<td>{sp.n_tasks}</td><td>{sp.compute_seconds:.3f}</td>"
            f"<td>{sp.nbytes}</td></tr>"
            for sp in spans
        )
        # per-activity fine metrics (reference metrics.py:159 ContextMeter
        # samples aggregated over heartbeats): seconds/bytes per
        # (context, activity-label) — execute, gather-dep network vs
        # deserialize vs other, spill serialize/disk-write/disk-read
        activities: dict[tuple[str, str, str], float] = {}
        for key, v in self.spans.cumulative_worker_metrics.items():
            # key = (context, span_id, prefix, label, unit)
            try:
                context, _sid, _pre, label, unit = key
            except Exception:
                continue
            k = (str(context), str(label), str(unit))
            activities[k] = activities.get(k, 0.0) + float(v)
        act_rows = "".join(
            f"<tr><td>{_html.escape(ctx)}</td><td>{_html.escape(label)}</td>"
            f"<td>{val:.3f}</td><td>{_html.escape(unit)}</td></tr>"
            for (ctx, label, unit), val in sorted(activities.items())
        )
        return f"""<!doctype html><html><head><meta charset="utf-8">
<title>distributed_tpu performance report</title></head><body>
<h1>distributed_tpu performance report</h1>
<h2>Cluster</h2>
<pre>{_html.escape(_json.dumps(counts, indent=1))}</pre>
<h2>Workers</h2>
<table border="1"><tr><th>address</th><th>threads</th><th>stored</th>
<th>bytes</th><th>occupancy</th></tr>{rows}</table>
<h2>Activities (fine metrics)</h2>
<table border="1"><tr><th>context</th><th>activity</th><th>total</th>
<th>unit</th></tr>{act_rows}</table>
<h2>Spans</h2>
<table border="1"><tr><th>span</th><th>tasks</th><th>compute s</th>
<th>bytes</th></tr>{span_rows}</table>
<h2>Task stream (last {len(stream)})</h2>
<pre>{_html.escape(_json.dumps(stream[-200:], indent=0, default=str))}</pre>
</body></html>"""

    async def get_runspec(self, key: Key = "") -> dict:
        """Fetch a task's spec + dependency keys for client-side replay
        (reference recreate_tasks.py ReplayTaskScheduler)."""
        ts = self.state.tasks.get(key)
        if ts is None:
            raise KeyError(key)
        return {
            "run_spec": wrap_opaque(ts.run_spec),
            "deps": [d.key for d in ts.dependencies],
        }

    async def get_telemetry(self) -> list[dict]:
        """The fleet telemetry snapshot (JSON-safe records): the RPC
        twin of the HTTP ``/telemetry`` route (telemetry.py)."""
        return self.state.telemetry.snapshot()

    async def get_ledger(self, n: int | None = None) -> list[dict]:
        """The decision–outcome ledger (summary head + resident row
        tail): the RPC twin of the HTTP ``/ledger`` route (ledger.py;
        docs/observability.md "Decision ledger & critical-path")."""
        return self.state.ledger.snapshot(n)

    async def get_census(self, deep: bool = False) -> list[dict]:
        """The state census (head + per-family records + recent
        findings): the RPC twin of the HTTP ``/census`` route
        (diagnostics/census.py; docs/observability.md "State census &
        retention").  ``deep=True`` adds the O(n) walk families — the
        relation-set edge counts — and is meant for quiesced or
        dump-time use, not a per-second poll."""
        return self.state.census.snapshot(deep=deep)

    async def get_cluster_state(self, exclude: list[str] | None = None) -> dict:
        """Debug dump of the whole cluster (reference scheduler.py:3964)."""
        s = self.state
        scheduler_info = {
            "address": self.address,
            "id": self.id,
            "tasks": {
                k: {
                    "state": ts.state,
                    "priority": ts.priority,
                    "who_has": [ws.address for ws in ts.who_has],
                    "processing_on": (
                        ts.processing_on.address if ts.processing_on else None
                    ),
                    "nbytes": ts.nbytes,
                    "dependencies": [d.key for d in ts.dependencies],
                }
                for k, ts in s.tasks.items()
            },
            "workers": {
                addr: {
                    "name": str(ws.name),
                    "nthreads": ws.nthreads,
                    "nbytes": ws.nbytes,
                    "status": str(ws.status),
                    "processing": [ts.key for ts in ws.processing],
                    "has_what": [ts.key for ts in ws.has_what],
                }
                for addr, ws in s.workers.items()
            },
            "clients": {c: [ts.key for ts in cs.wants_what]
                        for c, cs in s.clients.items()},
            "events": {t: len(evs) for t, evs in s.events.items()},
            "transition_log_length": len(s.transition_log),
        }
        if "telemetry" not in (exclude or ()):
            # the measured-truth snapshot travels with the dump: a
            # post-mortem can see which links/priors the cost model was
            # lying about without a live cluster (telemetry.py)
            scheduler_info["telemetry"] = self.state.telemetry.snapshot()
        if "ledger" not in (exclude or ()):
            # decision–outcome ledger tail + a PRECOMPUTED critical-path
            # summary (ledger.py, diagnostics/critical_path.py): the
            # dump's task table still holds the dependency map here, so
            # the path is computed while the graph is known — the
            # offline DumpArtefact.critical_path() recomputes it from
            # the same two sections
            ledger_info: dict[str, Any] = {
                "rows": s.ledger.tail(500),
                "summary": s.ledger.summary(),
            }
            try:
                from distributed_tpu.diagnostics.critical_path import (
                    critical_path,
                )

                cp = critical_path(
                    ledger_info["rows"],
                    {
                        k: [d.key for d in ts.dependencies]
                        for k, ts in s.tasks.items()
                    },
                )
                if cp is not None:
                    ledger_info["critical_path"] = {
                        "makespan": cp["makespan"],
                        "n_tasks": cp["n_tasks"],
                        "terminal": cp["terminal"],
                        "attribution": cp["attribution"],
                        "by_prefix": cp["by_prefix"],
                    }
            except Exception:
                logger.exception("critical-path precompute failed")
            scheduler_info["ledger"] = ledger_info
        if "transition_log" not in (exclude or ()):
            # the newest transition rows travel WITH the dump so a
            # post-mortem can replay a task's story offline
            # (diagnostics/cluster_dump.DumpArtefact.story; reference
            # cluster_dump.py:111); exclude=['transition_log'] keeps
            # periodic snapshots cheap
            scheduler_info["transition_log"] = [
                list(row) for row in list(s.transition_log)[-5000:]
            ]
        if "profile" not in (exclude or ()):
            # the self-profile tail travels with the dump: a postmortem
            # can see where the scheduler's wall went (phase budget),
            # the sampled control-plane tree, and any stall captures —
            # without a live cluster (docs/observability.md)
            prof: dict[str, Any] = {
                "wall_seconds": {
                    k: round(v, 6) for k, v in s.wall.snapshot().items()
                },
            }
            if self.cp_profiler is not None:
                prof["samples_total"] = self.cp_profiler.total_samples
                prof["idle_samples"] = self.cp_profiler.idle_samples
                prof["tree"] = self.cp_profiler.get_profile()
            if self.watchdog is not None:
                prof["stalls_total"] = self.watchdog.stalls_total
                prof["stalls"] = list(self.watchdog.stalls)
            scheduler_info["profile"] = prof
        if "census" not in (exclude or ()):
            # the state census travels with the dump (deep = relation
            # walks included): a post-mortem can see exactly what the
            # control plane was still holding, with any recorded
            # retention findings (diagnostics/census.py)
            scheduler_info["census"] = s.census.snapshot(deep=True)
        out = {"scheduler": scheduler_info}
        if "census" not in (exclude or ()):
            out["worker_census"] = await self.broadcast(
                msg={"op": "get_census", "deep": True}
            )
        if "flight_recorder" not in (exclude or ()):
            # every node's causal tail ships in the dump by default
            # (bounded, JSON-safe): chaos post-mortems can join the
            # scheduler's ingress/engine/egress hops against each
            # worker's stimulus events without a live cluster.  The two
            # cluster-wide broadcasts are independent: gather them.
            scheduler_info["flight_recorder"] = self.trace.tail(500)
            out["worker_traces"], out["workers"] = await asyncio.gather(
                self.broadcast(msg={"op": "get_trace", "n": 200}),
                self.broadcast(msg={"op": "identity"}),
            )
        else:
            out["workers"] = await self.broadcast(msg={"op": "identity"})
        return out

    def _counts_json(self) -> dict:
        s = self.state
        by_state: dict[str, int] = {}
        for ts in s.tasks.values():
            by_state[ts.state] = by_state.get(ts.state, 0) + 1
        return {
            "tasks": len(s.tasks),
            "states": by_state,
            "workers": len(s.workers),
            "clients": len(s.clients),
            "queued": len(s.queued),
            "unrunnable": len(s.unrunnable),
        }

    async def log_event_handler(self, topic: Any = None, msg: Any = None) -> None:
        self.log_event(topic or "all", msg)

    def log_event(self, topic: Any, msg: Any) -> None:
        """Record + fan out to subscribed clients (reference scheduler.py:8244)."""
        self.state.log_event(topic, msg)

    def _fan_out_event(self, topics: list, msg: Any) -> None:
        for t in topics:
            for client in self._topic_subscribers.get(t, ()):
                self.report(
                    {"op": "event", "topic": t, "msg": msg}, client=client
                )

    def subscribe_topic(self, topic: str = "", client: str = "", **kw: Any) -> None:
        self._topic_subscribers.setdefault(topic, set()).add(client)

    def unsubscribe_topic(self, topic: str = "", client: str = "", **kw: Any) -> None:
        self._topic_subscribers.get(topic, set()).discard(client)

    def handle_client_log_event(self, topic: Any = None, msg: Any = None,
                                client: str = "", **kw: Any) -> None:
        self.log_event(topic or "all", msg)

    async def get_task_stream(self, start: float | None = None,
                              count: int | None = None) -> list:
        return self.task_stream.collect(start=start, count=count)

    async def get_profile(self, workers: list[str] | None = None,
                          start: float | None = None,
                          scope: str = "all") -> Any:
        """Merged profiles (reference scheduler.py:7991), with the
        scheduler's own control-plane tree in the merge.

        ``scope``: ``"workers"`` — executor trees from the fleet only
        (the pre-self-profiling behavior); ``"scheduler"`` — this
        process's control-plane tree only (no broadcast); ``"all"``
        (default) — both merged."""
        from distributed_tpu.diagnostics.profile import merge
        from distributed_tpu.protocol.serialize import unwrap

        if scope not in ("workers", "scheduler", "all"):
            raise ValueError(f"unknown profile scope {scope!r}")
        trees = []
        if scope in ("workers", "all"):
            resp = await self.broadcast(
                msg={"op": "profile", "start": start}, workers=workers
            )
            for v in resp.values():
                v = unwrap(v)
                if isinstance(v, dict) and "count" in v:
                    trees.append(v)
        if scope in ("scheduler", "all") and self.cp_profiler is not None:
            trees.append(self.cp_profiler.get_profile(start=start))
        return merge(*trees)

    async def get_events_handler(self, topic: str | None = None) -> Any:
        if topic is not None:
            return list(self.state.events.get(topic, ()))
        return {t: list(evs) for t, evs in self.state.events.items()}

    @property
    def dashboard_address(self) -> str | None:
        """http://host:port of the live dashboard, None before start.

        The host comes from the scheduler's ADVERTISED address, not the
        HTTP bind host: the latter defaults to 127.0.0.1, which would
        hand remote clients a link to their own loopback."""
        http = getattr(self, "http_server", None)
        if http is None:
            return None
        try:
            port = http.port
        except Exception:  # pragma: no cover - server not listening yet
            return None
        host = http.host
        try:
            from distributed_tpu.comm.addressing import parse_host_port

            adv = parse_host_port(self.address.split("://", 1)[-1])[0]
            if adv and adv not in ("0.0.0.0", ""):
                host = adv
        # graft-lint: allow[swallowed-exceptions] inproc:// has no host:port; keep the bind host
        except Exception:
            pass
        return f"http://{host}:{port}"

    def get_computations(self) -> list[dict]:
        """Recent update_graph batches, newest last (reference
        Scheduler.computations, scheduler.py:864)."""
        return [
            {
                "id": comp.id,
                "start": comp.start,
                "stop": comp.stop,
                "groups": sorted(tg.name for tg in comp.groups),
                "states": comp.states,
            }
            for comp in self.state.computations
        ]

    def eventstream_start(self, client: str = "") -> str:
        """Install the opt-in per-task event publisher (reference
        diagnostics/eventstream.py:12); consumers subscribe to the
        returned topic.  Opt-in because it costs a ring-buffer append
        plus subscriber fan-out on EVERY task completion.  Refcounted:
        the plugin is global, so one consumer's stop must not kill the
        stream for the others.  Passing ``client`` ties the reference to
        that client's lifetime — released automatically when the client
        disconnects (anonymous references require an explicit stop)."""
        from distributed_tpu.diagnostics.eventstream import EventStreamPlugin

        self._eventstream_refs += 1
        if client:
            self._eventstream_clients[client] = (
                self._eventstream_clients.get(client, 0) + 1
            )
        else:
            self._eventstream_anon += 1
        if EventStreamPlugin.name not in self.state.plugins:
            EventStreamPlugin(self)
        return EventStreamPlugin.topic

    def eventstream_stop(self, client: str = "") -> None:
        # an unmatched/double stop (tied OR anonymous) must not steal a
        # reference another live consumer still holds
        if client:
            held = self._eventstream_clients.get(client, 0)
            if not held:
                return
            if held == 1:
                del self._eventstream_clients[client]
            else:
                self._eventstream_clients[client] = held - 1
        else:
            if not self._eventstream_anon:
                return
            self._eventstream_anon -= 1
        self._release_eventstream_refs(1)

    def _release_eventstream_refs(self, n: int) -> None:
        from distributed_tpu.diagnostics.eventstream import EventStreamPlugin

        self._eventstream_refs = max(self._eventstream_refs - n, 0)
        if not self._eventstream_refs:
            self.state.plugins.pop(EventStreamPlugin.name, None)

    async def identity(self) -> dict:
        """Cluster snapshot; shape documented by
        ``utils.objects.SchedulerInfo`` (reference objects.py)."""
        return {
            "type": type(self).__name__,
            "id": self.id,
            "address": self.address,
            "dashboard": self.dashboard_address,
            "workers": {
                addr: {
                    "name": ws.name,
                    "nthreads": ws.nthreads,
                    "memory_limit": ws.memory_limit,
                    "status": str(getattr(ws, "status", "running")),
                }
                for addr, ws in self.state.workers.items()
            },
        }

    def __repr__(self) -> str:
        try:
            addr = self.address
        except ValueError:
            addr = "not-listening"
        return (
            f"<Scheduler {addr!r} workers={len(self.state.workers)} "
            f"tasks={len(self.state.tasks)}>"
        )


def _coalesce_worker_stream_msgs(msgs: list[dict]) -> list[dict]:
    """Fold consecutive same-op runs bound for one worker into batch
    messages: N ``compute-task`` dicts become one ``compute-tasks``
    envelope (each inner message keeps its own stimulus_id — causal
    stories survive), and adjacent ``free-keys`` with the SAME
    stimulus_id merge their key lists.  Only consecutive runs merge, so
    cross-op ordering (a free-keys fencing a later compute-task of the
    same key) is preserved exactly.  Never mutates input messages: the
    state machine shares message dicts across destinations."""
    if len(msgs) < 2:
        return msgs
    out: list[dict] = []
    for m in msgs:
        prev = out[-1] if out else None
        op = m.get("op")
        if op == "compute-task" and prev is not None:
            if prev.get("op") == "compute-tasks":
                prev["tasks"].append(m)
                continue
            if prev.get("op") == "compute-task":
                out[-1] = {"op": "compute-tasks", "tasks": [prev, m]}
                continue
        elif (
            op == "free-keys"
            and prev is not None
            and prev.get("op") == "free-keys"
            and prev.get("stimulus_id") == m.get("stimulus_id")
        ):
            out[-1] = {
                **prev,
                "keys": list(prev["keys"]) + list(m["keys"]),
            }
            continue
        out.append(m)
    return out
