"""Scheduler durability: incremental snapshots + journal-tail restart.

The scheduler is the one place where total state loss is possible — the
workers keep their data and their state machines across a scheduler
bounce, but the placement/replica/interest truth lived only in
``SchedulerState``.  This module turns PR 10's replayable stimulus
journal into real durability (ROADMAP item 2):

- **Incremental snapshots** — a versioned, digest-stamped serialization
  of the scheduler's task/worker/replica truth.  A *base* snapshot
  serializes everything; a *delta* snapshot re-serializes only the rows
  the :class:`DurabilityTracker` marked dirty since the previous epoch
  (task rows are the big axis and cost O(changed); worker/client/stat
  rows are small and ride every epoch).  Snapshots are written through
  ``tracing.atomic_write_bytes`` (temp + fsync + rename + dir fsync),
  so a reader sees the old epoch or the new one, never a torn file.

- **Journal segments** — the flight recorder's bounded in-memory
  journal deque gains an append-only on-disk tail: a sink installed on
  ``FlightRecorder.journal_sink`` captures every record the moment it
  is journaled, so the capture stays complete even after the deque
  evicts its head (the eviction race ``verify_journal`` can only
  detect).  Segments rotate with snapshot epochs: segment *e* holds
  exactly the records ``[watermark_e, watermark_{e+1})``, where
  ``watermark_e`` is the journal ``seq`` at the instant snapshot *e*
  was encoded — snapshots run between stream payloads, so a watermark
  always falls on an engine-batch boundary.

- **Restore** — fold base + deltas into an effective snapshot, rebuild
  a fresh ``SchedulerState`` through the same helpers the engine uses
  (``new_task`` / ``add_worker_state`` / ``add_replica``), verify the
  rebuilt state's structural digest where the snapshot carries one,
  then replay the journal tail through the real batched engine
  (``diagnostics.flight_recorder.replay_stimulus_trace``).  The
  deterministic proof that snapshot + tail reconstructs the pre-crash
  state bit-identically is ``sim/chaos.py::scenario_scheduler_bounce``.

Integrity failures raise *typed* errors (:class:`SnapshotVersionError`,
:class:`SnapshotCorruptError`, :class:`JournalCorruptError`) instead of
replaying garbage.  The one tolerated artifact is a torn FINAL line of
the FINAL journal segment: journal appends are not atomic, so a crash
mid-append leaves exactly that, and the record was never durable —
it is dropped and counted (docs/durability.md).

This module is in the sans-io lint scope: it never opens files itself
(byte IO is delegated to the ``tracing`` helpers or an injected sink —
the simulator runs everything against :class:`MemorySink`), defines no
coroutines, and stamps every duration with the monotonic clock.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import pickle
from typing import Any, Iterable

from distributed_tpu import config
from distributed_tpu.tracing import (
    append_jsonl,
    atomic_write_bytes,
    read_file_bytes,
    stamp_digests,
    to_jsonl,
)
from distributed_tpu.utils import OrderedSet, time

logger = logging.getLogger("distributed_tpu.durability")

#: bump when a snapshot row field is added/renamed/retyped; every
#: snapshot header carries it and the loader refuses mismatches
SNAPSHOT_SCHEMA_VERSION = 1


class DurabilityError(Exception):
    """Base for snapshot/journal integrity failures."""


class SnapshotVersionError(DurabilityError):
    """Snapshot written by an incompatible schema version."""


class SnapshotCorruptError(DurabilityError):
    """Snapshot fails its digest / structure checks."""


class JournalCorruptError(DurabilityError):
    """Journal segment fails digest / contiguity / parse checks
    anywhere but the tolerated torn final line."""


# ------------------------------------------------------------ run specs


class OpaqueSpec:
    """Placeholder for a run_spec that could not be round-tripped
    (non-picklable object): truthy so the scheduler still schedules the
    task, stable repr so journal digests survive a dump/load cycle.
    A worker can never execute one — callers that need real dispatch
    must journal picklable or frame-based specs."""

    __slots__ = ("text",)

    def __init__(self, text: str):
        self.text = text

    def __repr__(self) -> str:
        return self.text

    def __eq__(self, other: object) -> bool:
        return isinstance(other, OpaqueSpec) and other.text == self.text

    def __hash__(self) -> int:
        return hash(self.text)


def _b64(b: Any) -> str:
    return base64.b64encode(bytes(b)).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s.encode("ascii"))


def encode_run_spec(spec: Any) -> Any:
    """JSON-pure encoding of a run_spec (or exception payload).

    Handles the shapes the scheduler actually holds: ``None``, opaque
    ``Serialized``/``Pickled`` frame wrappers (frames copied to owned
    bytes — they may still be zero-copy views of a pooled receive
    buffer), JSON literals, and picklable objects.  Anything else
    degrades to an :class:`OpaqueSpec` repr marker — schedulable, not
    executable."""
    from distributed_tpu.protocol.serialize import Pickled, Serialized

    if spec is None:
        return None
    if isinstance(spec, (str, int, float, bool)):
        return {"t": "lit", "v": spec}
    if isinstance(spec, (Serialized, Pickled)):
        return {
            "t": "frames",
            "cls": type(spec).__name__,
            "header": _b64(pickle.dumps(spec.header)),
            "frames": [_b64(f) for f in spec.frames],
        }
    try:
        return {"t": "pickle", "v": _b64(pickle.dumps(spec))}
    except Exception:
        return {"t": "opaque", "r": repr(spec)}


def decode_run_spec(obj: Any) -> Any:
    from distributed_tpu.protocol.serialize import Pickled, Serialized

    if obj is None:
        return None
    t = obj.get("t") if isinstance(obj, dict) else None
    if t == "lit":
        return obj["v"]
    if t == "frames":
        cls = Serialized if obj.get("cls") != "Pickled" else Pickled
        return cls(
            pickle.loads(_unb64(obj["header"])),
            [_unb64(f) for f in obj["frames"]],
        )
    if t == "pickle":
        return pickle.loads(_unb64(obj["v"]))
    if t == "opaque":
        return OpaqueSpec(obj.get("r", "<opaque>"))
    # a raw journal payload that predates encoding (or a test literal)
    return obj


# -------------------------------------------------------- dirty tracking


class DurabilityTracker:
    """Dirty-row tracker attached as ``state.durability``.

    Out-of-engine mutation helpers (``add_replica``, ``update_nbytes``,
    worker lifecycle, client interest) call the ``mark_*`` hooks
    directly — the same seams the fleet mirror marks through.  Engine
    transitions mark through :meth:`mark_transition`, called from the
    oracle's ``_transition`` funnel and from each of the native tape
    replay's transition arms (NOT the generic plugin seam — the plugin
    dispatch machinery costs more per transition than the mark itself,
    and durability capture must stay inside the steady-state flood
    budget).  Marks are O(1) dict writes plus the transitioned task's
    relation neighborhood (a transition mutates its dependents'
    ``waiting_on``/``waiters`` rows too)."""

    def __init__(self, state: Any):
        self.state = state
        # insertion-ordered: new tasks appear in creation order, so a
        # delta's fresh rows append to the fold in creation order
        self.dirty_tasks: dict[str, None] = {}
        self.removed_tasks: dict[str, None] = {}
        self.dirty_workers: dict[str, None] = {}
        self.removed_workers: dict[str, None] = {}

    # one engine transition landed for ``ts`` (hot path: keep lean)
    def mark_transition(self, ts: Any) -> None:
        d = self.dirty_tasks
        d[ts.key] = None
        for dts in ts.dependents:
            d[dts.key] = None
        for dts in ts.dependencies:
            d[dts.key] = None

    def mark_task(self, ts: Any) -> None:
        self.dirty_tasks[ts.key] = None

    def mark_worker(self, ws: Any) -> None:
        self.dirty_workers[ws.address] = None

    def mark_replica(self, ts: Any, ws: Any) -> None:
        self.dirty_tasks[ts.key] = None
        self.dirty_workers[ws.address] = None

    def on_remove_task(self, ts: Any) -> None:
        self.dirty_tasks.pop(ts.key, None)
        self.removed_tasks[ts.key] = None

    def on_remove_worker(self, ws: Any) -> None:
        self.dirty_workers.pop(ws.address, None)
        self.removed_workers[ws.address] = None

    def drain(self) -> tuple[list[str], list[str], list[str], list[str]]:
        # deferred native segments carry their own dirty marks (the
        # replay appliers call mark_transition/mark_replica): a delta
        # snapshot taken after a purely-native flood must force replay
        # first or it would capture an empty dirty set
        ne = getattr(self.state, "native", None)
        if ne is not None and ne._pending:
            ne.sync()
        out = (
            list(self.dirty_tasks), list(self.removed_tasks),
            list(self.dirty_workers), list(self.removed_workers),
        )
        self.dirty_tasks.clear()
        self.removed_tasks.clear()
        self.dirty_workers.clear()
        self.removed_workers.clear()
        return out


# ------------------------------------------------------------ row codecs


def _enc_opaque(obj: Any) -> Any:
    """Exceptions / tracebacks: same encoding as run specs."""
    return encode_run_spec(obj)


def _task_row(state: Any, ts: Any) -> dict:
    row: dict[str, Any] = {
        "k": ts.key,
        "st": ts.state,
        "pri": list(ts.priority) if ts.priority is not None else None,
        "spec": encode_run_spec(ts.run_spec),
        "deps": [d.key for d in ts.dependencies],
        "won": [d.key for d in ts.waiting_on],
        "wtr": [d.key for d in ts.waiters],
        "wants": [cs.client_key for cs in ts.who_wants],
        "has": [ws.address for ws in ts.who_has],
        "nb": ts.nbytes,
    }
    ws = ts.processing_on
    if ws is not None:
        row["proc"] = ws.address
        row["booked"] = repr(ws.processing.get(ts, 0.0))
        if ts in ws.long_running:
            row["lrun"] = True
    if ts.type:
        row["type"] = ts.type
    if ts.exception is not None:
        row["exc"] = _enc_opaque(ts.exception)
    if ts.traceback is not None:
        row["tb"] = _enc_opaque(ts.traceback)
    if ts.exception_text:
        row["extext"] = ts.exception_text
    if ts.traceback_text:
        row["tbtext"] = ts.traceback_text
    if ts.exception_blame is not None:
        row["blame"] = ts.exception_blame.key
    if ts.erred_on:
        # insertion order, not sorted: the restored OrderedSet must
        # iterate exactly like the original (free-keys message order)
        row["erred_on"] = list(ts.erred_on)
    if ts.suspicious:
        row["susp"] = ts.suspicious
    if ts.retries:
        row["retry"] = ts.retries
    if ts.host_restrictions is not None:
        row["hostr"] = sorted(ts.host_restrictions)
    if ts.worker_restrictions is not None:
        row["workr"] = sorted(ts.worker_restrictions)
    if ts.resource_restrictions is not None:
        row["resr"] = dict(ts.resource_restrictions)
    if ts.loose_restrictions:
        row["loose"] = True
    if ts.actor:
        row["actor"] = True
    if ts.annotations is not None:
        row["ann"] = ts.annotations
    if ts.metadata is not None:
        row["meta"] = _enc_opaque(ts.metadata)
    if ts.run_id is not None:
        row["runid"] = ts.run_id
    if not ts.queueable:
        row["qable"] = False
    if ts.homed:
        row["homed"] = ts.homed if isinstance(ts.homed, str) else True
    prefix = ts.prefix
    if prefix is not None and ts in state.unknown_durations.get(prefix.name, ()):
        row["unkdur"] = True
    return row


def _worker_row(state: Any, ws: Any, with_orders: bool = True) -> dict:
    row: dict[str, Any] = {
        "a": ws.address,
        "name": ws.name if isinstance(ws.name, (str, int, float)) else str(ws.name),
        "nthreads": ws.nthreads,
        "mem": ws.memory_limit,
        "status": ws.status,
        "sseq": ws.status_seq,
        "sid": ws.server_id,
        "occ": repr(ws.occupancy),
        "nocc": ws._network_occ,
        "bw": repr(ws.bandwidth),
    }
    if ws.resources:
        row["resources"] = dict(ws.resources)
    if ws.used_resources:
        row["used"] = dict(ws.used_resources)
    if ws.extra:
        row["extra"] = _enc_opaque(dict(ws.extra))
    if with_orders:
        # insertion orders of the per-worker mirrors: who_has/processing
        # iteration order feeds victim scans and removal cascades, so
        # restore must reproduce it exactly (booked values live on the
        # task rows; these lists carry order + membership only)
        row["haso"] = [ts.key for ts in ws.has_what]
        row["proco"] = [ts.key for ts in ws.processing]
    return row


def _prefix_row(tp: Any) -> dict:
    return {
        "p": tp.name,
        "avg": repr(tp.duration_average),
        "maxexec": repr(tp.max_exec_time),
        "nb": tp.nbytes_total,
        "ndur": tp.n_durations,
        "counts": dict(tp.state_counts),
    }


def _group_row(tg: Any) -> dict:
    return {
        "g": tg.name,
        "states": dict(tg.states),
        "gdeps": sorted(g.name for g in tg.dependencies),
        "nb": tg.nbytes_total,
        "dur": repr(tg.duration),
        "types": sorted(tg.types),
        "start": repr(tg.start),
        "stop": repr(tg.stop),
        "lw": tg.last_worker.address if tg.last_worker is not None else None,
        "lwtl": tg.last_worker_tasks_left,
        "span": tg.span_id,
        "n": tg.n_tasks,
    }


def _stealing_rows(state: Any) -> dict | None:
    """In-flight steal state (the stealing extension's cross-payload
    truth): a steal-request answered after a restart must find its
    ``in_flight`` entry or the confirmed move is silently dropped."""
    steal = state.extensions.get("stealing") if state.extensions else None
    if steal is None:
        return None
    return {
        "in_flight": [
            {
                "k": key,
                "victim": info.victim.address,
                "thief": info.thief.address,
                "vd": repr(info.victim_duration),
                "td": repr(info.thief_duration),
                "stim": info.stimulus_id,
            }
            for key, info in steal.in_flight.items()
        ],
        "key_stealable": [
            # levels were computed with entry-time duration priors;
            # recomputing at restore would re-bucket tasks and diverge
            # the next balance cycle from the unbounced twin
            [key, addr, level]
            for key, (addr, level) in steal.key_stealable.items()
        ],
        "rr": steal._rr,
        "count": steal.count,
    }


def snapshot_rows(state: Any, *, full: bool,
                  tracker: DurabilityTracker | None = None) -> dict:
    """Collect the serialized rows of one snapshot.  ``full=False``
    serializes only tracker-dirty task rows (plus removals); worker /
    client / prefix / group / queue / extension rows are small and ride
    every epoch (worker order lists only when the worker is dirty)."""
    if full or tracker is None:
        task_keys = list(state.tasks)
        removed_tasks: list[str] = []
        dirty_workers = set(state.workers)
        if tracker is not None:
            tracker.drain()
    else:
        dirty, removed, dws, removed_ws = tracker.drain()
        task_keys = [k for k in dirty if k in state.tasks]
        removed_tasks = removed
        dirty_workers = set(dws)

    prefixes: dict[str, Any] = {}
    groups: dict[str, Any] = {}
    task_rows = []
    for k in task_keys:
        ts = state.tasks.get(k)
        if ts is None:
            continue
        task_rows.append(_task_row(state, ts))
        if ts.prefix is not None:
            prefixes[ts.prefix.name] = ts.prefix
        if ts.group is not None:
            groups[ts.group.name] = ts.group

    queued_order = _heap_order(state.queued)
    rows = {
        "tasks": task_rows,
        "removed_tasks": removed_tasks,
        "workers": [
            _worker_row(state, ws, with_orders=full or ws.address in dirty_workers)
            for ws in state.workers.values()
        ],
        "removed_workers": (
            [] if full or tracker is None else removed_ws
        ),
        "clients": [
            {"c": cs.client_key, "seen": repr(cs.last_seen)}
            for cs in state.clients.values()
        ],
        "prefixes": [_prefix_row(tp) for tp in prefixes.values()],
        "groups": [_group_row(tg) for tg in groups.values()],
        # queue structures in exact pop order (priority, add ordinal):
        # re-adding in this order reproduces pop order on the restored
        # heaps even across priority ties
        "queued": [ts.key for ts in queued_order],
        "parked": {
            addr: [ts.key for ts in _heap_order(heap)]
            for addr, heap in state.parked.items()
        },
        "unrunnable": [
            [ts.key, repr(since)] for ts, since in state.unrunnable.items()
        ],
        # membership sets in current iteration order: re-inserting in
        # this order reproduces scan order for same-process restores
        "idle": list(state.idle),
        "idle_task_count": [ws.address for ws in state.idle_task_count],
        "saturated": [ws.address for ws in state.saturated],
        "scalars": {
            "transition_counter": state.transition_counter,
            "n_tasks": state.n_tasks,
            "total_occupancy": repr(state._total_occupancy),
        },
        "ext": _stealing_rows(state),
    }
    return rows


def _heap_order(heap: Any) -> list:
    """Elements of a HeapSet in exact pop order (priority, add
    ordinal) — reaches into the heap's token map, which is the only
    place the add ordinal survives."""
    return sorted(heap._data, key=lambda el: (heap.key(el), heap._token[el]))


# ---------------------------------------------------------------- digest


def state_digest(state: Any) -> str:
    """Structural digest of the scheduler truth a restore must
    reproduce: task states/relations/assignments, worker scalars and
    mirrors, queue contents and order, interest, decision-relevant
    prefix/group statistics, and the engine counters.  Diagnostics
    (transition_log, events, computations, telemetry, ledger) are
    deliberately outside the contract — docs/durability.md."""
    h = hashlib.blake2b(digest_size=16)

    def put(*parts: Any) -> None:
        h.update(("\x1e".join(repr(p) for p in parts) + "\n").encode())

    put("scalars", state.transition_counter, state.n_tasks,
        repr(state._total_occupancy), state.total_nthreads)
    for key, ts in state.tasks.items():
        ws = ts.processing_on
        put(
            "task", key, ts.state, ts.priority, ts.nbytes,
            tuple(d.key for d in ts.dependencies),
            tuple(d.key for d in ts.waiting_on),
            tuple(d.key for d in ts.waiters),
            tuple(sorted(cs.client_key for cs in ts.who_wants)),
            tuple(w.address for w in ts.who_has),
            ws.address if ws is not None else None,
            repr(ws.processing.get(ts, 0.0)) if ws is not None else "",
            ts.suspicious, ts.retries, ts.homed, ts.actor,
            ts.exception_text, ts.run_spec is not None,
        )
    for addr, ws in state.workers.items():
        put(
            "worker", addr, ws.status, ws.nthreads, ws.memory_limit,
            repr(ws.occupancy), ws.nbytes, ws._network_occ,
            tuple(ts.key for ts in ws.has_what),
            tuple(ts.key for ts in ws.processing),
            tuple(sorted(ts.key for ts in ws.long_running)),
            ws.status_seq,
        )
    put("queued", tuple(ts.key for ts in _heap_order(state.queued)))
    put("parked", tuple(
        (addr, tuple(ts.key for ts in _heap_order(heap)))
        for addr, heap in sorted(state.parked.items())
    ))
    put("unrunnable", tuple(
        (ts.key, repr(since)) for ts, since in state.unrunnable.items()
    ))
    put("idle", tuple(state.idle))
    put("running", tuple(sorted(ws.address for ws in state.running)))
    for name in sorted(state.task_prefixes):
        tp = state.task_prefixes[name]
        put("prefix", name, repr(tp.duration_average),
            repr(tp.max_exec_time), tp.nbytes_total, tp.n_durations)
    for name in sorted(state.task_groups):
        tg = state.task_groups[name]
        put("group", name, sorted(tg.states.items()), tg.nbytes_total,
            tg.last_worker.address if tg.last_worker is not None else None,
            tg.last_worker_tasks_left, tg.n_tasks)
    return h.hexdigest()


# ------------------------------------------------------------- snapshots


def encode_snapshot(rows: dict, *, epoch: int, base: bool,
                    journal_seq: int, state_dig: str | None = None) -> bytes:
    """One snapshot file: canonical JSON with a blake2b digest stamped
    over the body — the loader rejects any bit rot the atomic-rename
    write discipline didn't already prevent."""
    body = {
        "kind": "dtpu-snapshot",
        "v": SNAPSHOT_SCHEMA_VERSION,
        "epoch": int(epoch),
        "base": bool(base),
        "journal_seq": int(journal_seq),
        "state_digest": state_dig,
        "rows": rows,
    }
    blob = json.dumps(body, default=repr, sort_keys=True,
                      separators=(",", ":")).encode()
    digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
    return json.dumps({"d": digest, "body": body}, default=repr,
                      separators=(",", ":")).encode()


def parse_snapshot(blob: bytes) -> dict:
    try:
        outer = json.loads(blob)
        body = outer["body"]
        want = outer["d"]
    except Exception as exc:
        raise SnapshotCorruptError(
            f"snapshot does not parse: {exc}"
        ) from exc
    check = json.dumps(body, default=repr, sort_keys=True,
                       separators=(",", ":")).encode()
    if hashlib.blake2b(check, digest_size=16).hexdigest() != want:
        raise SnapshotCorruptError(
            "snapshot fails its digest (bit rot or a hand edit); "
            "refusing to restore from it"
        )
    if body.get("kind") != "dtpu-snapshot":
        raise SnapshotCorruptError(
            f"not a durability snapshot: kind={body.get('kind')!r}"
        )
    v = body.get("v")
    if v != SNAPSHOT_SCHEMA_VERSION:
        raise SnapshotVersionError(
            f"snapshot schema v{v} != supported v{SNAPSHOT_SCHEMA_VERSION}; "
            "refusing to restore a mismatched format"
        )
    return body


def fold_snapshots(bodies: list[dict]) -> dict:
    """Fold a base snapshot + following deltas into one effective row
    set.  Updated task rows replace in place (dict order — the tasks
    dict's insertion order — is preserved); fresh rows append in their
    creation order; removals apply before the epoch's rows."""
    if not bodies:
        raise SnapshotCorruptError("no snapshot bodies to fold")
    if not bodies[0].get("base"):
        raise SnapshotCorruptError(
            f"fold must start at a base snapshot (epoch "
            f"{bodies[0].get('epoch')} is a delta)"
        )
    tasks: dict[str, dict] = {}
    workers: dict[str, dict] = {}
    out = dict(bodies[0]["rows"])
    for body in bodies:
        rows = body["rows"]
        for k in rows.get("removed_tasks", ()):
            tasks.pop(k, None)
        for row in rows.get("tasks", ()):
            tasks[row["k"]] = row
        for a in rows.get("removed_workers", ()):
            workers.pop(a, None)
        live = {row["a"] for row in rows.get("workers", ())}
        for a in list(workers):
            if a not in live:
                # worker rows ride every epoch: absence = removal
                del workers[a]
        for row in rows.get("workers", ()):
            prev = workers.get(row["a"])
            if prev is not None and "haso" not in row:
                # scalar-only row: keep the last recorded order lists
                # (no replica/processing membership change since)
                merged = dict(prev)
                merged.update(row)
                row = merged
            workers[row["a"]] = row
        # small whole-families: later epochs replace member-wise
        for fam, key in (("prefixes", "p"), ("groups", "g")):
            if rows.get(fam):
                merged_fam = {r[key]: r for r in out.get(fam, ())}
                for r in rows[fam]:
                    merged_fam[r[key]] = r
                out[fam] = list(merged_fam.values())
        for fam in ("clients", "queued", "parked", "unrunnable", "idle",
                    "idle_task_count", "saturated", "scalars", "ext"):
            if fam in rows:
                out[fam] = rows[fam]
    out["tasks"] = list(tasks.values())
    out["workers"] = list(workers.values())
    out["removed_tasks"] = []
    out["removed_workers"] = []
    return out


def _f(s: Any) -> float:
    return float(s) if not isinstance(s, float) else s


def restore_state(state: Any, rows: dict) -> None:
    """Rebuild a fresh ``SchedulerState`` from folded snapshot rows,
    through the engine's own registration helpers so the mirror /
    native SoA / ledger see a normally-built state.  ``state`` must be
    empty (fresh construction)."""
    tasks = state.tasks
    workers = state.workers

    # -- workers: registration first (tasks reference them) -----------
    for row in rows.get("workers", ()):
        ws = state.add_worker_state(
            row["a"], nthreads=int(row.get("nthreads") or 1),
            memory_limit=int(row.get("mem") or 0),
            name=row.get("name"), resources=row.get("resources") or None,
            server_id=row.get("sid"),
        )
        ws.status_seq = int(row.get("sseq") or 0)
        ws.bandwidth = _f(row.get("bw", ws.bandwidth))
        extra = decode_run_spec(row.get("extra"))
        if isinstance(extra, dict):
            ws.extra.update(extra)
        status = row.get("status", "running")
        if status != ws.status:
            state.set_worker_status(ws, status)
            if status != "running":
                state.running.discard(ws)

    # -- tasks pass 1: rows in creation order -------------------------
    for row in rows.get("tasks", ()):
        ts = state.new_task(
            row["k"], decode_run_spec(row.get("spec")), row.get("st", "released")
        )
        pri = row.get("pri")
        ts.priority = tuple(pri) if pri is not None else None
        ts.nbytes = int(row.get("nb", -1))
        ts.type = row.get("type")
        ts.exception = decode_run_spec(row.get("exc"))
        ts.traceback = decode_run_spec(row.get("tb"))
        ts.exception_text = row.get("extext", "")
        ts.traceback_text = row.get("tbtext", "")
        if row.get("erred_on"):
            ts.erred_on = OrderedSet(row["erred_on"])
        ts.suspicious = int(row.get("susp", 0))
        ts.retries = int(row.get("retry", 0))
        if row.get("hostr") is not None:
            ts.host_restrictions = set(row["hostr"])
        if row.get("workr") is not None:
            ts.worker_restrictions = set(row["workr"])
        if row.get("resr") is not None:
            ts.resource_restrictions = dict(row["resr"])
        ts.loose_restrictions = bool(row.get("loose"))
        ts.actor = bool(row.get("actor"))
        if row.get("ann") is not None:
            ts.annotations = row["ann"]
        meta = decode_run_spec(row.get("meta"))
        if meta is not None:
            ts.metadata = meta
        ts.run_id = row.get("runid")
        ts.queueable = row.get("qable", True)
        homed = row.get("homed", False)
        ts.homed = homed if isinstance(homed, str) else bool(homed)

    # -- tasks pass 2: relations / assignments / interest -------------
    for row in rows.get("tasks", ()):
        ts = tasks[row["k"]]
        for dkey in row.get("deps", ()):
            dts = tasks.get(dkey)
            if dts is not None:
                ts.add_dependency(dts)
        for dkey in row.get("won", ()):
            dts = tasks.get(dkey)
            if dts is not None:
                ts.waiting_on.add(dts)
        for dkey in row.get("wtr", ()):
            dts = tasks.get(dkey)
            if dts is not None:
                ts.waiters.add(dts)
        blame = row.get("blame")
        if blame is not None:
            ts.exception_blame = tasks.get(blame)
        for cid in row.get("wants", ()):
            cs = state.add_client_state(cid)
            ts.who_wants.add(cs)
            cs.wants_what.add(ts)
        for addr in row.get("has", ()):
            ws = workers.get(addr)
            if ws is not None:
                state.add_replica(ts, ws)
        proc = row.get("proc")
        if proc is not None:
            ws = workers.get(proc)
            if ws is not None:
                # direct rebuild of the processing mirror: the booked
                # occupancy must restore bit-exact, not be re-derived
                # from current duration priors
                booked = _f(row.get("booked", "0.0"))
                ws.processing[ts] = booked  # graft-lint: allow[mirror-parity] restore-time rebuild; the worker row is re-marked wholesale below
                ts.processing_on = ws
                if row.get("lrun"):
                    ws.long_running.add(ts)
                if ts.actor:
                    ws.actors.add(ts)
        if row.get("unkdur") and ts.prefix is not None:
            state.unknown_durations.setdefault(
                ts.prefix.name, set()
            ).add(ts)

    # -- per-worker mirror orders (booked values came from task rows) -
    for row in rows.get("workers", ()):
        ws = workers.get(row["a"])
        if ws is None:
            continue
        if "haso" in row:
            order = [tasks[k] for k in row["haso"] if k in tasks]
            if set(order) == set(ws.has_what):
                ws.has_what = dict.fromkeys(order)  # graft-lint: allow[mirror-parity] order-only rebuild at restore; marked below
        if "proco" in row:
            order = [tasks[k] for k in row["proco"] if k in tasks]
            if set(order) == set(ws.processing):
                ws.processing = {t: ws.processing[t] for t in order}  # graft-lint: allow[mirror-parity] order-only rebuild at restore; marked below
        ws.occupancy = _f(row.get("occ", "0.0"))  # graft-lint: allow[mirror-parity] exact scalar restore; marked below
        ws._network_occ = int(row.get("nocc") or 0)
        if row.get("used"):
            ws.used_resources = dict(row["used"])
        if state.mirror is not None:
            state.mirror.mark(ws)
        if state.native is not None:
            state.native.mark_worker(ws)

    # -- clients ------------------------------------------------------
    for row in rows.get("clients", ()):
        cs = state.add_client_state(row["c"])
        cs.last_seen = _f(row.get("seen", "0.0"))

    # -- queues (exact pop order) -------------------------------------
    parked_keys = {
        k: addr
        for addr, keys in (rows.get("parked") or {}).items()
        for k in keys
    }
    for k in rows.get("queued", ()):
        ts = tasks.get(k)
        if ts is None:
            continue
        state.queued.add(ts)
        if k not in parked_keys:
            state.queued_unparked.add(ts)
    for addr, keys in (rows.get("parked") or {}).items():
        ws = workers.get(addr)
        for k in keys:
            ts = tasks.get(k)
            if ts is not None and ws is not None:
                state.park_task(ts, ws)
    for k, since in rows.get("unrunnable", ()):
        ts = tasks.get(k)
        if ts is not None:
            state.unrunnable[ts] = _f(since)

    # -- prefix / group statistics (decision inputs) ------------------
    for row in rows.get("prefixes", ()):
        tp = state.task_prefixes.get(row["p"])
        if tp is None:
            tp = state.new_task_prefix(row["p"])
        tp.duration_average = _f(row.get("avg", "-1.0"))
        tp.max_exec_time = _f(row.get("maxexec", "-1.0"))
        tp.nbytes_total = int(row.get("nb") or 0)
        tp.n_durations = int(row.get("ndur") or 0)
        tp.state_counts.clear()
        tp.state_counts.update(row.get("counts") or {})
    for row in rows.get("groups", ()):
        tg = state.task_groups.get(row["g"])
        if tg is None:
            continue
        tg.states = dict(row.get("states") or tg.states)
        tg.nbytes_total = int(row.get("nb") or 0)
        tg.duration = _f(row.get("dur", "0.0"))
        tg.types = set(row.get("types") or ())
        tg.start = _f(row.get("start", "0.0"))
        tg.stop = _f(row.get("stop", "0.0"))
        lw = row.get("lw")
        tg.last_worker = workers.get(lw) if lw else None
        tg.last_worker_tasks_left = int(row.get("lwtl") or 0)
        tg.span_id = row.get("span")
        tg.n_tasks = int(row.get("n") or tg.n_tasks)
        for gname in row.get("gdeps", ()):
            dep = state.task_groups.get(gname)
            if dep is not None:
                tg.dependencies.add(dep)

    # -- scalars + membership sets ------------------------------------
    scalars = rows.get("scalars") or {}
    state.transition_counter = int(scalars.get("transition_counter") or 0)
    state.n_tasks = int(scalars.get("n_tasks") or state.n_tasks)
    state._total_occupancy = _f(scalars.get("total_occupancy", "0.0"))
    # canonical membership from the model...
    for ws in workers.values():
        state.check_idle_saturated(ws)
    # ...then rebuilt in recorded iteration order (victim scans iterate
    # these; idle is a dict and the membership sets are OrderedSets, so
    # re-inserting in recorded order reproduces scan order exactly)
    idle_order = [a for a in rows.get("idle", ()) if a in state.idle]
    if set(idle_order) == set(state.idle):
        state.idle = {a: workers[a] for a in idle_order}
    for fam, recorded in (
        ("saturated", rows.get("saturated", ())),
        ("idle_task_count", rows.get("idle_task_count", ())),
    ):
        current = getattr(state, fam)
        rec_ws = [workers[a] for a in recorded if a in workers]
        if set(rec_ws) == current:
            setattr(state, fam, OrderedSet(rec_ws))


def restore_stealing(steal: Any, rows: dict | None) -> None:
    """Re-seed a freshly built WorkStealing extension from snapshot
    rows: the stealable index with its entry-time levels, the in-flight
    confirm windows, and the exact occupancy overlays."""
    state = steal.state
    if rows is None:
        # no recorded extension state: seed stealable from scratch for
        # tasks already processing at the restore point
        for ts in state.tasks.values():
            if ts.state == "processing":
                steal.put_key_in_stealable(ts)
        return
    for key, addr, level in rows.get("key_stealable", ()):
        ts = state.tasks.get(key)
        levels = steal.stealable.get(addr)
        if ts is None or levels is None or ts.state != "processing":
            continue
        levels[int(level)].add(ts)
        steal.key_stealable[key] = (addr, int(level))
    for row in rows.get("in_flight", ()):
        victim = state.workers.get(row["victim"])
        thief = state.workers.get(row["thief"])
        ts = state.tasks.get(row["k"])
        if victim is None or thief is None or ts is None:
            continue
        steal.seed_in_flight(
            ts, victim, thief, _f(row["vd"]), _f(row["td"]),
            row.get("stim", ""),
        )
    steal._rr = int(rows.get("rr") or 0)
    steal.count = int(rows.get("count") or 0)


# --------------------------------------------------------------- journal


def parse_journal_segment(
    blob: bytes, *, expected_seq: int | None, final: bool,
    label: str = "journal",
) -> tuple[list[dict], int]:
    """Parse one journal segment with integrity checks: every record's
    payload digest, schema version, and seq contiguity from
    ``expected_seq``.  A torn FINAL line of the FINAL segment is the
    documented crash artifact — dropped and counted, never an error;
    everything else raises :class:`JournalCorruptError`.  Returns
    ``(records, torn_lines)``."""
    from distributed_tpu.tracing import TRACE_SCHEMA_VERSION, payload_digest

    records: list[dict] = []
    torn = 0
    lines = blob.split(b"\n")
    # the torn-write allowance applies to exactly the LAST non-empty
    # line (a crash mid-append): a corrupt penultimate line must raise,
    # not be miscounted as the crash artifact with the real final
    # record silently dropped
    last_i = max(
        (i for i, ln in enumerate(lines) if ln.strip()), default=-1
    )
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        last = i == last_i
        try:
            rec = json.loads(line)
        except Exception as exc:
            if final and last:
                torn += 1
                logger.warning(
                    "%s: dropping torn final record (crash mid-append)",
                    label,
                )
                break
            raise JournalCorruptError(
                f"{label}: record at line {i} does not parse mid-segment "
                f"({exc}); refusing to replay past corruption"
            ) from exc
        v = rec.get("v")
        if v != TRACE_SCHEMA_VERSION:
            raise JournalCorruptError(
                f"{label}: record seq {rec.get('seq')} carries schema "
                f"v{v} != supported v{TRACE_SCHEMA_VERSION}"
            )
        want = rec.get("digest")
        if not want or payload_digest(rec.get("payload")) != want:
            raise JournalCorruptError(
                f"{label}: record seq {rec.get('seq')} (op "
                f"{rec.get('op')!r}) fails its payload digest"
            )
        seq = rec.get("seq")
        if expected_seq is not None and seq != expected_seq:
            raise JournalCorruptError(
                f"{label}: seq {seq} breaks contiguity (expected "
                f"{expected_seq}) — a span was evicted or lost"
            )
        if expected_seq is not None:
            expected_seq += 1
        records.append(rec)
    return records, torn


# ----------------------------------------------------------------- sinks


class MemorySink:
    """In-memory sink (the simulator's substrate, and the unit tests'):
    same byte-level semantics as :class:`FileSink`, no filesystem."""

    def __init__(self):
        self.snapshots: dict[int, bytes] = {}
        self.journals: dict[int, bytearray] = {}

    def write_snapshot(self, epoch: int, blob: bytes) -> int:
        self.snapshots[epoch] = bytes(blob)
        return len(blob)

    def append_journal(self, epoch: int, records: list[dict]) -> int:
        stamp_digests(records)
        blob = to_jsonl(records).encode()
        self.journals.setdefault(epoch, bytearray()).extend(blob)
        return len(blob)

    def read_snapshot(self, epoch: int) -> bytes:
        return self.snapshots[epoch]

    def read_journal(self, epoch: int) -> bytes:
        return bytes(self.journals.get(epoch, b""))

    def snapshot_epochs(self) -> list[int]:
        return sorted(self.snapshots)

    def journal_epochs(self) -> list[int]:
        return sorted(self.journals)


class FileSink:
    """On-disk sink: ``snap-<epoch>.json`` via fsync'd atomic rename,
    ``journal-<epoch>.jsonl`` append-only (fsync per flush).  File IO
    is delegated to the ``tracing`` helpers (this module stays in the
    sans-io lint scope)."""

    def __init__(self, directory: str, fsync_journal: bool = True):
        self.directory = directory
        self.fsync_journal = bool(fsync_journal)
        os.makedirs(directory, exist_ok=True)

    def _snap_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"snap-{epoch:08d}.json")

    def _journal_path(self, epoch: int) -> str:
        return os.path.join(self.directory, f"journal-{epoch:08d}.jsonl")

    def write_snapshot(self, epoch: int, blob: bytes) -> int:
        return atomic_write_bytes(self._snap_path(epoch), blob)

    def append_journal(self, epoch: int, records: list[dict]) -> int:
        stamp_digests(records)
        return append_jsonl(
            self._journal_path(epoch), records, fsync=self.fsync_journal
        )

    def read_snapshot(self, epoch: int) -> bytes:
        return read_file_bytes(self._snap_path(epoch))

    def read_journal(self, epoch: int) -> bytes:
        try:
            return read_file_bytes(self._journal_path(epoch))
        except FileNotFoundError:
            return b""

    def _epochs(self, prefix: str, suffix: str) -> list[int]:
        out = []
        for fn in os.listdir(self.directory):
            if fn.startswith(prefix) and fn.endswith(suffix):
                try:
                    out.append(int(fn[len(prefix):-len(suffix)]))
                except ValueError:
                    continue
        return sorted(out)

    def snapshot_epochs(self) -> list[int]:
        return self._epochs("snap-", ".json")

    def journal_epochs(self) -> list[int]:
        return self._epochs("journal-", ".jsonl")


# ---------------------------------------------------------------- manager


class DurabilityStats:
    """Counters exposed as ``dtpu_durability_*`` (http/server.py;
    docs/observability.md)."""

    __slots__ = (
        "snapshot_seconds", "snapshot_bytes", "snapshot_rows",
        "epochs", "base_epochs", "journal_records", "journal_bytes",
        "replay_records", "restore_seconds", "torn_records",
        "reconcile_corrections",
    )

    def __init__(self):
        self.snapshot_seconds = 0.0
        self.snapshot_bytes = 0
        self.snapshot_rows = 0
        self.epochs = 0
        self.base_epochs = 0
        self.journal_records = 0
        self.journal_bytes = 0
        self.replay_records = 0
        self.restore_seconds = 0.0
        self.torn_records = 0
        self.reconcile_corrections = 0


class DurabilityManager:
    """Owns one scheduler state's durable capture: the dirty tracker,
    the journal segment writer, and epoch bookkeeping.

    The manager is sans-io in the sense that matters: ``snapshot()``
    encodes on the caller's thread (the event loop, between payloads —
    O(changed rows)) and hands bytes to the sink; the live server runs
    the sink writes on an executor thread, the simulator's MemorySink
    is a dict store.  ``attach()`` begins capture with an epoch-0 base
    snapshot taken BEFORE journaling is enabled on the very same call —
    snapshot-then-journal is atomic with respect to the stream, so the
    segment's first record is exactly the snapshot's watermark and the
    deque's head-eviction can never open a gap."""

    def __init__(self, state: Any, sink: Any, *,
                 full_every: int | None = None,
                 state_digests: bool = False):
        self.state = state
        self.sink = sink
        self.full_every = int(
            full_every if full_every is not None
            else config.get("scheduler.durability.full-every")
        )
        self.state_digests = bool(state_digests)
        self.tracker = DurabilityTracker(state)
        self.stats = DurabilityStats()
        self.epoch = 0
        # segment records flush into the epoch of the LAST WRITTEN
        # snapshot: segment e holds exactly [watermark_e, watermark_e+1)
        self._segment = 0
        self._pending: list[dict] = []
        self._attached = False

    # ------------------------------------------------------------ capture

    def attach(self) -> dict:
        """Install tracker + journal sink and write the epoch-0 base
        snapshot.  Returns the base snapshot header info."""
        assert not self._attached
        state = self.state
        state.durability = self.tracker
        # base snapshot FIRST, journal capture armed in the same
        # synchronous call: nothing can journal between the two, so the
        # watermark contract holds from record 0
        info = self.snapshot(full=True)
        state.trace.journal_sink = self._on_record
        state.trace.journal_enabled = True
        self._attached = True
        return info

    def detach(self) -> None:
        state = self.state
        if state.trace.journal_sink is self._on_record:
            state.trace.journal_sink = None
        if state.durability is self.tracker:
            state.durability = None
        self._attached = False

    def _on_record(self, rec: dict) -> None:
        self._pending.append(rec)
        self.stats.journal_records += 1

    def flush_journal(self) -> int:
        """Append buffered records to the current epoch's segment."""
        if not self._pending:
            return 0
        records, self._pending = self._pending, []
        n = self.sink.append_journal(self._segment, records)
        self.stats.journal_bytes += n
        return n

    def snapshot(self, full: bool | None = None) -> dict:
        """Take one snapshot: flush the open segment (records below the
        watermark belong to the closing epoch), encode the rows, write
        through the sink, advance the epoch."""
        t0 = time()
        state = self.state
        epoch = self.epoch
        if full is None:
            full = epoch % max(self.full_every, 1) == 0
        self.flush_journal()
        rows = snapshot_rows(state, full=full, tracker=self.tracker)
        dig = state_digest(state) if self.state_digests else None
        blob = encode_snapshot(
            rows, epoch=epoch, base=full,
            journal_seq=state.trace._journal_seq, state_dig=dig,
        )
        nbytes = self.sink.write_snapshot(epoch, blob)
        self.epoch = epoch + 1
        self._segment = epoch
        st = self.stats
        st.snapshot_seconds += time() - t0
        st.snapshot_bytes += nbytes
        st.snapshot_rows += len(rows["tasks"])
        st.epochs += 1
        if full:
            st.base_epochs += 1
        return {
            "epoch": epoch, "base": full, "bytes": nbytes,
            "task_rows": len(rows["tasks"]),
            "journal_seq": state.trace._journal_seq,
        }

    # ------------------------------------------------------------ restore

    @staticmethod
    def load(sink: Any) -> tuple[dict, list[dict], dict]:
        """Load the latest restorable image from a sink: fold base +
        deltas, collect and verify the journal tail.  Returns
        ``(folded_rows, tail_records, info)``.  Integrity failures
        raise typed errors — a corrupt latest snapshot is never
        silently skipped."""
        epochs = sink.snapshot_epochs()
        if not epochs:
            raise SnapshotCorruptError("no snapshot in the durability sink")
        bodies = [parse_snapshot(sink.read_snapshot(e)) for e in epochs]
        base_i = max(
            i for i, b in enumerate(bodies) if b.get("base")
        )
        chain = bodies[base_i:]
        # the delta chain must be gapless: a snapshot lost to a
        # swallowed off-loop write failure (the threaded sink logs and
        # drops) would silently fold away every row dirty only in the
        # missing epoch's window — refuse loudly instead
        chain_epochs = [int(b["epoch"]) for b in chain]
        want_epochs = list(range(chain_epochs[0], chain_epochs[0] + len(chain)))
        if chain_epochs != want_epochs:
            raise SnapshotCorruptError(
                f"snapshot chain has epoch gaps: found {chain_epochs} "
                f"from base epoch {chain_epochs[0]} (a delta snapshot "
                "was lost); refusing a divergent fold"
            )
        folded = fold_snapshots(chain)
        watermark = int(chain[-1]["journal_seq"])
        latest_epoch = int(chain[-1]["epoch"])
        # journal tail: records >= watermark live in segments of the
        # latest epoch onward (the segment OPENED by the latest
        # snapshot carries its watermark as first seq)
        tail: list[dict] = []
        torn = 0
        jepochs = [e for e in sink.journal_epochs() if e >= latest_epoch]
        expected = watermark
        for j, e in enumerate(jepochs):
            blob = sink.read_journal(e)
            recs, t = parse_journal_segment(
                blob, expected_seq=expected, final=(j == len(jepochs) - 1),
                label=f"journal-{e}",
            )
            tail.extend(recs)
            torn += t
            expected = watermark + len(tail)
        info = {
            "epoch": latest_epoch,
            "base_epoch": int(chain[0]["epoch"]),
            "deltas": len(chain) - 1,
            "journal_seq": watermark,
            "tail_records": len(tail),
            "torn_records": torn,
            "state_digest": chain[-1].get("state_digest"),
        }
        return folded, tail, info

    @staticmethod
    def restore_into(state: Any, sink: Any, *,
                     verify_digest: bool = True) -> dict:
        """The whole recovery sequence against a fresh state: fold,
        rebuild, verify the structural digest (when the snapshot
        carries one), replay the journal tail through the real batched
        engine.  Replay emissions are discarded — they were already on
        the wire before the crash.  Returns restore info incl. the
        measured wall RTO of the state-rebuild phase."""
        from distributed_tpu.diagnostics.flight_recorder import (
            replay_stimulus_trace,
        )

        t0 = time()
        folded, tail, info = DurabilityManager.load(sink)
        restore_state(state, folded)
        want = info.get("state_digest")
        if verify_digest and want:
            got = state_digest(state)
            if got != want:
                raise SnapshotCorruptError(
                    f"restored state digest {got} != snapshot's {want}: "
                    "the snapshot codec missed a mutation (file a bug); "
                    "refusing to continue from a divergent state"
                )
        # journaling must stay OFF during replay: the tail's records
        # must not re-journal themselves into the next capture
        assert not state.trace.journal_enabled
        replay_stimulus_trace(state, tail, verify_digests=False)
        info["restore_seconds"] = time() - t0
        return info


# ---------------------------------------------------------- reconciliation


def reconcile_worker(
    state: Any, address: str, held: Iterable, stimulus_id: str,
) -> tuple[tuple[dict, dict], dict]:
    """Cross-check a (re-)registering worker's reported data keys
    against the restored ``who_has`` — every correction routed through
    the engine, never by direct mutation.

    - a reported key whose task is ``memory`` but missing this replica
      → ``stimulus_add_keys`` (replica registration);
    - a reported key whose task is ``processing`` (the completion was
      in flight when the scheduler died) → ``stimulus_tasks_finished_
      batch`` (the engine decides — wrong-worker reports are fenced);
    - a restored replica the worker did NOT report → ``stimulus_
      release_worker_data`` (stale replica strip);
    - unknown keys are ignored (scatter data with no task row cannot be
      rebuilt without a client to want it).

    Returns ``((client_msgs, worker_msgs), counts)``."""
    ws = state.workers.get(address)
    if ws is None:
        return ({}, {}), {"unknown-worker": 1}
    held_pairs = [(k, int(nb)) for k, nb in held]
    held_keys = {k for k, _ in held_pairs}
    counts = {"added": 0, "finished": 0, "stripped": 0, "unknown": 0}
    client_msgs: dict = {}
    worker_msgs: dict = {}

    def merge(cm: dict, wm: dict) -> None:
        for dst, src in ((client_msgs, cm), (worker_msgs, wm)):
            for k, v in src.items():
                dst.setdefault(k, []).extend(v)

    add_keys: list[str] = []
    finished: list[tuple] = []
    for key, nb in held_pairs:
        ts = state.tasks.get(key)
        if ts is None:
            counts["unknown"] += 1
            continue
        if ts.state == "memory":
            if ws not in ts.who_has:
                add_keys.append(key)
                counts["added"] += 1
        elif ts.state == "processing":
            finished.append((key, address, stimulus_id, {"nbytes": nb}))
            counts["finished"] += 1
        # waiting/queued/released: the engine's stale-completion arm in
        # stimulus_tasks_finished_batch would free the surplus copy; we
        # leave those alone here — the worker keeps serving peers until
        # the normal release cascade reaches it
    if add_keys:
        merge(*state.stimulus_add_keys(add_keys, address, stimulus_id))
    if finished:
        merge(*state.stimulus_tasks_finished_batch(finished))
    for ts in [t for t in ws.has_what if t.key not in held_keys]:
        recs = state.stimulus_release_worker_data(
            ts.key, address, stimulus_id
        )
        if recs:
            merge(*state.transitions_batch([(recs, stimulus_id)]))
        counts["stripped"] += 1
    return (client_msgs, worker_msgs), counts


def worker_held_keys(worker_state: Any) -> list:
    """The ``held_keys`` registration payload a worker ships: every
    stored key with its nbytes — what the scheduler's recovery window
    reconciles ``who_has`` against."""
    out = []
    for key in worker_state.data:
        ts = worker_state.tasks.get(key)
        nb = ts.nbytes if ts is not None and ts.nbytes is not None else 0
        out.append([key, int(nb or 0)])
    return out


def snapshot_and_journal_digest_chain(sink: Any) -> list[dict]:
    """Inventory view for diagnostics/CLI: every epoch's snapshot
    size/kind/watermark (parse errors reported per epoch rather than
    raised — this is an inspection surface, not the restore path)."""
    out = []
    for e in sink.snapshot_epochs():
        try:
            body = parse_snapshot(sink.read_snapshot(e))
            out.append({
                "epoch": e, "base": body.get("base"),
                "journal_seq": body.get("journal_seq"),
                "task_rows": len(body["rows"].get("tasks", ())),
            })
        except DurabilityError as exc:
            out.append({"epoch": e, "error": str(exc)})
    return out
