"""Persistent SchedulerState device mirror: delta-maintained fleet SoA.

The co-processor kernels (placement planning, work stealing, AMM replica
drops, rebalance — scheduler/jax_placement.py, stealing.py, amm.py,
server.py) all consume the same fleet snapshot: per-worker ``nthreads``,
``occupancy``, managed-memory ``nbytes``, processing depth and the
``running``/``idle`` bits.  Before this module each kernel cycle
re-derived those arrays from scratch with a Python loop over
``state.workers`` and paid a fresh H2D upload — on the exact tunnel
whose latency PERF.md Round 5 measured dominating the TPU path.

``SchedulerMirror`` keeps ONE persistent structure-of-arrays copy of the
fleet, updated by deltas from the transition engine and the worker
lifecycle paths instead of rebuilt per cycle:

- **Stable slots.**  Every registered worker owns a slot in the SoA
  (``WorkerState.idx``); slots survive unrelated churn, tombstoned slots
  are reused LIFO, and capacity doubles (never shrinks) so array shapes
  stay jit-cache-friendly and row indices stay valid across calls.
- **Dirty rows, not deltas-with-values.**  Mutation sites mark the row
  dirty (a ``set.add``); ``refresh()`` re-reads the live ``WorkerState``
  fields for dirty rows only.  Completeness of the marking is the
  invariant — it is what the from-scratch oracle check verifies — and
  value-correctness then holds by construction.  Per-cycle cost is
  O(dirty), not O(W).
- **Device residency.**  ``device_view()`` keeps jax arrays cached
  across cycles; a cycle uploads only the rows that changed since the
  last device sync (a scatter of O(dirty) rows) or nothing at all when
  the resident arrays are still fresh.
- **Oracle + fallback.**  The from-scratch pack (``oracle_fleet``)
  remains both the correctness oracle and the runtime fallback: with
  the mirror disabled every consumer runs its original Python pack, and
  ``DTPU_MIRROR_CHECK=1`` re-derives the fleet from scratch on every
  view and asserts bit-identical equality — the same contract style as
  the batched transition engine's per-key oracle (docs/batching.md).

The mirror is pure host-side numpy except ``device_view``; jax is
imported lazily so schedulers on no-device hosts never touch it.
"""

from __future__ import annotations

import logging
import os
from typing import TYPE_CHECKING, Any, NamedTuple

import numpy as np

if TYPE_CHECKING:
    from distributed_tpu.scheduler.state import SchedulerState, WorkerState

logger = logging.getLogger("distributed_tpu.mirror")

#: worker status strings -> stable i8 codes (mirror rows are numeric)
STATUS_CODES: dict[str, int] = {
    "running": 0,
    "paused": 1,
    "closing": 2,
    "closing_gracefully": 3,
    "init": 4,
    "closed": 5,
}
STATUS_UNKNOWN = 7

#: fields refreshed per row, in (name, dtype) order — the single source
#: of truth for the SoA layout, the oracle rows and the device cache
FIELDS: tuple[tuple[str, Any], ...] = (
    ("nthreads", np.int32),
    ("occupancy", np.float32),
    ("nbytes", np.float32),
    ("nprocessing", np.int32),
    ("running", np.bool_),
    ("idle", np.bool_),
    ("status", np.int8),
)

_MIN_CAP = 8


class MirrorParityError(AssertionError):
    """Incremental mirror diverged from the from-scratch oracle pack."""


class FleetView(NamedTuple):
    """One refreshed snapshot of the fleet SoA.

    The arrays are the mirror's LIVE buffers (capacity-sized; tombstone
    rows are zeroed with ``running=False``): on-loop consumers may read
    them synchronously but must copy before handing them to another
    thread — the next ``refresh()`` mutates dirty rows in place.
    """

    slots: np.ndarray        # i32[L] live slot indices, ascending
    nthreads: np.ndarray     # i32[cap]
    occupancy: np.ndarray    # f32[cap]
    nbytes: np.ndarray       # f32[cap] managed memory
    nprocessing: np.ndarray  # i32[cap]
    running: np.ndarray      # bool[cap]
    idle: np.ndarray         # bool[cap] (idle AND running: thief-eligible)
    status: np.ndarray       # i8[cap] STATUS_CODES
    addrs: list              # [cap] slot -> address | None
    ws_of: list              # [cap] slot -> WorkerState | None
    live_list: list          # [L] WorkerState in slot order
    live_pos: np.ndarray     # i32[cap] slot -> position in live_list | -1
    n_live: int


def oracle_fleet(state: "SchedulerState") -> dict[str, tuple]:
    """The from-scratch fleet pack — the Python loop the mirror
    replaces, kept as the correctness oracle and the disabled-mirror
    fallback.  Returns ``{address: row}`` with exactly the dtypes the
    mirror stores, so comparison is bit-identical."""
    rows: dict[str, tuple] = {}
    for addr, ws in state.workers.items():
        rows[addr] = (
            np.int32(ws.nthreads),
            np.float32(ws.occupancy),
            np.float32(ws.nbytes),
            np.int32(len(ws.processing)),
            np.bool_(ws in state.running),
            np.bool_(addr in state.idle and ws in state.running),
            np.int8(STATUS_CODES.get(ws.status, STATUS_UNKNOWN)),
        )
    return rows


class SchedulerMirror:
    """Incrementally-maintained SoA mirror of the scheduler's fleet."""

    def __init__(self, state: "SchedulerState", *,
                 capacity_doubling: bool = True,
                 check: bool | None = None):
        self.state = state
        self.capacity_doubling = capacity_doubling
        #: DTPU_MIRROR_CHECK: verify against the from-scratch oracle on
        #: every view (tests / staging; production pays nothing)
        self.check = (
            check if check is not None
            else os.environ.get("DTPU_MIRROR_CHECK", "").lower()
            not in ("", "0", "false", "off", "no")
        )
        self.cap = 0
        self._free: list[int] = []     # tombstoned slots, LIFO reuse
        self._next_slot = 0            # high-water mark of ever-used slots
        self._alloc_arrays(_MIN_CAP)
        self.addrs: list = [None] * self.cap   # slot -> address | None
        self.ws_of: list = [None] * self.cap   # slot -> WorkerState | None
        self._dirty: set[int] = set()
        self._device_dirty: set[int] = set()
        self._members_dirty = True
        self._live_slots = np.zeros(0, np.int32)
        self._live_list: list = []
        self._live_pos = np.full(self.cap, -1, np.int32)
        # device cache: field name -> jax array (capacity-sized)
        self._dev: dict[str, Any] = {}
        self._dev_cap = -1
        # SHARDED device cache (the mesh plan path): field name ->
        # jax array placed with NamedSharding over the engine mesh's
        # "workers" axis.  Slot s lives on shard s // (cap // n_shards)
        # — the block mapping NamedSharding uses for dim 0 — so slot
        # stability (tombstone LIFO reuse, no compaction) IS shard
        # stability; only capacity growth remaps rows (counted as a
        # full per-shard re-pack).  Separate dirty set: both the
        # single-device and the sharded cache must observe every row
        # change regardless of which consumer synced last.
        self._sdev: dict[str, Any] = {}
        self._sdev_mesh: Any | None = None
        self._sdev_cap = -1
        self._sdev_dirty: set[int] = set()
        # ------------------------------------------------ counters
        # (exposed through diagnostics/metrics; asserted by tests)
        self.generation = 0          # bumps when a refresh changed rows
        self.deltas_applied = 0      # mark() calls on live rows
        self.rows_refreshed = 0      # rows re-read from live state
        self.rows_uploaded = 0       # device rows scattered (partial H2D)
        self.bytes_uploaded = 0      # partial-upload payload bytes
        self.full_uploads = 0        # full-array device_put (growth/init)
        self.membership_rebuilds = 0  # live-view rebuilds (churn only)
        self.dirty_high_water = 0    # max dirty rows seen at one refresh
        self.oracle_checks = 0
        self.oracle_failures = 0
        #: incremented by consumers that fell back to the from-scratch
        #: Python pack while this mirror exists — 0 on the hot path
        self.oracle_packs = 0
        # ---------------------------------------- per-shard counters
        # (sharded_device_view; dtpu_mirror_shard_* at /metrics): a
        # fresh cycle must show ZERO rows uploaded on EVERY shard, and
        # full_packs must not creep past growth events
        self.shard_rows_uploaded: list[int] = []
        self.shard_bytes_uploaded: list[int] = []
        self.shard_full_packs: list[int] = []

    # ------------------------------------------------------- allocation

    def _alloc_arrays(self, cap: int) -> None:
        self.cap = cap
        for name, dtype in FIELDS:
            setattr(self, name, np.zeros(cap, dtype))

    def _grow(self) -> None:
        new_cap = self.cap * 2 if self.capacity_doubling else self.cap + _MIN_CAP
        for name, _dtype in FIELDS:
            old = getattr(self, name)
            buf = np.zeros(new_cap, old.dtype)
            buf[: self.cap] = old
            setattr(self, name, buf)
        self.addrs.extend([None] * (new_cap - self.cap))
        self.ws_of.extend([None] * (new_cap - self.cap))
        lp = np.full(new_cap, -1, np.int32)
        lp[: self.cap] = self._live_pos
        self._live_pos = lp
        self.cap = new_cap
        # shapes changed: the device caches must be rebuilt wholesale
        # (growth also remaps slot->shard: rows_per_shard doubled)
        self._dev.clear()
        self._device_dirty.clear()
        self._sdev.clear()
        self._sdev_dirty.clear()

    # ---------------------------------------------------- delta sources

    def on_add_worker(self, ws: "WorkerState") -> None:
        """Assign a stable slot (tombstone reuse first, then growth)."""
        if self._free:
            slot = self._free.pop()
        else:
            if self._next_slot >= self.cap:
                self._grow()
            slot = self._next_slot
            self._next_slot += 1
        ws.idx = slot
        self.addrs[slot] = ws.address
        self.ws_of[slot] = ws
        self._dirty.add(slot)
        self.deltas_applied += 1
        self._members_dirty = True

    def on_remove_worker(self, ws: "WorkerState") -> None:
        """Tombstone the slot; the row zeroes at the next refresh."""
        slot = ws.idx
        if slot < 0 or slot >= len(self.addrs) or self.ws_of[slot] is not ws:
            return
        self.addrs[slot] = None
        self.ws_of[slot] = None
        self._free.append(slot)
        ws.idx = -1
        self._dirty.add(slot)
        self.deltas_applied += 1
        self._members_dirty = True

    def mark(self, ws: "WorkerState") -> None:
        """A mirrored field of ``ws`` changed: mark its row dirty."""
        slot = ws.idx
        if slot >= 0:
            self._dirty.add(slot)
            self.deltas_applied += 1

    # ---------------------------------------------------------- refresh

    def refresh(self) -> int:
        """Flush dirty rows from live state into the host SoA; returns
        the number of rows refreshed (0 when the mirror was fresh)."""
        n = len(self._dirty)
        if n == 0:
            return 0
        if n > self.dirty_high_water:
            self.dirty_high_water = n
        state = self.state
        idle = state.idle
        running = state.running
        # ascending slot order: refresh writes commute per slot, but a
        # deterministic walk keeps upsert/scatter row order (and any
        # digest over it) hash-seed-independent
        for slot in sorted(self._dirty):
            ws = self.ws_of[slot]
            if ws is None:
                self.nthreads[slot] = 0
                self.occupancy[slot] = 0.0
                self.nbytes[slot] = 0.0
                self.nprocessing[slot] = 0
                self.running[slot] = False
                self.idle[slot] = False
                self.status[slot] = STATUS_CODES["closed"]
            else:
                self.nthreads[slot] = ws.nthreads
                self.occupancy[slot] = ws.occupancy
                self.nbytes[slot] = ws.nbytes
                self.nprocessing[slot] = len(ws.processing)
                is_running = ws in running
                self.running[slot] = is_running
                self.idle[slot] = is_running and ws.address in idle
                self.status[slot] = STATUS_CODES.get(ws.status, STATUS_UNKNOWN)
        self._device_dirty.update(self._dirty)
        self._sdev_dirty.update(self._dirty)
        self._dirty.clear()
        self.rows_refreshed += n
        self.generation += 1
        return n

    def _rebuild_membership(self) -> None:
        self._live_slots = np.asarray(
            [s for s, ws in enumerate(self.ws_of) if ws is not None],
            np.int32,
        )
        self._live_list = [self.ws_of[int(s)] for s in self._live_slots]
        self._live_pos.fill(-1)
        self._live_pos[self._live_slots] = np.arange(
            len(self._live_slots), dtype=np.int32
        )
        self._members_dirty = False
        self.membership_rebuilds += 1

    # ------------------------------------------------------------ views

    def fleet_view(self) -> FleetView:
        """Refresh dirty rows and return the shared host snapshot every
        co-processor front-end consumes this cycle."""
        self.refresh()
        if self._members_dirty:
            self._rebuild_membership()
        if self.check:
            self.verify()
        return FleetView(
            slots=self._live_slots,
            nthreads=self.nthreads,
            occupancy=self.occupancy,
            nbytes=self.nbytes,
            nprocessing=self.nprocessing,
            running=self.running,
            idle=self.idle,
            status=self.status,
            addrs=self.addrs,
            ws_of=self.ws_of,
            live_list=self._live_list,
            live_pos=self._live_pos,
            n_live=len(self._live_list),
        )

    def device_view(
        self, fields: tuple[str, ...] = ("nthreads", "occupancy", "running", "idle")
    ) -> dict[str, Any] | None:
        """Device-resident fleet arrays, updated row-wise.

        Returns ``{field: jax array}`` (capacity-sized, matching slot
        indices) or ``None`` when jax is unavailable — callers then use
        the host arrays from :meth:`fleet_view`.  Upload cost per call:
        nothing when no row changed since the last device sync, an
        O(dirty) scatter otherwise, a full ``device_put`` only at first
        use or after capacity growth.
        """
        # wall-budget seam (diagnostics/selfprofile.py): refresh + H2D
        # bill to mirror.upload on whichever thread runs the view
        with self.state.wall.phase("mirror.upload"):
            return self._device_view(fields)

    def _device_view(self, fields: tuple[str, ...]) -> dict[str, Any] | None:
        self.refresh()
        try:
            import jax.numpy as jnp
        except Exception:  # pragma: no cover - no-jax hosts
            return None
        if self._dev_cap != self.cap:
            self._dev.clear()
            self._dev_cap = self.cap
        # only ever-requested fields live on device: scattering the
        # remaining FIELDS would ship rows nothing reads (the host
        # consumers use fleet_view) on exactly the dispatch-latency-
        # bound path this cache exists for
        if self._device_dirty and self._dev:
            n_changed = len(self._device_dirty)
            rows = np.fromiter(sorted(self._device_dirty), np.int32, n_changed)
            # pow2-pad the scatter (repeat a real row; identical values,
            # so duplicates are harmless) to bound jit-shape churn
            pad = _bucket(n_changed)
            if pad > n_changed:
                rows = np.concatenate(
                    [rows, np.full(pad - n_changed, rows[0], np.int32)]
                )
            rows_j = jnp.asarray(rows)
            for name in self._dev:
                host = getattr(self, name)
                vals = host[rows]
                self._dev[name] = self._dev[name].at[rows_j].set(
                    jnp.asarray(vals)
                )
                self.bytes_uploaded += int(vals.nbytes)
            self.rows_uploaded += n_changed
            # flight-recorder kernel hop: dirty-row scatter volume per
            # device sync (a fresh cycle emits nothing — zero H2D)
            self.state.trace.emit(
                "kernel", "mirror-upload", "", n=n_changed, dest="scatter"
            )
        missing = [f for f in fields if f not in self._dev]
        if missing:
            # first use of a field (or capacity growth): full upload,
            # which carries every past change for that field
            for name in missing:
                self._dev[name] = jnp.asarray(getattr(self, name))
            self.full_uploads += 1
            self.state.trace.emit(
                "kernel", "mirror-upload", "", n=self.cap, dest="full"
            )
        self._device_dirty.clear()
        return {f: self._dev[f] for f in fields}

    def sharded_device_view(
        self,
        mesh,
        fields: tuple[str, ...] = ("nthreads", "occupancy", "running"),
    ) -> dict[str, Any] | None:
        """Mesh-sharded fleet arrays for the SHARDED placement engine
        (ops/leveled.place_graph_leveled_sharded): capacity-sized jax
        arrays placed with ``NamedSharding(mesh, P("workers"))`` — each
        device of the engine mesh holds exactly its block of slot rows.

        Upload cost per call mirrors :meth:`device_view`, but accounted
        PER SHARD: nothing when no row changed since the last sharded
        sync (a fresh cycle ships zero fleet rows on every shard —
        counter-asserted by the bench smoke gate), an O(dirty) scatter
        grouped by owning shard otherwise, and a full per-shard pack
        only at first use, capacity growth or a mesh change.  Returns
        ``None`` when jax is unavailable or the mesh cannot divide the
        capacity (callers fall back to replicated host arrays).
        """
        with self.state.wall.phase("mirror.upload"):
            return self._sharded_device_view(mesh, fields)

    def _sharded_device_view(
        self, mesh, fields: tuple[str, ...]
    ) -> dict[str, Any] | None:
        self.refresh()
        try:
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P
        except Exception:  # pragma: no cover - no-jax hosts
            return None
        try:
            n_shards = int(mesh.shape["workers"])
        except (KeyError, TypeError):
            return None
        if n_shards <= 0 or self.cap % n_shards != 0:
            # pow2 capacity x pow2 workers-axis in practice; a mesh that
            # cannot divide the slot space gets the replicated fallback
            return None
        sharding = NamedSharding(mesh, P("workers"))
        if len(self.shard_rows_uploaded) != n_shards:
            # first sharded view, or a DIFFERENT mesh shape: the label
            # space changed, so the counter vectors restart
            self.shard_rows_uploaded = [0] * n_shards
            self.shard_bytes_uploaded = [0] * n_shards
            self.shard_full_packs = [0] * n_shards
        if self._sdev_cap != self.cap or self._sdev_mesh != mesh:
            # capacity growth or mesh swap: arrays rebuild wholesale
            # below (counters keep accumulating — they are monotonic).
            # Mesh EQUALITY, not identity: a caller rebuilding an equal
            # mesh per cycle must not trigger a re-pack per plan.
            self._sdev.clear()
            self._sdev_cap = self.cap
            self._sdev_mesh = mesh
        rows_per_shard = self.cap // n_shards
        if self._sdev_dirty and self._sdev:
            # per-shard dirty-row scatter: group the dirty slots by
            # owning shard and ship each shard ONLY its rows (pow2-
            # padded with a repeated real row to bound jit-shape churn)
            by_shard: dict[int, list[int]] = {}
            for slot in sorted(self._sdev_dirty):
                by_shard.setdefault(slot // rows_per_shard, []).append(slot)
            for shard_i, slots in sorted(by_shard.items()):
                n_changed = len(slots)
                rows = np.asarray(slots, np.int32)
                pad = _bucket(n_changed)
                if pad > n_changed:
                    rows = np.concatenate(
                        [rows, np.full(pad - n_changed, rows[0], np.int32)]
                    )
                rows_j = jnp.asarray(rows)
                for name in self._sdev:
                    host = getattr(self, name)
                    vals = host[rows]
                    self._sdev[name] = self._sdev[name].at[rows_j].set(
                        jnp.asarray(vals)
                    )
                    self.shard_bytes_uploaded[shard_i] += int(vals.nbytes)
                self.shard_rows_uploaded[shard_i] += n_changed
            self.rows_uploaded += len(self._sdev_dirty)
            self.state.trace.emit(
                "kernel", "mirror-upload", "", n=len(self._sdev_dirty),
                dest="shard-scatter",
            )
        missing = [f for f in fields if f not in self._sdev]
        if missing:
            # first use of a field / growth / mesh change: one full
            # sharded device_put — every shard receives its whole block
            for name in missing:
                self._sdev[name] = jax.device_put(
                    getattr(self, name), sharding
                )
            for shard_i in range(n_shards):
                self.shard_full_packs[shard_i] += 1
            self.full_uploads += 1
            self.state.trace.emit(
                "kernel", "mirror-upload", "", n=self.cap,
                dest="shard-full",
            )
        self._sdev_dirty.clear()
        return {f: self._sdev[f] for f in fields}

    def sharded_stats(self) -> dict[str, Any]:
        """Per-shard upload counters (empty lists before the first
        :meth:`sharded_device_view`); one list entry per ``workers``-
        axis shard of the engine mesh."""
        return {
            "n_shards": len(self.shard_rows_uploaded),
            "rows_uploaded": list(self.shard_rows_uploaded),
            "bytes_uploaded": list(self.shard_bytes_uploaded),
            "full_packs": list(self.shard_full_packs),
        }

    # ----------------------------------------------------------- oracle

    def verify(self) -> None:
        """Assert the incremental mirror equals the from-scratch pack
        bit-for-bit (raises :class:`MirrorParityError`).  Pending dirty
        rows are flushed first — the claim under test is that the DIRTY
        MARKING is complete, i.e. no mutation escaped the delta paths."""
        self.refresh()
        self.oracle_checks += 1
        state = self.state
        rows = oracle_fleet(state)
        try:
            live = [s for s in range(len(self.addrs)) if self.ws_of[s] is not None]
            assert len(live) == len(rows), (
                f"live slots {len(live)} != workers {len(rows)}"
            )
            for slot in live:
                ws = self.ws_of[slot]
                assert ws.idx == slot, (ws, slot, ws.idx)
                addr = self.addrs[slot]
                assert addr == ws.address, (addr, ws.address)
                expected = rows[addr]
                got = tuple(
                    getattr(self, name)[slot] for name, _ in FIELDS
                )
                for (name, _), e, g in zip(FIELDS, expected, got):
                    assert e == g and type(e) == type(g), (
                        f"{addr} slot {slot} field {name}: "
                        f"mirror={g!r} oracle={e!r}"
                    )
            for slot in self._free:
                assert self.ws_of[slot] is None and self.addrs[slot] is None, slot
        except AssertionError as e:
            self.oracle_failures += 1
            raise MirrorParityError(str(e)) from e

    def stats(self) -> dict[str, int]:
        """Counter snapshot for diagnostics, bench json and tests."""
        return {
            "generation": self.generation,
            "capacity": self.cap,
            "workers_live": int(len(self.state.workers)),
            "deltas_applied": self.deltas_applied,
            "rows_refreshed": self.rows_refreshed,
            "rows_uploaded": self.rows_uploaded,
            "bytes_uploaded": self.bytes_uploaded,
            "full_uploads": self.full_uploads,
            "membership_rebuilds": self.membership_rebuilds,
            "dirty_high_water": self.dirty_high_water,
            "oracle_checks": self.oracle_checks,
            "oracle_failures": self.oracle_failures,
            "oracle_packs": self.oracle_packs,
        }

    def __repr__(self) -> str:
        return (
            f"<SchedulerMirror cap={self.cap} live={len(self.state.workers)} "
            f"gen={self.generation} dirty={len(self._dirty)}>"
        )


def _bucket(n: int, floor: int = 8) -> int:
    """Next power-of-two >= n (>= floor) — local so the mirror never
    imports the jax-backed ops modules."""
    b = floor
    while b < n:
        b <<= 1
    return b
