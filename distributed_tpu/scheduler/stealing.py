"""Work stealing: rebalance assigned-but-unstarted tasks (reference stealing.py).

Every 100 ms, ``balance()`` moves queued work from saturated workers
("victims") to idle ones ("thieves") when the move pays for itself:
``occ_thief + cost <= occ_victim - cost/2`` (reference stealing.py:462-465).
Tasks are bucketed into 15 cost levels by log2(transfer_time /
compute_time) so cheap-to-move work is considered first.  Moves use an
async confirm protocol with the victim worker — the task may already be
executing there — fenced by stimulus ids (reference stealing.py:279,333).

The inner (victim, level, thief) selection is a pure function over
occupancy/cost arrays; ``distributed_tpu.ops.stealing`` provides the
batched device variant (K Jacobi rounds of rank-matched victim/thief
pairing under the same steal criterion), used when the JAX co-processor
is enabled, the fleet is at least ``scheduler.jax.min-workers``, and the
cycle has enough stealable tasks to amortize a device dispatch.  Either
path feeds the same async confirm protocol.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict, deque
from math import log2
from typing import TYPE_CHECKING, Any

from distributed_tpu import config
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.graph.spec import Key
from distributed_tpu.rpc.core import PeriodicCallback
from distributed_tpu.utils import OrderedSet
from distributed_tpu.utils.misc import seq_name, time

if TYPE_CHECKING:
    from distributed_tpu.scheduler.server import Scheduler
    from distributed_tpu.scheduler.state import TaskState, WorkerState

logger = logging.getLogger("distributed_tpu.stealing")

# 15 steal levels; level i covers cost ratios around 2**(i-7)
# (reference stealing.py:70: fast tasks in low levels move first)
N_LEVELS = 15
LATENCY = 0.1  # assumed steal round-trip (reference stealing.py:33-37)


class InFlightInfo:
    __slots__ = ("victim", "thief", "victim_duration", "thief_duration", "stimulus_id")

    def __init__(self, victim, thief, victim_duration, thief_duration, stimulus_id):
        self.victim = victim
        self.thief = thief
        self.victim_duration = victim_duration
        self.thief_duration = thief_duration
        self.stimulus_id = stimulus_id


class WorkStealing:
    """Scheduler extension (reference stealing.py:57)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.state = scheduler.state
        # stealable[worker_address][level] -> set of TaskStates
        self.stealable: dict[str, list[OrderedSet]] = {}
        self.key_stealable: dict[Key, tuple[str, int]] = {}
        # in-flight steal requests awaiting worker confirmation
        self.in_flight: dict[Key, InFlightInfo] = {}
        # extra occupancy accounted to workers for unconfirmed moves
        self.in_flight_occupancy: defaultdict[Any, float] = defaultdict(float)
        self.in_flight_tasks: defaultdict[Any, int] = defaultdict(int)
        self.metrics: dict[str, dict] = {
            "request_count_total": defaultdict(int),
            "request_cost_total": defaultdict(float),
        }
        self.count = 0
        self.log: deque = deque(maxlen=100_000)
        self._in_flight_event = asyncio.Event()
        self._in_flight_event.set()
        self.enabled = bool(config.get("scheduler.work-stealing"))
        self.speculative = bool(
            config.get("scheduler.work-stealing-speculative")
        )
        # event-driven balance: a kick is pending between the triggering
        # transition and its (debounced) tick
        self._kick_pending = False
        self._last_balance = 0.0
        # injectable seams (ROADMAP item 1 simulator): the sans-io sim
        # re-points ``clock`` at its VirtualClock (the 0.05 s python
        # cycle bound must never read the wall clock there — a wall
        # break mid-cycle would make two same-seed runs diverge) and
        # ``seq`` at a per-run deterministic id mint (seq_name is a
        # process-global counter, so ids would differ between runs)
        self.clock = time
        self.seq = seq_name
        self._rr = 0  # round-robin cursor for dep-free thief choice
        # off-loop device-plan pipeline (see _balance_device)
        self._device_plan_inflight = False
        self._device_executor: Any | None = None

        for ws in self.state.workers.values():
            self.add_worker_state(ws)

        self.state.plugins["stealing"] = self
        scheduler.stream_handlers["steal-response"] = self.move_task_confirm
        interval = config.parse_timedelta(
            config.get("scheduler.work-stealing-interval")
        )
        self._pc = PeriodicCallback(self.balance, interval)
        if config.get("scheduler.work-stealing"):
            scheduler.periodic_callbacks["stealing"] = self._pc
            if scheduler.status.name == "running":
                self._pc.start()

    async def close(self) -> None:
        self._pc.stop()
        if self._device_executor is not None:
            self._device_executor.shutdown(wait=False, cancel_futures=True)
            self._device_executor = None

    # -------------------------------------------------------- plugin hooks

    def add_worker_state(self, ws: "WorkerState") -> None:
        # OrderedSet: the balance cycle steals a level's tasks in
        # iteration order, and restart recovery rebuilds these from the
        # snapshot's key_stealable order (scheduler/durability.py) — a
        # hash-ordered set cannot reproduce the pre-crash scan order
        self.stealable[ws.address] = [OrderedSet() for _ in range(N_LEVELS)]

    def add_worker(self, scheduler: Any, address: str) -> None:
        ws = self.state.workers.get(address)
        if ws is not None and address not in self.stealable:
            self.add_worker_state(ws)

    def remove_worker(self, scheduler: Any, address: str) -> None:
        self.stealable.pop(address, None)
        # drop the departed worker's overlay + metric rows NOW: with
        # steals continuously in flight the bulk clear in
        # _revert_in_flight never runs, and the defaultdicts otherwise
        # retain one row per ever-removed WorkerState — a dead row
        # could even scatter onto a reused mirror slot (census-found,
        # tests/test_census.py)
        for d in (self.in_flight_occupancy, self.in_flight_tasks):
            for ws in [w for w in d if w.address == address]:
                del d[ws]
        for m in self.metrics.values():
            m.pop(address, None)

    # Tape-safe plugin contract (scheduler/native_engine.py): the
    # native engine's applier replays ``transition`` per tape row in
    # exact stream order with task/scheduler state current as of that
    # row.  This hook qualifies because it reads only its arguments,
    # row-current task state and stealing-private structures — it must
    # never read WorkerState.occupancy (native floods sync occupancy at
    # segment end, not per row).  Any plugin WITHOUT this marker forces
    # the whole flood onto the pure-python oracle.
    tape_safe = True

    def transition(self, key: Key, start: str, finish: str, *args: Any,
                   **kwargs: Any) -> None:
        """Track stealability as tasks enter/leave processing."""
        if finish == "processing":
            ts = self.state.tasks[key]
            self.put_key_in_stealable(ts)
            self._maybe_kick()
        elif start == "processing":
            ts = self.state.tasks.get(key)
            if ts is not None:
                self.remove_key_from_stealable(ts)
            info = self.in_flight.pop(key, None)
            if info is not None:
                self._revert_in_flight(info)

    # ----------------------------------------------------- stealable index

    def steal_time_ratio(self, ts: "TaskState") -> tuple[float | None, int | None]:
        """(cost, level); cost_multiplier None = never steal
        (reference stealing.py:241)."""
        if not ts.dependencies:
            return 0, 0
        # restrictions are NOT filtered here: _get_thief restricts the
        # candidate set (with the loose-restrictions fallback), matching
        # reference stealing.py:530-541
        if ts.actor:
            return None, None
        compute_time = self.state.get_task_duration(ts)
        if compute_time <= 0:
            return None, None
        nbytes = sum(dts.get_nbytes() for dts in ts.dependencies)
        transfer_time = nbytes / self.state.bandwidth + LATENCY
        cost = transfer_time / compute_time
        level = int(min(N_LEVELS - 1, max(0, log2(cost + 1e-9) + 7)))
        return cost, level

    def put_key_in_stealable(self, ts: "TaskState") -> None:
        if ts.processing_on is None:
            return
        if ts.homed:
            # placed on its plan-assigned home: stealing a co-assigned
            # tile apart undoes the partition plan (measured: with deep
            # home stacks stealable, peer fetches tripled back to the
            # no-plan level).  Drift is shed by the placement resolve's
            # backlog-outlier check, not by the balancer.
            return
        cost, level = self.steal_time_ratio(ts)
        if cost is None:
            return
        addr = ts.processing_on.address
        levels = self.stealable.get(addr)
        if levels is None:
            return
        levels[level].add(ts)
        self.key_stealable[ts.key] = (addr, level)

    def remove_key_from_stealable(self, ts: "TaskState") -> None:
        loc = self.key_stealable.pop(ts.key, None)
        if loc is None:
            return
        addr, level = loc
        levels = self.stealable.get(addr)
        if levels is not None:
            levels[level].discard(ts)

    # ------------------------------------------------------- move protocol

    def _revert_in_flight(self, info: "InFlightInfo") -> None:
        """Close one confirm window's occupancy/task-count overlays —
        the ONE revert shared by the transition hook (task left
        processing mid-steal) and move_task_confirm.  Overlay rows for
        workers that were removed while the window was open are NOT
        recreated (the defaultdict write would resurrect a dead
        WorkerState's row forever), integer task counts delete at zero,
        and the bulk clear still runs whenever the last window closes
        (float overlay drift never outlives an idle balancer)."""
        occ = self.in_flight_occupancy
        counts = self.in_flight_tasks
        workers = self.state.workers
        thief, victim = info.thief, info.victim
        if thief in occ or workers.get(thief.address) is thief:
            occ[thief] -= info.thief_duration
        if victim in occ or workers.get(victim.address) is victim:
            occ[victim] += info.victim_duration
        left = counts.get(victim)
        if left is not None:
            if left <= 1:
                del counts[victim]
            else:
                counts[victim] = left - 1
        if not self.in_flight:
            occ.clear()
            counts.clear()
            self._in_flight_event.set()

    def seed_in_flight(self, ts: "TaskState", victim: "WorkerState",
                       thief: "WorkerState", victim_duration: float,
                       thief_duration: float, stimulus_id: str) -> None:
        """Open one confirm window: the ``in_flight`` entry plus its
        occupancy/task-count overlays.  The ONE copy of this
        bookkeeping, shared by the live move (``move_task_request``),
        the snapshot restore (``durability.restore_stealing``), and the
        journal replay (``flight_recorder``) — a change landing in only
        one copy diverges a restored scheduler's next balance cycle
        from the unbounced twin."""
        self.in_flight[ts.key] = InFlightInfo(
            victim, thief, victim_duration, thief_duration, stimulus_id
        )
        self.in_flight_occupancy[victim] -= victim_duration
        self.in_flight_occupancy[thief] += thief_duration
        self.in_flight_tasks[victim] += 1
        self._in_flight_event.clear()

    def move_task_request(self, ts: "TaskState", victim: "WorkerState",
                          thief: "WorkerState") -> None:
        """Ask the victim to relinquish ts (reference stealing.py:279)."""
        key = ts.key
        if key in self.in_flight:
            return
        stimulus_id = self.seq("steal")
        victim_duration = victim.processing.get(ts, 0.0)
        comm_cost = self.state.get_comm_cost(ts, thief)
        # shadow divergence monitor (read-only): this steal was priced
        # with the constant model — record the measured twin under the
        # move's stimulus id (telemetry.py; docs/observability.md)
        self.state.shadow_comm_cost(ts, thief, comm_cost, "steal",
                                    stimulus_id)
        compute = self.state.get_task_duration(ts)
        thief_duration = compute + comm_cost
        if self.state.ledger.enabled:
            # decision ledger (ledger.py): the steal DECISION is priced
            # here; this row supersedes the victim placement's open row.
            # On confirm the re-placement files the definitive "steal"
            # row (superseding this one in turn); a rejection joins it
            # as "rejected", and a victim finishing first joins it as
            # "overtaken" — steal regret never absorbs a realization
            # from a worker the kernel didn't price.
            self.state.ledger_file_decision(
                ts, thief, stimulus_id, "steal", compute, comm_cost
            )
        self.remove_key_from_stealable(ts)
        if self.state.trace.journal_enabled:
            # the confirm window is cross-payload scheduler truth: a
            # durable tail spanning an unanswered steal-request must
            # rebuild this in_flight entry or the victim's eventual
            # steal-response finds nothing and the move is dropped
            # (scheduler/durability.py; replayed by flight_recorder)
            self.state.trace.record(
                "steal-request",
                {"key": key, "victim": victim.address,
                 "thief": thief.address, "vd": repr(victim_duration),
                 "td": repr(thief_duration)},
                stimulus_id,
            )
        self.seed_in_flight(
            ts, victim, thief, victim_duration, thief_duration, stimulus_id
        )
        try:
            self.scheduler.send_all({}, {victim.address: [{
                "op": "steal-request", "key": key, "stimulus_id": stimulus_id,
            }]})
        except CommClosedError:
            self.in_flight.pop(key, None)

    def move_task_speculative(self, ts: "TaskState", victim: "WorkerState",
                              thief: "WorkerState") -> None:
        """Move WITHOUT the confirm round trip: free the key on the
        victim and re-place on the thief in one step.

        Only safe-and-profitable for tasks deep in a big victim backlog:
        the victim MIGHT already be executing the task (we cannot know
        without asking — that is what the confirm protocol serializes),
        but a wrong guess only wastes that one execution: free-keys
        cancels it victim-side, a stale completion report is fenced by
        ``processing_on``, and the thief's run is authoritative.  The
        reference always pays the round trip (reference
        stealing.py:279); on an imbalanced burst the confirm wait was
        ~20% of the whole rebalance wall."""
        key = ts.key
        if key in self.in_flight:
            return
        if self.state.workers.get(thief.address) is not thief or (
            thief not in self.state.running
        ):
            # dead thief: leave the task in stealable for the next cycle
            return
        stimulus_id = self.seq("steal-spec")
        # same shadow hop as the confirm path: the criterion priced this
        # move with the constant model just before calling here
        # (constant=None: recomputed only behind the sampling gate)
        self.state.shadow_comm_cost(ts, thief, None, "steal", stimulus_id)
        self.remove_key_from_stealable(ts)
        # the journaled engine twin performs the move (ledger kind
        # "steal-spec": the re-placement row supersedes the victim
        # placement's open row in one step — no confirm leg)
        _cm, ws_msgs = self.state.stimulus_steal_move(
            key, victim.address, thief.address, stimulus_id,
            kind="steal-spec",
        )
        msgs = {victim.address: [{
            "op": "free-keys", "keys": [key], "stimulus_id": stimulus_id,
        }]}
        for addr, lst in ws_msgs.items():
            msgs.setdefault(addr, []).extend(lst)
        self.count += 1
        self.log.append(("speculative", key, victim.address, thief.address))
        self.metrics["request_count_total"][victim.address] += 1
        try:
            self.scheduler.send_all({}, msgs)
        except CommClosedError:
            pass

    async def move_task_confirm(self, key: Key = "", state: str | None = None,
                                stimulus_id: str = "", worker: str = "",
                                **kwargs: Any) -> None:
        """The victim answered (reference stealing.py:333)."""
        info = self.in_flight.pop(key, None)
        if info is None:
            return
        if self.state.trace.journal_enabled:
            # the CLOSE of the confirm window is cross-payload truth
            # too: without this record a tail spanning request+answer
            # replays the in_flight entry back to life (occupancy
            # overlays included) and the bounced scheduler's next
            # balance cycle diverges from the unbounced twin.  matched
            # mirrors the stimulus fence for the MOVE only: matched or
            # not, a consumed window always reverts its overlays (the
            # live semantics below; replay_stimulus_trace calls the
            # same _revert_in_flight).
            self.state.trace.record(
                "steal-confirm",
                {"key": key, "matched": info.stimulus_id == stimulus_id},
                stimulus_id,
            )
        if info.stimulus_id != stimulus_id:
            # a mismatched (stale/forged) answer still CONSUMED the
            # window: revert the overlays too, or the skew — and the
            # dead defaultdict rows carrying it — outlive the steal
            # forever (found by the poison-flood census gate)
            self._revert_in_flight(info)
            return
        victim, thief = info.victim, info.thief
        self._revert_in_flight(info)

        ts = self.state.tasks.get(key)
        if ts is None or ts.state != "processing" or ts.processing_on is not victim:
            # the task finished / was released / moved meanwhile
            return
        if self.state.workers.get(victim.address) is not victim:
            return
        if state in ("ready", "waiting"):
            # victim gave it up: reassign to thief through the journaled
            # engine twin (stimulus_steal_move) — the definitive "steal"
            # ledger row supersedes the request row filed at
            # move_task_request and joins at memory with the regret.  A
            # dead thief degrades to reschedule-from-scratch inside the
            # twin; either way the move replays from the journal tail.
            thief_alive = (
                self.state.workers.get(thief.address) is thief
                and thief in self.state.running
            )
            cm, wm = self.state.stimulus_steal_move(
                key, victim.address, thief.address, stimulus_id,
                kind="steal",
            )
            if thief_alive:
                self.count += 1
                self.log.append(
                    ("confirm", key, victim.address, thief.address)
                )
                self.metrics["request_count_total"][victim.address] += 1
            self.scheduler.send_all(cm, wm)
        else:
            # already executing (or gone): leave it
            if ts.ledger_row >= 0:
                self.state.ledger.join_row(ts.ledger_row, "rejected")
                ts.ledger_row = -1
            self.log.append(("reject", key, state, victim.address))

    # ------------------------------------------------------------ balance

    # below this many stealable tasks a device dispatch costs more than
    # the python scan it replaces
    DEVICE_MIN_TASKS = 64

    def _maybe_kick(self) -> None:
        """Event-driven stealing: a task just landed on a worker while
        others sit idle — schedule a balance tick shortly instead of
        waiting out the periodic interval.  The reference relies on the
        100 ms cycle alone (reference stealing.py:402), which makes the
        first-cycle latency dominate short imbalanced bursts; the 5 ms
        debounce batches a whole submit wave into one tick."""
        if self._kick_pending or not self.enabled or not self.state.idle:
            return
        self._kick_pending = True
        # plain TimerHandle, not a background Task: kicks fire on the
        # per-task hot path, and a Task + sleep + done-callback per kick
        # is measurable loop load at thousands of tasks/s
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._kick_pending = False
            return
        loop.call_later(0.005, self._kick_tick)

    def _kick_tick(self) -> None:
        self._kick_pending = False
        if (
            self.enabled
            and not self.scheduler._ongoing_background_tasks.closed
            and self.clock() - self._last_balance >= 0.02
        ):
            self.balance()

    def balance(self) -> None:
        """One stealing cycle (reference stealing.py:402)."""
        rr0 = self._rr
        self._balance_cycle()
        if self._rr != rr0 and self.state.trace.journal_enabled:
            # the dep-free round-robin cursor advanced this cycle — and
            # not every advance pairs with a journaled steal-request (a
            # candidate can fail _steal_pays after the rotation).  The
            # cursor picks future thieves, so a durable tail must pin it
            # or a restored scheduler's next balance diverges from the
            # unbounced twin (scheduler/durability.py).
            self.state.trace.record(
                "steal-rr", {"rr": self._rr}, self.seq("steal-rr")
            )

    def _balance_cycle(self) -> None:
        self._last_balance = self.clock()
        s = self.state
        if not s.idle or len(s.workers) < 2:
            return
        idle_workers = [ws for ws in s.idle.values() if ws in s.running]
        if not idle_workers:
            return
        from distributed_tpu.scheduler.jax_placement import (
            device_dispatch_worthwhile,
        )

        n_stealable = sum(
            len(t) for levels in self.stealable.values() for t in levels
        )
        if not n_stealable:
            # nothing to move (e.g. every queued task is homed/pinned —
            # the shuffle regime): skip both engines outright
            return
        if self._device_plan_inflight:
            # a device plan is being computed off-loop for a snapshot a
            # few ms old; applying python steals on top would double-move
            return
        if device_dispatch_worthwhile(
            len(s.workers),
            n_stealable,
            self.DEVICE_MIN_TASKS,
            periodic=True,
        ):
            try:
                self._balance_device(idle_workers)
                return
            except Exception:
                logger.exception("device balance failed; python fallback")
        # flight-recorder kernel hop: one event per host-path cycle
        # (the device path stamps its own in _balance_device)
        s.trace.emit("kernel", "steal-cycle", "", n=n_stealable, dest="host")
        if s.saturated:
            victims = list(s.saturated)
        else:
            victims = sorted(
                (ws for ws in s.workers.values()
                 if ws.processing and ws not in s.idle.values()),
                key=lambda ws: ws.occupancy / max(ws.nthreads, 1),
                reverse=True,
            )[:10]
        start = self.clock()
        for victim in victims:
            levels = self.stealable.get(victim.address)
            if levels is None:
                continue
            for level, tasks in enumerate(levels):
                if not tasks:
                    continue
                for ts in list(tasks):
                    if not idle_workers:
                        return
                    if ts.key in self.in_flight or ts.processing_on is not victim:
                        tasks.discard(ts)
                        continue
                    thief = self._get_thief(ts, idle_workers)
                    if thief is None:
                        continue
                    occ_thief = self._combined_occupancy(thief)
                    occ_victim = self._combined_occupancy(victim)
                    comm_cost_thief = s.get_comm_cost(ts, thief)
                    compute = s.get_task_duration(ts)
                    if (
                        occ_thief / max(thief.nthreads, 1)
                        + comm_cost_thief + compute
                        <= occ_victim / max(victim.nthreads, 1) - compute / 2
                    ):
                        if (
                            self.speculative
                            and len(victim.processing) >= 4 * victim.nthreads
                            and not ts.actor
                            and not ts.resource_restrictions
                        ):
                            # deep pile: the odds this particular task is
                            # already executing are < nthreads/len — skip
                            # the confirm round trip (wrong guesses waste
                            # one execution, never correctness)
                            self.move_task_speculative(ts, victim, thief)
                        else:
                            self.move_task_request(ts, victim, thief)
                        occ_thief = self._combined_occupancy(thief)
                        if occ_thief / max(thief.nthreads, 1) > LATENCY:
                            idle_workers = [
                                w for w in idle_workers if w is not thief
                            ]
            if self.clock() - start > 0.05:  # bound cycle time like the reference
                break

    # bounds for one device cycle, mirroring the python path's top-10
    # victims + 0.05 s cycle cap (reference stealing.py:402): the SoA
    # snapshot python-loop runs on the event loop and must stay O(bounded)
    DEVICE_MAX_VICTIMS = 32
    DEVICE_MAX_TASKS = 8192

    # bounds on the thief-resident byte scan (event-loop work): skip
    # very wide tasks (the missing remainder dominates the price
    # anyway), and skip deps replicated past the holder cap (per-dep
    # scans are memoized per cycle, so total cost is
    # O(distinct deps x capped holders) + O(tasks x deps) combines)
    DEVICE_RESIDENT_SCAN_MAX_DEPS = 32
    DEVICE_RESIDENT_SCAN_MAX_HOLDERS = 16

    def _balance_device(self, idle_workers: list) -> None:
        """One balance cycle via the device kernel (ops/stealing.py):
        fleet arrays from the persistent mirror (O(dirty) refresh; the
        from-scratch pack below stays as the no-mirror oracle path) ->
        K-round jitted selection -> the same move_task_request confirm
        protocol, with per-move safety AND criterion re-checks
        (restrictions, liveness, true comm cost) on the way out."""
        import numpy as np

        from distributed_tpu.ops import stealing as ops_stealing
        from distributed_tpu.ops.stealing import _RANK_BITS

        max_rank = (1 << _RANK_BITS) - 1
        s = self.state
        s.trace.emit(
            "kernel", "steal-cycle", "", n=len(idle_workers), dest="device"
        )
        mirror = s.mirror
        overlay_slots: list[int] = []
        overlay_vals: list[float] = []
        if mirror is not None:
            fv = mirror.fleet_view()
            nthreads_arr = fv.nthreads
            running_arr = fv.running
            idle_arr = fv.idle
            nprocessing = fv.nprocessing
            # snapshot, not the live list: the plan lands asynchronously
            # and tombstone slots can be REUSED by joiners meanwhile — a
            # reused slot must resolve to the worker the kernel priced
            # (whose liveness the apply step then re-checks), never to
            # the substitute
            ws_of: list = list(fv.ws_of)
            for w, extra in self.in_flight_occupancy.items():
                if w.idx >= 0:
                    overlay_slots.append(w.idx)
                    overlay_vals.append(extra)
            if overlay_slots:
                occ_arr = fv.occupancy.copy()
                occ_arr[overlay_slots] += overlay_vals
            else:
                occ_arr = fv.occupancy
            slot_of = None  # WorkerState.idx IS the slot
        else:
            # from-scratch oracle pack: the pre-mirror O(W) Python loops
            workers = list(s.workers.values())
            idle_set = set(idle_workers)
            slot_of = {ws.address: i for i, ws in enumerate(workers)}
            ws_of = workers
            occ_arr = np.asarray(
                [self._combined_occupancy(ws) for ws in workers], np.float32
            )
            nthreads_arr = np.asarray(
                [ws.nthreads for ws in workers], np.int32
            )
            idle_arr = np.asarray(
                [ws in idle_set for ws in workers], bool
            )
            running_arr = np.asarray(
                [ws in s.running for ws in workers], bool
            )
            nprocessing = np.asarray(
                [len(ws.processing) for ws in workers], np.int32
            )

        if s.saturated:
            victim_slots = [
                ws.idx if slot_of is None else slot_of.get(ws.address, -1)
                for ws in s.saturated
            ]
            victim_slots = [v for v in victim_slots if v >= 0]
        else:
            vload = occ_arr / np.maximum(nthreads_arr, 1)
            # NOT filtered on running: a paused worker keeps its pile and
            # the pause handler re-marks its homed tasks stealable
            # precisely so this balancer drains them (server.py
            # handle_worker_status_change) — same as the python path.
            # Tombstone slots are excluded by nprocessing == 0.
            cand = np.flatnonzero((nprocessing > 0) & ~idle_arr)
            victim_slots = cand[
                np.argsort(-vload[cand], kind="stable")
            ].tolist()
        victim_slots = victim_slots[: self.DEVICE_MAX_VICTIMS]

        tasks: list = []
        victim_idx: list[int] = []
        keys: list[int] = []
        costs: list[float] = []
        computes: list[float] = []
        alt_thief: list[int] = []
        rank = 0
        scan_cap = self.DEVICE_RESIDENT_SCAN_MAX_DEPS
        holder_cap = self.DEVICE_RESIDENT_SCAN_MAX_HOLDERS
        bandwidth = s.bandwidth
        # per-dependency idle-holder bytes, computed ONCE per distinct
        # dep this cycle: a victim's pile usually shares its few inputs,
        # and without the memo the holder scan repeats per task
        dep_memo: dict[Any, dict[int, float]] = {}
        for vi in victim_slots:
            vws = ws_of[int(vi)]
            if vws is None:
                continue
            levels = self.stealable.get(vws.address)
            if levels is None:
                continue
            if rank >= self.DEVICE_MAX_TASKS:
                break
            for level, tset in enumerate(levels):
                for ts in list(tset):
                    if rank >= self.DEVICE_MAX_TASKS:
                        break
                    if ts.key in self.in_flight \
                            or ts.processing_on is not vws:
                        tset.discard(ts)
                        continue
                    compute = s.get_task_duration(ts)
                    # comm-cost fidelity: the scalar kernel cost used to
                    # assume NO dependency is resident on any thief —
                    # over-estimating by exactly the bytes an idle thief
                    # already holds, which wrongly rejects profitable
                    # steals toward data (the python oracle's
                    # get_comm_cost subtracts them).  Use the replica
                    # slices to price the BEST idle thief (an achievable
                    # lower bound, achieved by ``alt``); the apply step
                    # re-checks the criterion with the true per-thief
                    # cost and falls back to ``alt`` when the rank-
                    # matched thief can't pay it.
                    nbytes = 0.0
                    best_slot = -1
                    if len(ts.dependencies) <= scan_cap:
                        resident: dict[int, float] = {}
                        for d in ts.dependencies:
                            nb = d.get_nbytes()
                            nbytes += nb
                            per_dep = dep_memo.get(d)
                            if per_dep is None:
                                per_dep = {}
                                # widely-replicated deps are skipped
                                # (counted fully missing — the old
                                # conservative price): the scan must
                                # stay O(small) per distinct dep on the
                                # event loop
                                if len(d.who_has) <= holder_cap:
                                    for h in d.who_has:
                                        hi = (
                                            h.idx if slot_of is None
                                            else slot_of.get(h.address, -1)
                                        )
                                        if hi >= 0 and idle_arr[hi]:
                                            per_dep[hi] = nb
                                dep_memo[d] = per_dep
                            for hi, hb in per_dep.items():
                                resident[hi] = resident.get(hi, 0.0) + hb
                        best_bytes = 0.0
                        for hi, rb in resident.items():
                            if rb > best_bytes:
                                best_bytes, best_slot = rb, hi
                        nbytes -= best_bytes
                    else:
                        nbytes = float(
                            sum(d.get_nbytes() for d in ts.dependencies)
                        )
                    tasks.append(ts)
                    victim_idx.append(int(vi))
                    keys.append((level << _RANK_BITS) | min(rank, max_rank))
                    costs.append(nbytes / bandwidth + LATENCY)
                    computes.append(compute)
                    alt_thief.append(best_slot)
                    rank += 1
        if not tasks:
            return
        occ_kernel: Any = np.asarray(occ_arr, np.float32)
        nthreads_kernel: Any = nthreads_arr
        idle_kernel: Any = idle_arr
        running_kernel: Any = running_arr
        if mirror is not None:
            # device-resident fleet half: the cached arrays re-upload
            # only rows dirtied since the last cycle — a fresh mirror
            # dispatches the kernel with ZERO fleet H2D traffic.  The
            # in-flight overlay (usually empty) lands as an O(#in-flight)
            # device-side scatter-add.
            dv = mirror.device_view(
                ("nthreads", "occupancy", "running", "idle")
            )
            if dv is not None:
                occ_kernel = dv["occupancy"]
                if overlay_slots:
                    import jax.numpy as jnp

                    occ_kernel = occ_kernel.at[
                        jnp.asarray(np.asarray(overlay_slots, np.int32))
                    ].add(
                        jnp.asarray(np.asarray(overlay_vals, np.float32))
                    )
                nthreads_kernel = dv["nthreads"]
                idle_kernel = dv["idle"]
                running_kernel = dv["running"]
        batch = ops_stealing.StealBatch(
            task_victim=np.asarray(victim_idx, np.int32),
            task_key=np.asarray(keys, np.int32),
            task_cost=np.asarray(costs, np.float32),
            task_compute=np.asarray(computes, np.float32),
            occ=occ_kernel,
            nthreads=nthreads_kernel,
            idle=idle_kernel,
            running=running_kernel,
        )
        # the kernel call (jit compile on first use — >1 s — plus the
        # dispatch+sync) runs on a daemon thread: a blocking jax call on
        # the event loop stalls heartbeats and every RPC for its whole
        # duration (measured dominating a 128-worker shuffle's wall).
        # The apply step hops back to the loop and re-validates each
        # move against live state, so staleness of the few-ms-old
        # snapshot costs only a skipped steal, never a wrong one.
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (sync tests): plan inline
            self._apply_device_plan(
                ops_stealing.plan_steals(batch), tasks, ws_of, alt_thief
            )
            return
        if self._device_executor is None:
            from distributed_tpu.scheduler.jax_placement import (
                _DaemonExecutor,
            )

            self._device_executor = _DaemonExecutor("steal-device")
        self._device_plan_inflight = True
        fut = self._device_executor.submit(ops_stealing.plan_steals, batch)

        def _done(f):
            try:
                loop.call_soon_threadsafe(
                    self._device_plan_landed, f, tasks, ws_of, alt_thief
                )
            except RuntimeError:
                self._device_plan_inflight = False  # loop closed

        fut.add_done_callback(_done)

    def _device_plan_landed(self, fut, tasks: list, ws_of: list,
                            alt_thief: list) -> None:
        self._device_plan_inflight = False
        try:
            thief_of = fut.result()
        except BaseException:
            if not fut.cancelled():
                logger.exception(
                    "device steal plan failed; python path continues"
                )
            return
        self._apply_device_plan(thief_of, tasks, ws_of, alt_thief)

    def _steal_pays(self, ts: "TaskState", victim: "WorkerState",
                    thief: "WorkerState") -> bool:
        """The python balance criterion against LIVE state with the TRUE
        per-thief comm cost (thief-resident dependencies subtracted) —
        the device kernel priced every candidate at its best-case
        cost, so each accepted move re-earns its place here."""
        s = self.state
        compute = s.get_task_duration(ts)
        return (
            self._combined_occupancy(thief) / max(thief.nthreads, 1)
            + s.get_comm_cost(ts, thief) + compute
            <= self._combined_occupancy(victim) / max(victim.nthreads, 1)
            - compute / 2
        )

    def _apply_device_plan(self, thief_of, tasks: list, ws_of: list,
                           alt_thief: list | None = None) -> None:
        s = self.state
        if alt_thief is None:
            alt_thief = [-1] * len(tasks)
        for ts, ti, ai in zip(tasks, thief_of, alt_thief):
            if ti < 0:
                continue
            thief = ws_of[int(ti)]
            victim = ts.processing_on
            if thief is None or victim is None or ts.key in self.in_flight:
                continue
            if ts.homed:
                # pinned home while the plan computed off-loop (shuffle
                # registration): stealing it now would move its input
                # partition off the very worker the pin protects
                continue
            valid = s.valid_workers(ts)

            def eligible(w) -> bool:
                if w is None or w is victim or w not in s.running:
                    return False
                return (
                    valid is None or w in valid or ts.loose_restrictions
                )

            if not eligible(thief):
                continue
            if not self._steal_pays(ts, victim, thief):
                # the rank-matched thief can't pay the true comm cost;
                # the thief the lower-bound price was computed FOR (the
                # idle holder of the most dependency bytes) may still
                alt = (
                    ws_of[int(ai)] if 0 <= int(ai) < len(ws_of) else None
                )
                if (
                    alt is None or alt is thief or not eligible(alt)
                    or not self._steal_pays(ts, victim, alt)
                ):
                    continue
                thief = alt
            self.move_task_request(ts, victim, thief)

    def _combined_occupancy(self, ws: "WorkerState") -> float:
        # .get, NOT the defaultdict read: a [] miss here materialized a
        # permanent 0.0 row per ever-priced worker (census-found — the
        # overlay must only ever hold rows opened by seed_in_flight)
        return ws.occupancy + self.in_flight_occupancy.get(ws, 0.0)

    def _get_thief(self, ts: "TaskState",
                   idle_workers: list) -> "WorkerState | None":
        valid = self.state.valid_workers(ts)
        candidates = idle_workers
        if valid is not None:
            restricted = [ws for ws in idle_workers if ws in valid]
            if restricted:
                candidates = restricted
            elif not ts.loose_restrictions:
                return None
        if not candidates:
            return None
        if not ts.dependencies:
            # dep-free tasks see every idle thief as equal (objective is
            # occupancy only): rotate instead of re-running the O(W) min
            # per task — same spread, none of the scan
            self._rr += 1
            return candidates[self._rr % len(candidates)]
        return min(
            candidates, key=lambda ws: self.state.worker_objective(ts, ws)
        )

    def story(self, *keys: Key) -> list:
        return [t for t in self.log if any(k in t for k in keys)]
