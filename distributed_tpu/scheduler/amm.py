"""Active Memory Manager: replica creation/destruction policies
(reference active_memory_manager.py).

Every ``interval`` (2 s default) the extension polls its policies; each
policy yields ``Suggestion("replicate" | "drop", ts, candidates)``.  The
extension picks the recipient with the lowest projected memory for
replications and the holder with the highest for drops
(reference active_memory_manager.py:233,290), then enacts the round via
``acquire-replicas`` / ``remove-replicas`` worker messages.  The worker
side already closes the loop: acquire -> gather -> add-keys registers the
replica; remove -> release-worker-data unregisters it.

``ReduceReplicas`` trims replicas beyond current waiter demand — the
north-star bin-packing target.  With the JAX co-processor enabled and
enough replicated tasks, the whole round's drop selection runs as one
device call (``distributed_tpu.ops.amm.plan_drops``: K Jacobi rounds
peeling replicas off the highest-projected-memory holders); suggestions
still flow through ``_find_dropper``'s safety guards.  ``RetireWorker``
evacuates unique data for graceful retirement.
"""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import TYPE_CHECKING, Any, Generator, Iterable

from distributed_tpu import config
from distributed_tpu.graph.spec import Key
from distributed_tpu.rpc.core import PeriodicCallback
from distributed_tpu.utils.collections import OrderedSet
from distributed_tpu.utils.misc import import_term, seq_name

if TYPE_CHECKING:
    from distributed_tpu.scheduler.server import Scheduler
    from distributed_tpu.scheduler.state import TaskState, WorkerState

logger = logging.getLogger("distributed_tpu.amm")

Suggestion = tuple  # (op, ts, candidates | None)


class ActiveMemoryManagerExtension:
    """Scheduler extension (reference active_memory_manager.py:40)."""

    def __init__(self, scheduler: "Scheduler", policies: Iterable | None = None,
                 *, register: bool = True, start: bool | None = None,
                 interval: float | None = None):
        self.scheduler = scheduler
        self.state = scheduler.state
        # registration-ordered: policy run order decides suggestion
        # precedence within a round, so it must not be hash-ordered
        self.policies: OrderedSet[ActiveMemoryManagerPolicy] = OrderedSet()
        if policies is None:
            policies = []
            for spec in config.get("scheduler.active-memory-manager.policies"):
                kwargs = dict(spec)
                cls = import_term(kwargs.pop("class"))
                policies.append(cls(**kwargs))
        for policy in policies:
            self.add_policy(policy)
        if register:
            scheduler.extensions["amm"] = self
            scheduler.handlers["amm_run_once"] = self.run_once_handler
            scheduler.handlers["amm_start"] = self.start_handler
            scheduler.handlers["amm_stop"] = self.stop_handler
        self.interval = (
            interval
            if interval is not None
            else config.parse_timedelta(
                config.get("scheduler.active-memory-manager.interval")
            )
        )
        self._pc = PeriodicCallback(self._tick, self.interval)
        if start is None:
            start = config.get("scheduler.active-memory-manager.start")
        if register and start:
            scheduler.periodic_callbacks["amm"] = self._pc
        # injectable stimulus-id mint (ROADMAP item 1 simulator):
        # seq_name is a process-global counter, so the sim swaps in a
        # per-run deterministic mint to keep same-seed digests identical
        self.seq = seq_name
        # round-local bookkeeping (reference amm.py:58-66)
        self.pending: dict = {}
        self.workers_memory: dict = {}

    def add_policy(self, policy: "ActiveMemoryManagerPolicy") -> None:
        policy.manager = self
        self.policies.add(policy)

    async def close(self) -> None:
        self._pc.stop()

    async def run_once_handler(self) -> str:
        self.run_once()
        return "OK"

    async def start_handler(self) -> str:
        self._pc.start()
        return "OK"

    async def stop_handler(self) -> str:
        self._pc.stop()
        return "OK"

    async def _tick(self) -> None:
        self.run_once()

    # ------------------------------------------------------------ one round

    def run_once(self) -> None:
        stimulus_id = self.seq("amm")
        # projected memory per worker for this round: actual managed
        # bytes plus/minus the round's own decisions (reference
        # amm.py:~200).  Kept as an OVERLAY over live ``ws.nbytes``
        # (``_projected``) instead of a pre-seeded dict: the old
        # ``{ws: ws.nbytes for ws in workers}`` was an O(W) Python loop
        # per 2 s round, paid even when no policy suggested anything.
        self.workers_memory = {}
        try:
            # pending[ts] -> (set of recipients, set of droppers)
            self.pending = {}
            for policy in list(self.policies):
                try:
                    gen = policy.run()
                    while True:
                        try:
                            cmd = next(gen)
                        except StopIteration:
                            break
                        self._handle_suggestion(cmd)
                except Exception:
                    logger.exception("AMM policy %r failed", policy)
            drop_by_worker: defaultdict = defaultdict(list)
            repl_by_worker: defaultdict = defaultdict(dict)
            state = self.state
            ledger = state.ledger
            for ts, (recipients, droppers) in self.pending.items():
                if recipients:
                    holders = [wss.address for wss in ts.who_has]
                    for ws in recipients:
                        repl_by_worker[ws.address][ts.key] = holders
                        if ledger.enabled:
                            # decision ledger (ledger.py): one amm-repl
                            # row per (key, recipient), joined when the
                            # replica's add-keys lands — regret audits
                            # the predicted transfer price vs realized
                            # acquire latency
                            nb = ts.get_nbytes()
                            measured, used = (
                                state.get_replica_cost_measured(ts, ws)
                            )
                            ledger.file_amm(
                                "amm-repl", ts.key, ws.address,
                                stimulus_id,
                                pred_constant=(
                                    nb / state.bandwidth
                                    + state.transfer_latency
                                ),
                                pred_measured=measured,
                                used_measured=used, nbytes=nb,
                                src=holders[0] if holders else "",
                            )
                for ws in droppers:
                    drop_by_worker[ws.address].append(ts.key)
                    if ledger.enabled:
                        # drops predict no transfer; the row audits the
                        # decision->release-worker-data latency only
                        ledger.file_amm(
                            "amm-drop", ts.key, ws.address, stimulus_id,
                            nbytes=ts.get_nbytes(),
                        )
            worker_msgs: dict = {}
            for addr, who_has in repl_by_worker.items():
                worker_msgs.setdefault(addr, []).append({
                    "op": "acquire-replicas",
                    "who_has": who_has,
                    "nbytes": {
                        k: self.state.tasks[k].nbytes
                        for k in who_has if k in self.state.tasks
                    },
                    "stimulus_id": stimulus_id,
                })
            for addr, keys in drop_by_worker.items():
                worker_msgs.setdefault(addr, []).append({
                    "op": "remove-replicas",
                    "keys": keys,
                    "stimulus_id": stimulus_id,
                })
            # flight-recorder kernel hop: the AMM round's decisions are
            # joined to its stimulus id (the acquire/remove-replicas
            # envelopes and resulting transitions carry the same id)
            self.state.trace.emit(
                "kernel", "amm-cycle", stimulus_id, n=len(self.pending)
            )
            if worker_msgs:
                self.scheduler.send_all({}, worker_msgs)
        finally:
            self.pending = {}
            self.workers_memory = {}

    def _projected(self, ws: "WorkerState") -> float:
        """This round's projected managed memory: live bytes overlaid
        with the round's own pending decisions."""
        mem = self.workers_memory.get(ws)
        return ws.nbytes if mem is None else mem

    def _handle_suggestion(self, cmd: Suggestion) -> None:
        op, ts, candidates = cmd
        # decision order: these are iterated to file ledger rows and
        # build the acquire/remove envelopes
        recipients, droppers = self.pending.setdefault(
            ts, (OrderedSet(), OrderedSet())
        )
        if op == "replicate":
            ws = self._find_recipient(ts, candidates, recipients)
            if ws is not None:
                recipients.add(ws)
                self.workers_memory[ws] = (
                    self._projected(ws) + ts.get_nbytes()
                )
        elif op == "drop":
            ws = self._find_dropper(ts, candidates, recipients, droppers)
            if ws is not None:
                droppers.add(ws)
                self.workers_memory[ws] = max(
                    0, self._projected(ws) - ts.get_nbytes()
                )

    def _find_recipient(self, ts: "TaskState", candidates, pending_repl
                        ) -> "WorkerState | None":
        """Lowest projected memory among eligible non-holders
        (reference amm.py:233)."""
        if ts.state != "memory":
            return None
        if candidates is None:
            candidates = set(self.state.running)
        else:
            candidates = {ws for ws in candidates if ws in self.state.running}
        candidates -= ts.who_has
        candidates -= pending_repl
        if not candidates:
            return None
        # address tiebreak: equal projections must not fall back to
        # hash-seed set order
        return min(candidates, key=lambda ws: (self._projected(ws), ws.address))

    def _find_dropper(self, ts: "TaskState", candidates, pending_repl,
                      pending_drop) -> "WorkerState | None":
        """Highest projected memory among holders, never dropping the last
        replica or one under active use (reference amm.py:290)."""
        if len(ts.who_has) - len(pending_drop) < 2:
            return None
        if candidates is None:
            candidates = set(ts.who_has)
        else:
            candidates = {ws for ws in candidates if ws in ts.who_has}
        candidates -= pending_drop
        candidates -= pending_repl
        # don't drop from a worker about to run a dependent of ts
        candidates -= {
            waiter_ts.processing_on
            for waiter_ts in ts.waiters
            if waiter_ts.processing_on is not None
        }
        if not candidates:
            return None
        # address tiebreak: equal projections must not fall back to
        # hash-seed set order
        return max(candidates, key=lambda ws: (self._projected(ws), ws.address))


class ActiveMemoryManagerPolicy:
    """Base policy (reference active_memory_manager.py:431)."""

    manager: ActiveMemoryManagerExtension

    def run(self) -> Generator[Suggestion, None, None]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class ReduceReplicas(ActiveMemoryManagerPolicy):
    """Drop replicas beyond current waiter demand
    (reference active_memory_manager.py:527)."""

    # below this many replicated tasks a device dispatch costs more than
    # the python generator it replaces
    DEVICE_MIN_TASKS = 64

    @staticmethod
    def _desired(ts: "TaskState") -> int:
        return max(
            1,
            len({
                waiter.processing_on or waiter
                for waiter in ts.waiters
            }) if ts.waiters else 1,
        )

    def run(self) -> Generator[Suggestion, None, None]:
        from distributed_tpu.scheduler.jax_placement import (
            device_dispatch_worthwhile,
        )

        state = self.manager.state
        replicated = list(state.replicated_tasks)
        if device_dispatch_worthwhile(
            len(state.workers), len(replicated), self.DEVICE_MIN_TASKS,
            periodic=True,
        ):
            try:
                yield from self._run_device(replicated)
                return
            except Exception:
                logger.exception("device ReduceReplicas failed; python fallback")
        for ts in replicated:
            ndrop = len(ts.who_has) - self._desired(ts)
            for _ in range(ndrop):
                yield ("drop", ts, None)

    def _run_device(self, replicated: list) -> Generator[Suggestion, None, None]:
        """Whole-round drop selection in one device call
        (ops/amm.py); each emitted suggestion pins its chosen holder and
        still passes through _find_dropper's guards.

        The worker axis is the persistent mirror's slot space when a
        mirror exists (replica columns come straight from
        ``WorkerState.idx``, the projected-memory vector from the
        delta-maintained ``nbytes`` row — no per-round worker dict or
        O(W) Python pack); tombstone slots are never holders, so the
        kernel cannot select them.  Without a mirror the original dense
        pack below stays as the oracle path."""
        import numpy as np

        from distributed_tpu.ops import amm as ops_amm

        state = self.manager.state
        mirror = state.mirror
        rows = []
        for ts in replicated:
            ndrop = len(ts.who_has) - self._desired(ts)
            if ndrop > 0:
                rows.append((ts, ndrop))
        if not rows:
            return
        R = len(rows)
        if mirror is not None:
            fv = mirror.fleet_view()
            W = mirror.cap
            ws_of = fv.ws_of
            slot = lambda ws: ws.idx  # noqa: E731
            mem = fv.nbytes.astype(np.float32, copy=True)
            for ws, v in self.manager.workers_memory.items():
                if ws.idx >= 0:
                    mem[ws.idx] = v
        else:
            workers = list(state.workers.values())
            widx = {ws: i for i, ws in enumerate(workers)}
            W = len(workers)
            ws_of = workers
            slot = lambda ws: widx.get(ws, -1)  # noqa: E731
            mem = np.asarray(
                [self.manager._projected(ws) for ws in workers], np.float32
            )
        holders = np.zeros((R, W), bool)
        excluded = np.zeros((R, W), bool)
        nbytes = np.zeros(R, np.float32)
        ndrops = np.zeros(R, np.int32)
        for r, (ts, ndrop) in enumerate(rows):
            for ws in ts.who_has:
                i = slot(ws)
                if i >= 0:
                    holders[r, i] = True
            for waiter in ts.waiters:
                pw = waiter.processing_on
                if pw is not None:
                    i = slot(pw)
                    if i >= 0:
                        excluded[r, i] = True
            nbytes[r] = ts.get_nbytes()
            ndrops[r] = ndrop
        for r, w in ops_amm.plan_drops(
            ops_amm.DropBatch(holders, excluded, nbytes, ndrops, mem)
        ):
            dropper = ws_of[w]
            if dropper is not None:
                yield ("drop", rows[r][0], {dropper})


class RetireWorker(ActiveMemoryManagerPolicy):
    """Evacuate all unique data from one worker before retirement
    (reference active_memory_manager.py:571)."""

    def __init__(self, address: str):
        self.address = address
        self.done = False

    def run(self) -> Generator[Suggestion, None, None]:
        state = self.manager.state
        ws = state.workers.get(self.address)
        if ws is None:
            self.done = True
            self.manager.policies.discard(self)
            return
        unique = [ts for ts in ws.has_what if len(ts.who_has) == 1]
        if not unique:
            self.done = True
            self.manager.policies.discard(self)
            return
        others = [w for w in state.running if w is not ws]
        for ts in unique:
            yield ("replicate", ts, set(others) if others else None)

    def __repr__(self) -> str:
        return f"RetireWorker({self.address!r}, done={self.done})"
