"""Actors: stateful tasks pinned to one worker (reference actor.py).

``client.submit(MyClass, actor=True)`` runs the constructor once on a
worker; the instance stays in ``worker.state.actors`` and the task's
"value" is an ``ActorPlaceholder``.  Resolving the future yields an
``Actor`` proxy whose method calls are direct client->worker RPCs
(``actor_execute``, reference worker.py:2159) bypassing the scheduler,
and whose plain attributes are fetched via ``actor_attribute``.
"""

from __future__ import annotations

import asyncio
from typing import Any

from distributed_tpu.protocol.serialize import Serialize, unwrap
from distributed_tpu.rpc.core import rpc as _rpc


class ActorPlaceholder:
    """The stored 'value' of an actor task: (class, key, worker address)."""

    __slots__ = ("cls", "key", "worker")

    def __init__(self, cls: type, key: str, worker: str):
        self.cls = cls
        self.key = key
        self.worker = worker

    def __reduce__(self):
        return (ActorPlaceholder, (self.cls, self.key, self.worker))

    def __repr__(self) -> str:
        return f"<ActorPlaceholder {self.cls.__name__} {self.key} on {self.worker}>"


class Actor:
    """Client-side proxy to a remote actor instance (reference actor.py:22)."""

    def __init__(self, cls: type, worker: str, key: str, io: Any = None):
        self._cls = cls
        self._worker = worker
        self._key = key
        self._io = io if io is not None else _rpc(worker)

    @classmethod
    def from_placeholder(cls, ph: ActorPlaceholder, io: Any = None) -> "Actor":
        return cls(ph.cls, ph.worker, ph.key, io=io)

    def __repr__(self) -> str:
        return f"<Actor: {self._cls.__name__}, key={self._key}>"

    def __dir__(self):
        return sorted(set(dir(type(self))) | {
            a for a in dir(self._cls) if not a.startswith("_")
        })

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        attr = getattr(self._cls, name, None)
        if callable(attr):
            async def call(*args: Any, **kwargs: Any):
                resp = await self._io.actor_execute(
                    actor=self._key,
                    function=name,
                    args=Serialize(args),
                    kwargs=Serialize(kwargs),
                )
                if resp.get("status") == "error":
                    from distributed_tpu.rpc.core import raise_remote_error

                    raise_remote_error(resp)
                return unwrap(resp["result"])

            return call

        async def get_attribute():
            resp = await self._io.actor_attribute(
                actor=self._key, attribute=name
            )
            if resp.get("status") == "error":
                from distributed_tpu.rpc.core import raise_remote_error

                raise_remote_error(resp)
            return unwrap(resp["result"])

        return get_attribute()
