"""Actors: stateful tasks pinned to one worker (reference actor.py).

``client.submit(MyClass, actor=True)`` runs the constructor once on a
worker; the instance stays in ``worker.state.actors`` and the task's
"value" is an ``ActorPlaceholder``.  Resolving the future yields an
``Actor`` proxy whose method calls are direct client->worker RPCs
(``actor_execute``, reference worker.py:2159) bypassing the scheduler,
and whose plain attributes are fetched via ``actor_attribute``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
from typing import Any, Callable

from distributed_tpu.protocol.serialize import Serialize, unwrap
from distributed_tpu.rpc.core import rpc as _rpc


class ActorFuture:
    """Result handle for one actor method call (reference actor.py:22
    BaseActorFuture / EagerActorFuture).

    Usable from BOTH worlds: ``await fut`` on the event loop, or the
    concurrent.futures-style sync surface — ``result(timeout)``,
    ``done()``, ``add_done_callback(fn)`` — from ordinary threads (the
    blocking client facade).  Also accepted by ``as_completed`` next to
    task futures."""

    def __init__(self, coro, loop: asyncio.AbstractEventLoop | None = None):
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        self._loop = loop or running
        if running is not None and self._loop is running:
            self._task: Any = asyncio.ensure_future(coro)
        elif self._loop is not None:
            # called from a foreign thread (sync facade): schedule on
            # the client's loop, expose a thread-safe handle
            self._task = asyncio.run_coroutine_threadsafe(coro, self._loop)
        else:
            raise RuntimeError(
                "ActorFuture needs a running event loop (or pass loop=)"
            )

    def __await__(self):
        task = self._task
        if isinstance(task, concurrent.futures.Future):
            return asyncio.wrap_future(task).__await__()
        return task.__await__()

    def done(self) -> bool:
        return self._task.done()

    def result(self, timeout: float | None = None) -> Any:
        """Block for the result.  From a foreign thread this waits on
        the concurrent future; ON the event loop thread it must not
        block — use ``await`` there."""
        task = self._task
        if isinstance(task, concurrent.futures.Future):
            return task.result(timeout)
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            raise RuntimeError(
                "ActorFuture.result() would block the event loop; "
                "use `await fut` here"
            )
        # asyncio.Task owned by a loop running in another thread
        done = concurrent.futures.Future()

        def _transfer(t):
            if t.cancelled():
                done.cancel()
            elif t.exception() is not None:
                done.set_exception(t.exception())
            else:
                done.set_result(t.result())

        task.get_loop().call_soon_threadsafe(
            lambda: task.add_done_callback(_transfer)
        )
        return done.result(timeout)

    def add_done_callback(self, fn: Callable) -> None:
        task = self._task
        if isinstance(task, concurrent.futures.Future):
            task.add_done_callback(fn)
            return
        try:
            on_loop = asyncio.get_running_loop() is task.get_loop()
        except RuntimeError:
            on_loop = False
        if on_loop:
            task.add_done_callback(fn)
        else:
            # asyncio.Task callbacks are NOT thread-safe: mutate the
            # callback list only on the owning loop
            task.get_loop().call_soon_threadsafe(task.add_done_callback, fn)

    def cancel(self) -> bool:
        task = self._task
        if isinstance(task, concurrent.futures.Future):
            return task.cancel()
        try:
            on_loop = asyncio.get_running_loop() is task.get_loop()
        except RuntimeError:
            on_loop = False
        if on_loop:
            return task.cancel()
        task.get_loop().call_soon_threadsafe(task.cancel)
        return True  # best effort from a foreign thread

    def __repr__(self) -> str:
        state = "done" if self.done() else "pending"
        return f"<ActorFuture {state}>"


class ActorPlaceholder:
    """The stored 'value' of an actor task: (class, key, worker address)."""

    __slots__ = ("cls", "key", "worker")

    def __init__(self, cls: type, key: str, worker: str):
        self.cls = cls
        self.key = key
        self.worker = worker

    def __reduce__(self):
        return (ActorPlaceholder, (self.cls, self.key, self.worker))

    def __repr__(self) -> str:
        return f"<ActorPlaceholder {self.cls.__name__} {self.key} on {self.worker}>"


class Actor:
    """Client-side proxy to a remote actor instance (reference actor.py:22)."""

    def __init__(self, cls: type, worker: str, key: str, io: Any = None,
                 loop: asyncio.AbstractEventLoop | None = None):
        self._cls = cls
        self._worker = worker
        self._key = key
        self._io = io if io is not None else _rpc(worker)
        try:
            self._loop = loop or asyncio.get_running_loop()
        except RuntimeError:
            self._loop = loop

    @classmethod
    def from_placeholder(cls, ph: ActorPlaceholder, io: Any = None,
                         loop: asyncio.AbstractEventLoop | None = None) -> "Actor":
        return cls(ph.cls, ph.worker, ph.key, io=io, loop=loop)

    def __repr__(self) -> str:
        return f"<Actor: {self._cls.__name__}, key={self._key}>"

    def __dir__(self):
        return sorted(set(dir(type(self))) | {
            a for a in dir(self._cls) if not a.startswith("_")
        })

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        attr = getattr(self._cls, name, None)
        if callable(attr):
            def call(*args: Any, **kwargs: Any) -> "ActorFuture":
                # validate loop availability BEFORE building the
                # coroutine: raising after _run() exists leaks a
                # never-awaited coroutine and buries the real error
                # under a RuntimeWarning
                loop = self._loop
                if loop is None:
                    try:
                        loop = asyncio.get_running_loop()
                    except RuntimeError:
                        raise RuntimeError(
                            f"actor call {name}() needs a running event "
                            "loop (construct the Actor with loop=, or "
                            "call from async code)"
                        ) from None

                async def _run():
                    resp = await self._io.actor_execute(
                        actor=self._key,
                        function=name,
                        args=Serialize(args),
                        kwargs=Serialize(kwargs),
                    )
                    if resp.get("status") == "error":
                        from distributed_tpu.rpc.core import (
                            raise_remote_error,
                        )

                        raise_remote_error(resp)
                    return unwrap(resp["result"])

                return ActorFuture(_run(), loop=loop)

            return call

        async def get_attribute():
            resp = await self._io.actor_attribute(
                actor=self._key, attribute=name
            )
            if resp.get("status") == "error":
                from distributed_tpu.rpc.core import raise_remote_error

                raise_remote_error(resp)
            return unwrap(resp["result"])

        return get_attribute()
