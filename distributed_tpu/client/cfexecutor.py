"""concurrent.futures.Executor facade over a Client (reference cfexecutor.py:46).

``client.get_executor()`` returns an executor whose futures are standard
``concurrent.futures.Future`` objects, bridged from cluster futures on
the client's event loop — drop-in for code written against the stdlib
executor API.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
from typing import Any, Callable


class ClientExecutor(cf.Executor):
    def __init__(self, client: Any, **submit_kwargs: Any):
        self.client = client
        self.submit_kwargs = submit_kwargs
        self._futures: set = set()
        self._cluster_futures: dict = {}
        self._shutdown = False

    def submit(self, fn: Callable, *args: Any, **kwargs: Any) -> cf.Future:
        if self._shutdown:
            raise RuntimeError("executor has been shut down")
        assert self.client.loop is not None, "client not started"
        merged = {"pure": False, **self.submit_kwargs, **kwargs}
        fut = self.client.submit(fn, *args, **merged)
        cfut: cf.Future = cf.Future()  # stays PENDING: cancel() works
        self._futures.add(cfut)
        self._cluster_futures[cfut] = fut

        async def _relay():
            try:
                result = await fut.result()
            except BaseException as e:  # noqa: B036 - propagate task errors
                if cfut.set_running_or_notify_cancel():
                    cfut.set_exception(e)
            else:
                if cfut.set_running_or_notify_cancel():
                    cfut.set_result(result)
            finally:
                self._futures.discard(cfut)
                self._cluster_futures.pop(cfut, None)

        asyncio.run_coroutine_threadsafe(_relay(), self.client.loop)
        return cfut

    def map(self, fn: Callable, *iterables: Any, timeout: float | None = None,
            chunksize: int = 1) -> Any:
        import time as _time

        futs = [self.submit(fn, *args) for args in zip(*iterables)]
        # stdlib semantics: timeout is an overall deadline, not per-future
        end_time = None if timeout is None else timeout + _time.monotonic()

        def gen():
            for f in futs:
                remaining = (
                    None if end_time is None else end_time - _time.monotonic()
                )
                yield f.result(remaining)

        return gen()

    def shutdown(self, wait: bool = True, *, cancel_futures: bool = False) -> None:
        self._shutdown = True
        if cancel_futures:
            for f in list(self._futures):
                if f.cancel():
                    cluster_fut = self._cluster_futures.pop(f, None)
                    if cluster_fut is not None:
                        cluster_fut.release()
                    self._futures.discard(f)
        if wait:
            cf.wait(list(self._futures), timeout=30)
