"""Client: futures, submit/map/gather/scatter (reference client.py).

The client keeps one batched stream to the scheduler; ``_handle_report``
dispatches ``key-in-memory`` / ``task-erred`` / ``lost-data`` report
messages onto client-side ``Future`` objects, which are refcounted so the
scheduler can release results nobody holds anymore
(reference client.py:174,741,1548).

Async-first: every API is a coroutine on the running event loop; the
sync facade (``Client(..., asynchronous=False)``) drives a dedicated
loop thread via ``LoopRunner`` like the reference's ``SyncMethodMixin``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid
from collections.abc import Iterable, Iterator
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.comm.core import Comm, connect
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.graph.spec import Graph, Key, TaskRef, TaskSpec, tokenize
from distributed_tpu.protocol.serialize import Serialize, unwrap
from distributed_tpu.rpc.batched import BatchedSend
from distributed_tpu.rpc.core import raise_remote_error, rpc
from distributed_tpu.utils.misc import LoopRunner, funcname, seq_name, time

logger = logging.getLogger("distributed_tpu.client")


class FutureState:
    """Client-side record of one key's lifecycle."""

    __slots__ = ("event", "status", "type", "exception", "traceback", "traceback_text")

    def __init__(self) -> None:
        self.event = asyncio.Event()
        self.status = "pending"
        self.type: str | None = None
        self.exception: BaseException | None = None
        self.traceback: Any = None
        self.traceback_text = ""

    def finish(self, type: str | None = None) -> None:
        self.status = "finished"
        self.type = type
        self.event.set()

    def lose(self) -> None:
        self.status = "lost"
        self.event.clear()

    def set_error(self, exception: BaseException, traceback: Any,
                  traceback_text: str = "") -> None:
        self.status = "error"
        self.exception = exception
        self.traceback = traceback
        self.traceback_text = traceback_text
        self.event.set()

    def cancel(self) -> None:
        self.status = "cancelled"
        self.exception = asyncio.CancelledError()
        self.event.set()

    def retry(self) -> None:
        """Scheduler reran an erred/lost key: wait for the new attempt."""
        self.status = "pending"
        self.exception = None
        self.traceback = None
        self.traceback_text = ""
        self.event.clear()


class Future:
    """A remote result (reference client.py:174)."""

    def __init__(self, key: Key, client: "Client"):
        self.key = key
        self.client = client
        self._cleared = False
        client._inc_ref(key)

    @property
    def _state(self) -> FutureState:
        return self.client.futures[self.key]

    @property
    def status(self) -> str:
        if self.client is None:
            return "unbound"
        st = self.client.futures.get(self.key)
        return st.status if st is not None else "cancelled"

    def done(self) -> bool:
        if self.client is None:
            return False
        st = self.client.futures.get(self.key)
        return st is not None and st.event.is_set()

    def cancelled(self) -> bool:
        return self.status == "cancelled"

    async def result(self, timeout: float | None = None):
        """Wait for and fetch the value (async; the sync shell wraps this)."""
        return await self.client._result(self, timeout=timeout)

    async def exception(self, timeout: float | None = None):
        st = self.client.futures.get(self.key)
        if st is None:
            return None
        await asyncio.wait_for(st.event.wait(), timeout)
        return st.exception

    async def traceback(self, timeout: float | None = None):
        st = self.client.futures.get(self.key)
        if st is None:
            return None
        await asyncio.wait_for(st.event.wait(), timeout)
        return st.traceback

    async def cancel(self):
        await self.client.cancel([self])

    def release(self) -> None:
        if not self._cleared:
            self._cleared = True
            self.client._dec_ref(self.key)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"<Future: {self.status}, key: {self.key}>"

    def _repr_html_(self) -> str:
        color = {
            "finished": "green", "error": "red", "cancelled": "gray"
        }.get(self.status, "orange")
        return (
            f"<b>Future:</b> <tt>{self.key}</tt> "
            f"<b style='color:{color}'>{self.status}</b>"
        )

    def __getstate__(self) -> str:
        # futures pickle as their key alone (reference client.py:430);
        # the receiving side rebinds to its own client (_rebind_futures)
        return self.key

    def __setstate__(self, key: str) -> None:
        self.key = key
        self.client = None  # unbound stub until rebound
        self._cleared = True

    def __await__(self):
        return self.result().__await__()

    def __hash__(self) -> int:
        return hash(self.key)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Future) and other.key == self.key


class Client:
    """Entry point for users (reference client.py:741)."""

    def __init__(
        self,
        address: str | None = None,
        *,
        asynchronous: bool = True,
        name: str | None = None,
        timeout: float = 10.0,
        heartbeat_interval: float | None = None,
        security: Any | None = None,
    ):
        self.address = address
        self.security = security
        self._connection_args = (
            security.get_connection_args("client") if security is not None
            else {}
        )
        self.id = f"Client-{name or ''}{uuid.uuid4().hex[:12]}"
        self._heartbeat_interval = heartbeat_interval
        self._heartbeat_pc: Any | None = None
        self.futures: dict[Key, FutureState] = {}
        # pickled-size cache for the large-closure warning: weak keys so
        # user functions die normally and ids are never reused stale
        import weakref

        self._fn_sizes: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.refcount: dict[Key, int] = {}
        self._cancel_expected: dict[Key, "FutureState"] = {}
        self.scheduler_comm: Comm | None = None
        self.batched_stream = BatchedSend()
        self.scheduler: rpc | None = None
        self.status = "newly-created"
        self.asynchronous = asynchronous
        self._timeout = timeout
        self._handle_report_task: asyncio.Task | None = None
        self._pubsub_subs: dict[str, list] = {}
        self._event_handlers: dict[str, list] = {}
        self._worker_rpcs: dict[str, Any] = {}
        self._scheduler_identity: dict = {}  # last identity() snapshot
        self._generation = 0
        self.loop: asyncio.AbstractEventLoop | None = None
        self._loop_runner: LoopRunner | None = None
        if not asynchronous:
            self._loop_runner = LoopRunner()
            self._loop_runner.start()
            self.sync(self._start)

    # ------------------------------------------------------- sync facade

    def gather_sync(self, futures: Any, errors: str = "raise") -> Any:
        return self.sync(self.gather, futures, errors=errors)

    def result_sync(self, future: "Future", timeout: float | None = None) -> Any:
        return self.sync(future.result, timeout=timeout)

    def scatter_sync(self, data: Any, **kwargs: Any) -> Any:
        return self.sync(self.scatter, data, **kwargs)

    # ------------------------------------------------------------ lifecycle

    def sync(self, coro_fn: Callable, *args: Any, **kwargs: Any) -> Any:
        assert self._loop_runner is not None
        return self._loop_runner.run_sync(coro_fn, *args, **kwargs)

    async def _start(self) -> "Client":
        self.loop = asyncio.get_running_loop()
        comm = await connect(self.address, **self._connection_args)
        await comm.write(
            {"op": "register-client", "client": self.id, "reply": False}
        )
        resp = await comm.read()
        if resp.get("status") != "OK":
            raise ValueError(f"scheduler rejected client: {resp!r}")
        self.scheduler_comm = comm
        self.batched_stream.start(comm)
        self.scheduler = rpc(
            self.address, connection_args=self._connection_args
        )
        self._handle_report_task = asyncio.create_task(self._handle_report())
        # liveness heartbeat on the batched stream (reference
        # client.heartbeat 5s): the scheduler stamps ClientState.last_seen
        interval = (
            self._heartbeat_interval
            if self._heartbeat_interval is not None
            else config.parse_timedelta(config.get("client.heartbeat", "5s"))
        )
        if interval and interval > 0:
            from distributed_tpu.rpc.core import PeriodicCallback

            def _beat() -> None:
                try:
                    self.batched_stream.send(
                        {"op": "heartbeat-client", "client": self.id}
                    )
                except Exception:
                    pass

            self._heartbeat_pc = PeriodicCallback(_beat, interval)
            self._heartbeat_pc.start()
        self.status = "running"
        try:
            # one identity snapshot at connect so _repr_html_ (sync, must
            # not block) has workers/dashboard to show immediately
            await self.scheduler_info()
        except Exception:  # pragma: no cover - scheduler racing shutdown
            pass
        logger.info("%s connected to %s", self.id, self.address)
        return self

    async def __aenter__(self) -> "Client":
        if self.status == "newly-created":
            await self._start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    def __enter__(self) -> "Client":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.sync(self.close)
        if self._loop_runner is not None:
            self._loop_runner.stop()

    async def close(self) -> None:
        if self.status == "closed":
            return
        self.status = "closed"
        if self._heartbeat_pc is not None:
            self._heartbeat_pc.stop()
        if self._handle_report_task is not None:
            self._handle_report_task.cancel()
            try:
                await self._handle_report_task
            except (asyncio.CancelledError, Exception):
                pass
        try:
            if not self.batched_stream.closed():
                self.batched_stream.send({"op": "close-client", "client": self.id})
                self.batched_stream.send({"op": "close-stream"})
        except CommClosedError:
            pass
        await self.batched_stream.close(timeout=1)
        if self.scheduler_comm is not None:
            await self.scheduler_comm.close()
        if self.scheduler is not None:
            await self.scheduler.close_rpc()
        for r in self._worker_rpcs.values():
            await r.close_rpc()
        self._worker_rpcs.clear()
        for st in self.futures.values():
            if not st.event.is_set():
                st.cancel()

    # ------------------------------------------------------- report stream

    async def _handle_report(self) -> None:
        """Dispatch scheduler report messages (reference client.py:1548)."""
        assert self.scheduler_comm is not None
        try:
            while True:
                msgs = await self.scheduler_comm.read()
                if not isinstance(msgs, (list, tuple)):
                    msgs = (msgs,)
                for msg in msgs:
                    if msg == "OK":
                        continue
                    op = msg.pop("op", None)
                    if op == "key-in-memory":
                        self._handle_key_in_memory(**msg)
                    elif op == "task-erred":
                        self._handle_task_erred(**msg)
                    elif op == "lost-data":
                        self._handle_lost_data(**msg)
                    elif op == "cancelled-keys":
                        for key in msg.get("keys", ()):
                            # the state was already cancelled synchronously
                            # in Client.cancel; this report arrives over
                            # the batched stream and may postdate a
                            # RESUBMISSION of the key — only apply it to
                            # the FutureState the cancel targeted
                            missing = object()
                            expected = self._cancel_expected.pop(key, missing)
                            st = self.futures.get(key)
                            if st is not None and (
                                expected is missing or st is expected
                            ):
                                st.cancel()
                    elif op == "task-retried":
                        # another client's retry reran this key: drop our
                        # terminal view and wait for the fresh attempt.
                        # The initiating client reset its state in
                        # retry() already; anything non-terminal (e.g. a
                        # resubmission racing this report) is left alone
                        st = self.futures.get(msg.get("key"))
                        if st is not None and st.status in ("error", "lost"):
                            st.retry()
                    elif op == "pubsub-msg":
                        for sub in self._pubsub_subs.get(msg.get("name"), ()):
                            sub._put(msg.get("msg"))
                    elif op == "event":
                        for handler in self._event_handlers.get(
                            msg.get("topic"), ()
                        ):
                            try:
                                handler(msg.get("msg"))
                            except Exception:
                                logger.exception("event handler failed")
                    elif op in ("stream-closed", "close", "restart"):
                        if op == "restart":
                            # the initiating client cancels its futures
                            # in restart() itself; its tagged echo must
                            # not cancel work submitted since (the
                            # report stream is unordered with the rpc).
                            # Other clients cancel exactly the keys the
                            # scheduler snapshotted as theirs AT restart
                            # time — futures whose submission the
                            # scheduler processed after the restart are
                            # alive and must survive the echo.
                            if msg.get("initiator") != self.id:
                                keys = msg.get("keys")
                                if keys is None:
                                    targets = list(self.futures.values())
                                else:
                                    targets = [
                                        st for k in keys
                                        if (st := self.futures.get(k))
                                        is not None
                                    ]
                                for st in targets:
                                    st.cancel()
                        if op != "restart":
                            return
        except (CommClosedError, asyncio.CancelledError):
            pass
        finally:
            if self.status == "running":
                self.status = "connection-lost"
                for st in self.futures.values():
                    if not st.event.is_set():
                        st.set_error(
                            CommClosedError("lost connection to scheduler"), None
                        )

    def _handle_key_in_memory(self, key: Key = "", type: str | None = None,
                              **kw: Any) -> None:
        st = self.futures.get(key)
        if st is not None:
            st.finish(type=type)

    def _handle_task_erred(self, key: Key = "", exception: Any = None,
                           traceback: Any = None, **kw: Any) -> None:
        st = self.futures.get(key)
        if st is not None:
            exc = unwrap(exception)
            if not isinstance(exc, BaseException):
                exc = Exception(str(exc))
            st.set_error(exc, unwrap(traceback), kw.get("traceback_text", ""))

    def _handle_lost_data(self, key: Key = "", **kw: Any) -> None:
        st = self.futures.get(key)
        if st is not None:
            st.lose()

    # ---------------------------------------------------------- refcounting

    def _inc_ref(self, key: Key) -> None:
        self.refcount[key] = self.refcount.get(key, 0) + 1

    def _dec_ref(self, key: Key) -> None:
        n = self.refcount.get(key, 0) - 1
        if n <= 0:
            self.refcount.pop(key, None)
            self.futures.pop(key, None)
            # a pending cancel-confirmation for a dead key will never
            # matter again; don't let the sentinel (and its FutureState)
            # outlive the futures entry
            self._cancel_expected.pop(key, None)
            if self.status == "running" and not self.batched_stream.closed():
                try:
                    self.batched_stream.send(
                        {
                            "op": "client-releases-keys",
                            "keys": [key],
                            "client": self.id,
                        }
                    )
                except CommClosedError:
                    pass
        else:
            self.refcount[key] = n

    # ------------------------------------------------------------ submission

    def _warn_large_function(self, fn: Callable) -> None:
        """Task specs are serialized independently (one opaque leaf per
        task — the scheduler never unpickles them), so a large captured
        closure is pickled once PER TASK, not once per graph.  Warn like
        the reference (client.py 'Large object of size ... detected')
        and point at scatter, which exists for exactly this."""
        try:
            nbytes = self._fn_sizes.get(fn)
        except TypeError:
            return  # unhashable/unweakrefable callable: skip the check
        if nbytes is None:
            try:
                from distributed_tpu.protocol.pickle import dumps

                nbytes = len(dumps(fn))
                self._fn_sizes[fn] = nbytes
            except Exception:
                return
        else:
            return  # measured before: already warned if it was large
        threshold = config.parse_bytes(
            config.get("admin.large-function-warning-bytes")
        )
        if threshold and nbytes > threshold:
            logger.warning(
                "Large function payload (%.1f MiB) detected in map(): it is "
                "serialized once per task. Move captured data into "
                "arguments via client.scatter() and pass the future instead.",
                nbytes / 2**20,
            )

    def _graph_to_futures(
        self,
        tasks: dict[Key, Any],
        keys: list[Key],
        *,
        priority: int = 0,
        workers: list[str] | str | None = None,
        allow_other_workers: bool = False,
        resources: dict | None = None,
        retries: int | None = None,
        actors: Any = False,
        annotations_by_key: dict[Key, dict] | None = None,
    ) -> dict[Key, Future]:
        """Ship a graph, returning futures for ``keys``
        (reference client.py:3098)."""
        deps = {
            k: sorted(spec.dependencies()) if isinstance(spec, TaskSpec) else []
            for k, spec in tasks.items()
        }
        annotations: dict[Key, dict] = dict(annotations_by_key or {})
        ann: dict[str, Any] = {}
        from distributed_tpu.diagnostics.spans import current_span

        active_span = current_span()
        if active_span:
            ann["span"] = list(active_span)
        if workers is not None:
            ann["workers"] = workers
            if allow_other_workers:
                ann["allow_other_workers"] = True
        if resources:
            ann["resources"] = resources
        if retries:
            ann["retries"] = retries
        if ann:
            annotations = {k: {**ann, **annotations.get(k, {})} for k in tasks}
        futures: dict[Key, Future] = {}
        for key in keys:
            if key not in self.futures:
                self.futures[key] = FutureState()
            futures[key] = Future(key, self)
        self._generation += 1
        self.batched_stream.send(
            {
                "op": "update-graph",
                "client": self.id,
                # one Serialize leaf PER TASK, not one blob: the scheduler
                # (deserialize=False) stores each run_spec as opaque frames
                # and forwards them to workers verbatim — user code is
                # unpickled only where it runs
                "tasks": {k: Serialize(v) for k, v in tasks.items()},
                "dependencies": deps,
                "keys": list(keys),
                "user_priority": priority,
                "annotations_by_key": annotations or None,
                "actors": actors,
                "stimulus_id": seq_name("update-graph"),
            }
        )
        return futures

    def submit(
        self,
        fn: Callable,
        *args: Any,
        key: Key | None = None,
        pure: bool = True,
        priority: int = 0,
        workers: list[str] | str | None = None,
        allow_other_workers: bool = False,
        resources: dict | None = None,
        retries: int | None = None,
        actor: bool = False,
        **kwargs: Any,
    ) -> Future:
        """Run ``fn(*args, **kwargs)`` on the cluster (reference client.py:1828)."""
        if key is None:
            if pure and not actor:
                key = f"{funcname(fn)}-{tokenize(fn, args, tuple(sorted(kwargs.items())))}"
            else:
                key = f"{funcname(fn)}-{uuid.uuid4().hex[:16]}"
        st = self.futures.get(key)
        if st is not None:
            if st.status != "cancelled":
                return Future(key, self)
            # resubmission of a cancelled key: replace the stale client
            # state so a fresh task goes to the scheduler — but KEEP the
            # refcount: old cancelled Future objects still reference the
            # key, and their later release must not free the new task
            del self.futures[key]
        spec_args = _futures_to_refs(args)
        spec_kwargs = _futures_to_refs(kwargs)
        tasks: dict[Key, Any] = {key: TaskSpec(fn, spec_args, spec_kwargs)}
        futs = self._graph_to_futures(
            tasks, [key], priority=priority, workers=workers,
            allow_other_workers=allow_other_workers, resources=resources,
            retries=retries, actors=[key] if actor else False,
        )
        return futs[key]

    def map(
        self,
        fn: Callable,
        *iterables: Iterable,
        key: str | None = None,
        pure: bool = True,
        priority: int = 0,
        workers: list[str] | str | None = None,
        allow_other_workers: bool = False,
        resources: dict | None = None,
        retries: int | None = None,
        **kwargs: Any,
    ) -> list[Future]:
        """Map a function over argument lists (reference client.py:1967)."""
        iterables = tuple(list(it) for it in iterables)
        prefix = key or funcname(fn)
        self._warn_large_function(fn)
        tasks: dict[Key, Any] = {}
        keys: list[Key] = []
        for i, zargs in enumerate(zip(*iterables)):
            if pure:
                k = f"{prefix}-{tokenize(fn, zargs, tuple(sorted(kwargs.items())))}"
            else:
                k = f"{prefix}-{uuid.uuid4().hex[:16]}"
            keys.append(k)
            if k in tasks:
                continue
            st = self.futures.get(k)
            if st is not None:
                if st.status != "cancelled":
                    continue
                # same cancelled-key resubmission contract as submit()
                del self.futures[k]
            tasks[k] = TaskSpec(fn, _futures_to_refs(zargs), _futures_to_refs(kwargs))
        futs = self._graph_to_futures(
            {k: v for k, v in tasks.items()},
            [k for k in dict.fromkeys(keys)],
            priority=priority, workers=workers,
            allow_other_workers=allow_other_workers, resources=resources,
            retries=retries,
        )
        return [futs.get(k) or Future(k, self) for k in keys]

    def compute_graph(self, graph: Graph, keys: list[Key], **kwargs: Any
                      ) -> dict[Key, Future]:
        """Submit a pre-built ``Graph`` (the collections entry point)."""
        graph.validate()
        return self._graph_to_futures(dict(graph.tasks), keys, **kwargs)

    # ------------------------------------------------------------- results

    async def _result(self, future: Future, timeout: float | None = None) -> Any:
        st = self.futures.get(future.key)
        if st is None:
            raise asyncio.CancelledError(future.key)
        # one deadline for the WHOLE wait: re-waits after a task-retried
        # reset must not re-arm the user's timeout
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else loop.time() + timeout
        remaining = (
            (lambda: None) if deadline is None
            else (lambda: max(deadline - loop.time(), 0.001))
        )
        await asyncio.wait_for(st.event.wait(), remaining())
        while st.status == "pending":
            # woken by a terminal state that a task-retried report then
            # reset before this coroutine resumed: the key is being
            # recomputed — wait for the NEW attempt, don't gather it
            await asyncio.wait_for(st.event.wait(), remaining())
        if st.status == "error":
            assert st.exception is not None
            raise st.exception
        if st.status == "cancelled":
            raise asyncio.CancelledError(future.key)
        data = await self._gather_keys([future.key])
        return self._maybe_actor(data[future.key])

    def _maybe_actor(self, value: Any) -> Any:
        from distributed_tpu.client.actor import Actor, ActorPlaceholder

        if isinstance(value, ActorPlaceholder):
            return Actor.from_placeholder(value, io=self._worker_rpc(value.worker))
        return value

    def _worker_rpc(self, address: str):
        """Cached direct rpc to a worker (actor calls, direct gather)."""
        r = self._worker_rpcs.get(address)
        if r is None:
            from distributed_tpu.rpc.core import rpc as _rpc

            r = self._worker_rpcs[address] = _rpc(
                address, connection_args=self._connection_args
            )
        return r

    async def gather(self, futures: Any, errors: str = "raise") -> Any:
        """Wait for and download many futures (reference client.py:2317);
        preserves the nesting structure of ``futures``."""
        flat: list[Future] = []
        _collect_futures(futures, flat)
        # wait for completion
        for f in flat:
            st = self.futures.get(f.key)
            if st is None:
                if errors == "skip":
                    continue
                raise asyncio.CancelledError(f.key)
            await st.event.wait()
            while st.status == "pending":
                # set_error raced a task-retried reset (see _result):
                # re-wait for the new attempt's completion
                await st.event.wait()
            if st.status == "error" and errors == "raise":
                assert st.exception is not None
                raise st.exception
            if st.status == "cancelled" and errors == "raise":
                raise asyncio.CancelledError(f.key)
        keys = [
            f.key
            for f in flat
            if (st := self.futures.get(f.key)) is not None
            and st.status == "finished"
        ]
        data = await self._gather_keys(list(dict.fromkeys(keys)))
        return _substitute_futures(futures, data, errors)

    def _ensure_tracked(self, key: Key) -> "FutureState":
        """Track a key learned out-of-band (queue/variable/dataset): register
        interest with the scheduler, which reports its current state."""
        st = self.futures.get(key)
        if st is None:
            st = self.futures[key] = FutureState()
            self.batched_stream.send(
                {"op": "client-desires-keys", "keys": [key], "client": self.id}
            )
        return st

    async def _gather_keys(self, keys: list[Key]) -> dict[Key, Any]:
        if not keys:
            return {}
        assert self.scheduler is not None
        attempts = 3
        for attempt in range(attempts):
            resp = await self.scheduler.gather(keys=keys)
            if resp.get("status") == "OK":
                return {
                    k: self._maybe_actor(unwrap(v))
                    for k, v in resp["data"].items()
                }
            missing = resp.get("keys", [])
            logger.warning("gather attempt %d missing %s", attempt, missing)
            await asyncio.sleep(0.1 * (attempt + 1))
        raise KeyError(f"could not gather keys: {missing}")

    async def scatter(
        self,
        data: Any,
        workers: list[str] | None = None,
        broadcast: bool = False,
        hash: bool = True,
    ) -> Any:
        """Push local data into cluster memory (reference client.py:2486)."""
        unpack_single = False
        if isinstance(data, dict):
            named = {str(k): v for k, v in data.items()}
        else:
            if not isinstance(data, (list, tuple, set)):
                data = [data]
                unpack_single = True
            named = {}
            for v in data:
                if hash:
                    k = f"{type(v).__name__}-{tokenize_data(v)}"
                else:
                    k = f"{type(v).__name__}-{uuid.uuid4().hex[:16]}"
                named[k] = v
        assert self.scheduler is not None
        for key in named:
            if key not in self.futures:
                self.futures[key] = FutureState()
        keys = await self.scheduler.scatter(
            data={k: Serialize(v) for k, v in named.items()},
            client=self.id,
            workers=workers,
            broadcast=broadcast,
        )
        futs = {}
        for k in keys:
            self.futures[k].finish()
            futs[k] = Future(k, self)
        if isinstance(data, dict):
            return futs
        out = [futs[k] for k in named if k in futs]
        return out[0] if unpack_single else out

    async def cancel(self, futures: Iterable[Future], force: bool = False) -> None:
        keys = [f.key for f in futures]
        # cancel synchronously client-side (reference client.py _cancel):
        # the scheduler's confirmation rides the batched stream and could
        # otherwise cancel a future resubmitted in the meantime.  A key
        # with no state still registers (None) so the confirmation can
        # never hit a later resubmission.
        for k in keys:
            st = self.futures.get(k)
            if st is not None:
                st.cancel()
            self._cancel_expected[k] = st
        assert self.scheduler is not None
        await self.scheduler.cancel(keys=keys, client=self.id, force=force)

    async def retry(self, futures: Iterable[Future]) -> None:
        keys = []
        for f in futures:
            st = self.futures.get(f.key)
            if st is not None:
                st.retry()
            keys.append(f.key)
        assert self.scheduler is not None
        await self.scheduler.retry(keys=keys, client=self.id)

    # ------------------------------------------------------------ cluster ops

    async def run(self, fn: Callable, *args: Any,
                  workers: list[str] | None = None, wait: bool = True,
                  nanny: bool = False, **kwargs: Any) -> dict:
        """Run a function on workers (or their nannies with nanny=True)
        outside the task system (reference client.py:2904)."""
        assert self.scheduler is not None
        resp = await self.scheduler.broadcast(
            msg={
                "op": "run",
                "function": Serialize(fn),
                "args": Serialize(args),
                "kwargs": Serialize(kwargs),
                "wait": wait,
            },
            workers=workers,
            nanny=nanny,
        )
        out = {}
        for addr, r in resp.items():
            if isinstance(r, dict) and r.get("status") == "error":
                raise_remote_error(r)
            out[addr] = unwrap(r.get("result")) if isinstance(r, dict) else r
        return out

    async def run_on_scheduler(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        assert self.scheduler is not None
        resp = await self.scheduler.run_function(
            function=Serialize(fn), args=Serialize(args), kwargs=Serialize(kwargs)
        )
        if resp.get("status") == "error":
            raise_remote_error(resp)
        return unwrap(resp.get("result"))

    async def restart(self) -> None:
        """Forget every task cluster-wide; cancel this client's futures.

        The report stream is unordered with the rpc reply, so the echo
        is initiator-tagged and skipped here (a counter would leak on
        rpc failure).  Futures cancel in a finally: restart's intent is
        cancel-everything, and on an rpc failure the scheduler may or
        may not have restarted — pending futures must not hang either
        way."""
        assert self.scheduler is not None
        try:
            await self.scheduler.restart(client=self.id)
        finally:
            for st in self.futures.values():
                st.cancel()

    async def rebalance(self, futures: Iterable[Future] | None = None,
                        workers: list[str] | None = None) -> dict:
        """Even data across workers (reference client.py:3824)."""
        assert self.scheduler is not None
        keys = [f.key for f in futures] if futures is not None else None
        return await self.scheduler.rebalance(keys=keys, workers=workers)

    async def replicate(self, futures: Iterable[Future], n: int | None = None,
                        workers: list[str] | None = None) -> None:
        """Copy futures' data onto additional workers
        (reference client.py:3732)."""
        assert self.scheduler is not None
        await self.scheduler.replicate(
            keys=[f.key for f in futures], n=n, workers=workers
        )

    async def register_plugin(self, plugin: Any, name: str | None = None,
                              nanny: bool | None = None) -> Any:
        """Install a Scheduler/Worker/Nanny plugin cluster-wide
        (reference client.py register_plugin).

        ``nanny`` overrides the isinstance routing (reference has the
        same parameter): a NannyPlugin like ``UploadDirectory`` on a
        nanny-LESS cluster would otherwise broadcast to zero nannies and
        silently ship nothing — pass ``nanny=False`` to run its setup on
        the workers instead."""
        from distributed_tpu.diagnostics.plugin import (
            NannyPlugin,
            SchedulerPlugin,
        )

        assert self.scheduler is not None
        name = name or getattr(plugin, "name", None)
        if isinstance(plugin, SchedulerPlugin):
            return await self.scheduler.register_scheduler_plugin(
                plugin=Serialize(plugin), name=name
            )
        if nanny if nanny is not None else isinstance(plugin, NannyPlugin):
            resp = await self.scheduler.register_nanny_plugin(
                plugin=Serialize(plugin), name=name
            )
        else:
            # default: worker plugin (reference treats unknown as one)
            resp = await self.scheduler.register_worker_plugin(
                plugin=Serialize(plugin), name=name
            )
        # a failing setup() must not pass silently: the broadcast result
        # carries per-node error_message dicts (reference re-raises too)
        if isinstance(resp, dict):
            for r in resp.values():
                if isinstance(r, dict) and r.get("status") == "error":
                    from distributed_tpu.rpc.core import raise_remote_error

                    raise_remote_error(r)
        return resp

    async def unregister_worker_plugin(self, name: str) -> Any:
        assert self.scheduler is not None
        return await self.scheduler.unregister_worker_plugin(name=name)

    async def upload_file(self, path: str) -> None:
        """Ship a source file to all current and future workers
        (reference client.py:3767)."""
        from distributed_tpu.diagnostics.plugin import UploadFile

        await self.register_plugin(
            UploadFile(path), name=f"upload-{os.path.basename(path)}"
        )

    async def dump_cluster_state(self, filename: str | None = None) -> dict:
        """Full-state debug dump (reference client.py dump_cluster_state,
        cluster_dump.py)."""
        assert self.scheduler is not None
        state = await self.scheduler.get_cluster_state()
        if filename:
            import json

            def _write() -> None:  # dump can be huge: keep it off-loop
                with open(filename, "w") as f:
                    json.dump(state, f, default=str, indent=1)

            await asyncio.get_running_loop().run_in_executor(None, _write)
        return state

    async def memory_trace_start(self, workers: list[str] | None = None) -> dict:
        """Begin allocation tracing on workers (reference memray.py role;
        stdlib tracemalloc — no extra dependency)."""
        assert self.scheduler is not None
        return await self.scheduler.broadcast(
            msg={"op": "memory_trace", "action": "start"}, workers=workers
        )

    async def memory_trace_stop(self, workers: list[str] | None = None) -> dict:
        assert self.scheduler is not None
        return await self.scheduler.broadcast(
            msg={"op": "memory_trace", "action": "stop"}, workers=workers
        )

    async def memory_trace_report(self, top_n: int = 10,
                                  workers: list[str] | None = None) -> dict:
        """Per-worker top allocation sites + data-store view, so leaked
        interpreter memory is distinguishable from stored results."""
        assert self.scheduler is not None
        from distributed_tpu.protocol.serialize import nested_deserialize

        return nested_deserialize(await self.scheduler.broadcast(
            msg={"op": "memory_trace", "action": "report", "top_n": top_n},
            workers=workers,
        ))

    async def device_profile_start(
        self, workers: list[str] | None = None,
        logdir: str | None = None,
    ) -> dict:
        """Begin an XLA device-timeline trace on workers (the
        reference's low-level profiler role, profile.py:550 — see
        diagnostics/device_profile.py).  Tasks executed while tracing
        carry their key as a device-timeline annotation."""
        assert self.scheduler is not None
        return await self.scheduler.broadcast(
            msg={"op": "device_profile", "action": "start",
                 "logdir": logdir},
            workers=workers,
        )

    async def device_profile_stop(
        self, workers: list[str] | None = None
    ) -> dict:
        """End the device trace; each worker reports its trace directory
        (TensorBoard/XProf ``plugins/profile`` format) and the files
        captured."""
        assert self.scheduler is not None
        return await self.scheduler.broadcast(
            msg={"op": "device_profile", "action": "stop"},
            workers=workers,
        )

    async def recreate_error_locally(self, future: Future) -> None:
        """Re-run a failed task in this process for debugging
        (reference recreate_tasks.py:15)."""
        st = self.futures.get(future.key)
        if st is None:
            raise ValueError(f"unknown future {future.key}")
        await st.event.wait()
        if st.status != "error":
            raise ValueError(f"future {future.key} did not err")
        assert self.scheduler is not None
        resp = await self.scheduler.get_runspec(key=future.key)
        spec = unwrap(resp["run_spec"])
        deps = await self._gather_keys(resp["deps"])
        fn, args, kwargs = spec.substitute(deps)
        # raises the task's error in the caller's process
        if asyncio.iscoroutinefunction(fn):
            await fn(*args, **kwargs)
        else:
            fn(*args, **kwargs)

    # ------------------------------------------------------- observability

    def log_event(self, topic: str, msg: Any) -> None:
        """Record a structured event on the scheduler (reference
        client.py log_event)."""
        self.batched_stream.send(
            {"op": "log-event-client", "topic": topic, "msg": msg,
             "client": self.id}
        )

    async def get_events(self, topic: str | None = None) -> Any:
        assert self.scheduler is not None
        return await self.scheduler.events(topic=topic)

    def subscribe_topic(self, topic: str, handler: Callable) -> None:
        """Call ``handler(msg)`` for every event on ``topic``
        (reference client.py:4503)."""
        self._event_handlers.setdefault(topic, []).append(handler)
        self.batched_stream.send(
            {"op": "subscribe-topic", "topic": topic, "client": self.id}
        )

    def unsubscribe_topic(self, topic: str) -> None:
        self._event_handlers.pop(topic, None)
        self.batched_stream.send(
            {"op": "unsubscribe-topic", "topic": topic, "client": self.id}
        )

    async def get_task_stream(self, start: float | None = None,
                              count: int | None = None) -> list:
        assert self.scheduler is not None
        return await self.scheduler.get_task_stream(start=start, count=count)

    async def get_spans(self) -> list:
        assert self.scheduler is not None
        return await self.scheduler.get_spans()

    async def get_versions(self, check: bool = False) -> dict:
        """Version info for scheduler, workers, and this client
        (reference client.py get_versions)."""
        from distributed_tpu.versions import get_versions, version_mismatches

        assert self.scheduler is not None
        out = {
            "client": get_versions(),
            "scheduler": await self.scheduler.versions(),
            "workers": await self.scheduler.worker_versions(),
        }
        mismatches = version_mismatches(out)
        if mismatches and check:
            raise ValueError(f"version mismatches: {mismatches}")
        out["mismatches"] = mismatches
        return out

    async def benchmark_hardware(self) -> dict:
        """Memory/disk bandwidth micro-benchmarks on every worker
        (reference scheduler.py:7590)."""
        assert self.scheduler is not None
        return await self.scheduler.benchmark_hardware()

    async def performance_report(self, filename: str = "dtpu-report.html"
                                 ) -> str:
        """Self-contained HTML snapshot (reference scheduler.py:8077)."""
        assert self.scheduler is not None
        html = await self.scheduler.performance_report_html()

        def _write() -> None:
            with open(filename, "w") as f:
                f.write(html)

        await asyncio.get_running_loop().run_in_executor(None, _write)
        return filename

    async def eventstream_start(self) -> str:
        """Opt into per-task completion events; returns the topic name.
        The reference is tied to this client: it is released on
        disconnect even if :meth:`eventstream_stop` is never called."""
        assert self.scheduler is not None
        return await self.scheduler.eventstream_start(client=self.id)

    async def eventstream_stop(self) -> None:
        assert self.scheduler is not None
        await self.scheduler.eventstream_stop(client=self.id)

    async def profile(self, workers: list[str] | None = None,
                      start: float | None = None) -> dict:
        assert self.scheduler is not None
        return await self.scheduler.get_profile(workers=workers, start=start)

    async def publish_dataset(self, name: str, data: Any,
                              override: bool = False) -> None:
        """Publish futures/data under a name that outlives this client
        (reference client.py publish_dataset)."""
        flat: list[Future] = []
        _collect_futures(data, flat)
        assert self.scheduler is not None
        await self.scheduler.publish_put(
            name=name,
            keys=[f.key for f in flat],
            data=Serialize(data),
            override=override,
        )

    async def get_dataset(self, name: str) -> Any:
        assert self.scheduler is not None
        out = await self.scheduler.publish_get(name=name)
        if out is None:
            raise KeyError(f"dataset {name!r} not found")
        data = unwrap(out["data"])
        for key in out["keys"]:
            self._ensure_tracked(key)
        return _rebind_futures(data, self)

    async def list_datasets(self) -> list[str]:
        assert self.scheduler is not None
        return await self.scheduler.publish_list()

    async def unpublish_dataset(self, name: str) -> None:
        assert self.scheduler is not None
        await self.scheduler.publish_delete(name=name)

    async def who_has(self, futures: Iterable[Future] | None = None) -> dict:
        assert self.scheduler is not None
        keys = [f.key for f in futures] if futures is not None else None
        return await self.scheduler.who_has(keys=keys)

    async def has_what(self, workers: list[str] | None = None) -> dict:
        assert self.scheduler is not None
        return await self.scheduler.has_what(workers=workers)

    async def ncores(self, workers: list[str] | None = None) -> dict:
        assert self.scheduler is not None
        return await self.scheduler.ncores(workers=workers)

    nthreads = ncores

    async def scheduler_info(self) -> dict:
        assert self.scheduler is not None
        self._scheduler_identity = await self.scheduler.identity()
        return self._scheduler_identity

    async def wait_for_workers(
        self, n_workers: int, timeout: float | None = None
    ) -> None:
        """Block until ``n_workers`` are registered and running
        (reference client.py wait_for_workers)."""
        deadline = (time() + timeout) if timeout is not None else None
        while True:
            info = await self.scheduler_info()
            workers = info.get("workers", {})
            running = sum(
                1 for w in workers.values()
                if w.get("status", "running") == "running"
            )
            if running >= n_workers:
                return
            if deadline is not None and time() > deadline:
                raise TimeoutError(
                    f"only {running}/{n_workers} workers after {timeout}s"
                )
            await asyncio.sleep(0.05)

    def get_executor(self, **kwargs: Any):
        """concurrent.futures.Executor facade (reference client.py
        get_executor)."""
        from distributed_tpu.client.cfexecutor import ClientExecutor

        return ClientExecutor(self, **kwargs)

    def __repr__(self) -> str:
        return f"<Client {self.id!r} {self.status} scheduler={self.address!r}>"

    def _repr_html_(self) -> str:
        """Notebook widget (the reference's jinja2 ``widgets/`` role):
        connection summary plus the worker/thread/memory rollup from the
        last ``scheduler_info()`` snapshot (repr must not block)."""
        def format_bytes(n: float) -> str:
            for unit in ("B", "kiB", "MiB", "GiB", "TiB"):
                if n < 1024 or unit == "TiB":
                    return f"{n:.2f} {unit}"
                n /= 1024
            return f"{n:.2f} TiB"  # pragma: no cover

        rows = [
            ("Status", str(self.status)),
            ("Scheduler", str(self.address)),
        ]
        info = self._scheduler_identity or {}
        workers = info.get("workers", {})
        if workers:
            rows.append(("Workers", str(len(workers))))
            rows.append((
                "Threads",
                str(sum(w.get("nthreads", 0) for w in workers.values())),
            ))
            mem = sum(w.get("memory_limit") or 0 for w in workers.values())
            if mem:
                rows.append(("Memory", format_bytes(mem)))
        dash = info.get("dashboard")
        if dash:
            rows.append(("Dashboard", f'<a href="{dash}">{dash}</a>'))
        body = "".join(
            f"<tr><th style='text-align:left'>{k}</th><td>{v}</td></tr>"
            for k, v in rows
        )
        return (
            f"<h4 style='margin-bottom:0'>Client {self.id}</h4>"
            f"<table>{body}</table>"
        )


# ------------------------------------------------------------ helpers


def _futures_to_refs(obj: Any) -> Any:
    """Deep-replace Future objects with TaskRef markers."""
    if isinstance(obj, Future):
        return TaskRef(obj.key)
    if isinstance(obj, tuple):
        return tuple(_futures_to_refs(o) for o in obj)
    if isinstance(obj, list):
        return [_futures_to_refs(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _futures_to_refs(v) for k, v in obj.items()}
    return obj


def _rebind_futures(obj: Any, client: "Client") -> Any:
    """Re-point unpickled Future objects at this client."""
    if isinstance(obj, Future):
        return Future(obj.key, client)
    if isinstance(obj, tuple):
        return tuple(_rebind_futures(o, client) for o in obj)
    if isinstance(obj, list):
        return [_rebind_futures(o, client) for o in obj]
    if isinstance(obj, (set, frozenset)):
        return type(obj)(_rebind_futures(o, client) for o in obj)
    if isinstance(obj, dict):
        return {k: _rebind_futures(v, client) for k, v in obj.items()}
    return obj


def _collect_futures(obj: Any, out: list[Future]) -> None:
    if isinstance(obj, Future):
        out.append(obj)
    elif isinstance(obj, (list, tuple, set)):
        for o in obj:
            _collect_futures(o, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_futures(v, out)


def _substitute_futures(obj: Any, data: dict[Key, Any], errors: str) -> Any:
    if isinstance(obj, Future):
        return data.get(obj.key)
    if isinstance(obj, tuple):
        return tuple(_substitute_futures(o, data, errors) for o in obj)
    if isinstance(obj, list):
        return [_substitute_futures(o, data, errors) for o in obj]
    if isinstance(obj, set):
        return {_substitute_futures(o, data, errors) for o in obj}
    if isinstance(obj, dict):
        return {k: _substitute_futures(v, data, errors) for k, v in obj.items()}
    return obj


def tokenize_data(v: Any) -> str:
    return tokenize(type(v).__name__, repr(v)[:1000])


async def wait(futures: Any, timeout: float | None = None,
               return_when: str = "ALL_COMPLETED") -> Any:
    """Block until futures finish (reference client.py wait)."""
    flat: list[Future] = []
    _collect_futures(futures, flat)

    async def _one(f: Future):
        st = f.client.futures.get(f.key)
        if st is not None:
            await st.event.wait()
        return f

    if return_when == "FIRST_COMPLETED":
        done_set, pending = set(), set(flat)
        tasks = {asyncio.ensure_future(_one(f)): f for f in flat}
        done, not_done = await asyncio.wait(
            tasks, timeout=timeout, return_when=asyncio.FIRST_COMPLETED
        )
        for t in not_done:
            t.cancel()
        for t in done:
            done_set.add(tasks[t])
            pending.discard(tasks[t])
        return _DoneAndNotDone(done_set, pending)
    await asyncio.wait_for(
        asyncio.gather(*(_one(f) for f in flat)), timeout
    )
    return _DoneAndNotDone(set(flat), set())


class _DoneAndNotDone:
    def __init__(self, done: set, not_done: set):
        self.done = done
        self.not_done = not_done


class as_completed:
    """Iterate over futures in completion order (reference client.py:~5600)."""

    def __init__(self, futures: Iterable[Future] = (), *, with_results: bool = False):
        self.with_results = with_results
        self.queue: asyncio.Queue = asyncio.Queue()
        self.count = 0
        for f in futures:
            self.add(f)

    def add(self, future: Any) -> None:
        self.count += 1

        async def _watch(f: Any = future):
            if hasattr(f, "client"):  # task Future
                st = f.client.futures.get(f.key)
                if st is not None:
                    await st.event.wait()
                if self.with_results:
                    try:
                        result = await f.result()
                    except BaseException as e:  # noqa: B036
                        result = e
                    await self.queue.put((f, result))
                else:
                    await self.queue.put(f)
                return
            # ActorFuture (or any awaitable handle): completion IS the
            # await (reference actor futures iterate with as_completed
            # next to task futures)
            try:
                result = await f
            except BaseException as e:  # noqa: B036
                result = e
            if self.with_results:
                await self.queue.put((f, result))
            else:
                await self.queue.put(f)

        asyncio.ensure_future(_watch())

    def __aiter__(self) -> "as_completed":
        return self

    async def __anext__(self):
        if self.count == 0:
            raise StopAsyncIteration
        self.count -= 1
        return await self.queue.get()
