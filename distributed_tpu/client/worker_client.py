"""Tasks submitting sub-tasks from inside a worker
(reference worker_client.py, worker.py:2799 secede/rejoin).

``secede()`` tells the worker's state machine the current task left its
thread slot (a LongRunningMsg flows to the scheduler, which frees the
occupancy); ``worker_client()`` secedes and yields a Client connected to
the same scheduler, running on its own loop thread so the (synchronous)
task body can drive it with ``client.sync(...)``.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

from distributed_tpu.utils.misc import seq_name


def secede() -> None:
    """Remove the current task from its worker thread slot
    (reference worker.py:2799, threadpoolexecutor.py:70).

    Works from executor-thread task bodies AND from coroutine task
    bodies on the worker's event loop (the key rides a contextvar
    there); only the thread flavor grows the OS pool — a coroutine
    holds no thread."""
    from distributed_tpu.worker.context import (
        get_task_key,
        get_thread_key,
        get_worker,
    )
    from distributed_tpu.worker.state_machine import LongRunningEvent

    worker = get_worker()
    key = get_task_key()
    if key is None:
        raise ValueError("secede() must be called from inside a task")
    event = LongRunningEvent(
        stimulus_id=seq_name("secede"), key=key, compute_duration=0.0
    )
    if get_thread_key() is None:
        # coroutine body: already on the worker's loop
        worker.handle_stimulus(event)
        return
    worker.loop.call_soon_threadsafe(worker.handle_stimulus, event)
    # free the OS thread too: the state machine released the slot, but this
    # thread stays blocked in the task body — grow the pool so another task
    # can actually run (reference threadpoolexecutor.py:70 grows the same way)
    ex = worker.executor
    ex._max_workers += 1
    ex._adjust_thread_count()


def rejoin() -> None:
    """Undo secede()'s pool growth when the seceded section ends
    (reference threadpoolexecutor.py rejoin)."""
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    ex = worker.executor
    if ex._max_workers > worker.nthreads:
        ex._max_workers -= 1  # pool shrinks lazily as threads idle out


@contextlib.contextmanager
def worker_client(separate_thread: bool = True) -> Iterator:
    """Context manager yielding a Client usable from inside a task
    (reference worker_client.py).

    The task secedes first so the cluster does not deadlock waiting for
    the thread slot it occupies while it, in turn, waits on sub-tasks.
    """
    from distributed_tpu.client.client import Client
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    if separate_thread:
        secede()
    client = Client(worker.scheduler_addr, asynchronous=False)
    try:
        yield client
    finally:
        client.__exit__()
        if separate_thread:
            rejoin()
