"""distributed_tpu — a TPU-native distributed dynamic task-scheduling framework.

Capabilities of dask/distributed (reference at /root/reference), re-architected
TPU-first: a central asynchronous Scheduler whose hot loops (worker placement,
the transition engine, work stealing, replica management) run as jit-compiled
JAX kernels over a device-array mirror of scheduler state, peer-to-peer
Workers with a deterministic sans-IO state machine, a Client with Futures, and
a pluggable comm/serialization stack.
"""

from __future__ import annotations

__version__ = "0.1.0"

from distributed_tpu import config
from distributed_tpu.graph import Graph, TaskRef, TaskSpec

__all__ = [
    "config",
    "Graph",
    "TaskRef",
    "TaskSpec",
    "__version__",
]


def __getattr__(name: str):
    # Lazy re-exports so `import distributed_tpu` stays light and cycle-free.
    if name in ("Client", "Future", "as_completed", "wait", "fire_and_forget"):
        from distributed_tpu.client import client as _c

        return getattr(_c, name)
    if name == "Scheduler":
        from distributed_tpu.scheduler.server import Scheduler

        return Scheduler
    if name == "Worker":
        from distributed_tpu.worker.server import Worker

        return Worker
    if name == "Nanny":
        from distributed_tpu.worker.nanny import Nanny

        return Nanny
    if name == "LocalCluster":
        from distributed_tpu.deploy.local import LocalCluster

        return LocalCluster
    if name in ("SpecCluster", "Adaptive", "Cluster"):
        from distributed_tpu.deploy import spec as _spec

        return getattr(_spec, name)
    if name in ("Semaphore", "Lock", "MultiLock", "Event", "Queue", "Variable", "Pub", "Sub"):
        from distributed_tpu import coordination as _coord

        return getattr(_coord, name)
    if name == "Actor":
        from distributed_tpu.client.actor import Actor

        return Actor
    if name in ("SchedulerPlugin", "WorkerPlugin", "NannyPlugin"):
        from distributed_tpu.diagnostics import plugin as _p

        return getattr(_p, name)
    if name in ("SSHCluster", "SubprocessCluster"):
        from distributed_tpu import deploy as _d

        return getattr(_d, name)
    if name in ("progress", "progress_sync"):
        from distributed_tpu.diagnostics import progressbar as _pb

        return getattr(_pb, name)
    raise AttributeError(f"module 'distributed_tpu' has no attribute {name!r}")


_LAZY = (
    "Client", "Future", "as_completed", "wait", "fire_and_forget",
    "Scheduler", "Worker", "Nanny", "LocalCluster", "SpecCluster",
    "Adaptive", "Cluster", "Semaphore", "Lock", "MultiLock", "Event",
    "Queue", "Variable", "Pub", "Sub", "Actor", "SchedulerPlugin",
    "WorkerPlugin", "NannyPlugin", "SSHCluster", "SubprocessCluster",
    "progress", "progress_sync",
)


def __dir__() -> list[str]:
    # surface the lazy exports to dir()/tab-completion
    return sorted(set(globals()) | set(_LAZY))
