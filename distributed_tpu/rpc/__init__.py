from distributed_tpu.rpc.batched import BatchedSend
from distributed_tpu.rpc.core import (
    AsyncTaskGroup,
    ConnectionPool,
    PeriodicCallback,
    PooledRPCCall,
    Server,
    Status,
    clean_exception,
    error_message,
    raise_remote_error,
    rpc,
    send_recv,
)

__all__ = [
    "Server", "Status", "rpc", "send_recv", "ConnectionPool", "PooledRPCCall",
    "BatchedSend", "AsyncTaskGroup", "PeriodicCallback", "error_message",
    "raise_remote_error", "clean_exception",
]
