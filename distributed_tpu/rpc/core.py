"""RPC server skeleton: handler dispatch over comms.

Reference shape (core.py:285 ``Server``): a node exposes two handler maps —

- ``handlers``:        request/response ops. A comm sends
  ``{"op": name, "reply": True, **kwargs}`` and awaits one response.
- ``stream_handlers``: one-way ops arriving over long-lived batched streams
  (``handle_stream``), the scheduler<->worker and scheduler<->client event
  channels.

Plus the client side: ``rpc(addr).op_name(**kwargs)`` sugar backed by a
``ConnectionPool`` that reuses comms with limits.

Differences from the reference: asyncio-native throughout (no tornado);
handler results may be coroutines or plain values; errors are shipped back
as picklable exception payloads and re-raised remotely.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import sys
import traceback
import weakref
from collections.abc import Awaitable, Callable, Collection
from enum import Enum
from typing import Any

from distributed_tpu import config
from distributed_tpu.comm import connect, listen
from distributed_tpu.comm.core import Comm
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.protocol import Serialize
from distributed_tpu.protocol import pickle as _pickle
from distributed_tpu.tracing import FlightRecorder
from distributed_tpu.utils import funcname, time
from distributed_tpu.utils.misc import seq_name

logger = logging.getLogger("distributed_tpu.rpc")


class Status(Enum):
    """Node lifecycle (reference core.py:77)."""

    undefined = "undefined"
    created = "created"
    init = "init"
    starting = "starting"
    running = "running"
    paused = "paused"
    stopping = "stopping"
    stopped = "stopped"
    closing = "closing"
    closing_gracefully = "closing_gracefully"
    closed = "closed"
    failed = "failed"
    dont_reply = "dont_reply"


Status.lookup = {s.name: s for s in Status}  # type: ignore[attr-defined]


class AsyncTaskGroup:
    """Track background tasks for clean shutdown (reference core.py:173)."""

    def __init__(self) -> None:
        self.closed = False
        self._ongoing: set[asyncio.Task] = set()

    def call_soon(self, afunc: Callable[..., Awaitable], *args: Any, **kwargs: Any) -> None:
        if self.closed:
            return
        task = asyncio.create_task(afunc(*args, **kwargs))
        self._ongoing.add(task)
        task.add_done_callback(self._done)

    def call_later(self, delay: float, afunc: Callable[..., Awaitable], *args: Any) -> None:
        async def _later():
            await asyncio.sleep(delay)
            await afunc(*args)

        self.call_soon(_later)

    def _done(self, task: asyncio.Task) -> None:
        self._ongoing.discard(task)
        if not task.cancelled() and task.exception() is not None:
            exc = task.exception()
            if not isinstance(exc, (CommClosedError, asyncio.CancelledError)):
                logger.exception("background task failed", exc_info=exc)

    def close(self) -> None:
        self.closed = True

    async def stop(self) -> None:
        self.close()
        # never cancel the caller: close() itself often runs inside this
        # group (terminate RPC, close-worker stream op, idle-timeout)
        me = asyncio.current_task()
        pending = [t for t in self._ongoing if t is not me]
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    def __len__(self) -> int:
        return len(self._ongoing)


class PeriodicCallback:
    """asyncio periodic callback (reference compatibility.py)."""

    def __init__(self, callback: Callable, interval_s: float):
        self.callback = callback
        self.interval = interval_s
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @property
    def is_running(self) -> bool:
        return self._task is not None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                res = self.callback()
                if inspect.isawaitable(res):
                    await res
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("periodic callback %s failed", funcname(self.callback))


def error_message(e: BaseException) -> dict:
    """Picklable error payload (reference core.py error_message)."""
    tb = traceback.format_exception(type(e), e, e.__traceback__)
    max_len = config.get("admin.max-error-length")
    tb_text = "".join(tb)[-max_len:]
    try:
        pickled = _pickle.dumps(e)
        _pickle.loads(pickled)
    except Exception:
        e2 = Exception(f"{type(e).__name__}: {e}")
        pickled = _pickle.dumps(e2)
    return {
        "status": "error",
        "exception": pickled,
        "traceback-text": tb_text,
        "exception-text": repr(e),
    }


def raise_remote_error(resp: dict) -> None:
    if "exception" not in resp:
        # direct callers (client report stream, actors) may pass an
        # error-status reply that carries no pickled envelope — surface
        # a clear RPCError instead of KeyError masking the message
        from distributed_tpu.exceptions import RPCError

        raise RPCError(resp.get("error", resp.get("message", repr(resp))))
    exc = _pickle.loads(resp["exception"])
    if resp.get("traceback-text"):
        note = f"\n\nRemote traceback:\n{resp['traceback-text']}"
        try:
            exc.add_note(note)
        except AttributeError:  # pragma: no cover - py<3.11
            pass
    raise exc


class Server:
    """Handler-dispatch RPC server; base of Scheduler / Worker / Nanny."""

    default_ip = ""
    default_port = 0
    blocked_handlers_config_key = "scheduler.blocked-handlers"
    # node types with config-driven preloads (reference distributed.yaml
    # scheduler.preload / worker.preload / nanny.preload) set this to
    # their config prefix; CLI --preload flags are handled by the CLIs
    # and ADD to these
    preload_config_prefix: str | None = None

    def __init__(
        self,
        handlers: dict[str, Callable] | None = None,
        stream_handlers: dict[str, Callable] | None = None,
        connection_args: dict | None = None,
        deserialize: bool = True,
        name: str | None = None,
        timeout: float | None = None,
    ):
        self.handlers: dict[str, Callable] = {
            "identity": self.identity,
            "echo": self.echo,
            "connection_stream": self.handle_stream,
            "get_trace": self.get_trace,
        }
        if handlers:
            self.handlers.update(handlers)
        # per-node-type blocklist (reference worker.py blocked_handlers):
        # Worker/Nanny override blocked_handlers_config_key so each node
        # type is governed by its own config key.  Enforced at DISPATCH
        # (not by popping here): subclasses and extensions register
        # handlers after this __init__ runs, and those must be
        # blockable too.
        self._blocked_handlers = frozenset(
            config.get(self.blocked_handlers_config_key) or []
        )
        self.stream_handlers: dict[str, Callable] = dict(stream_handlers or {})
        # same-op runs within one batched-stream payload can be folded
        # into a single call: ``stream_batch_handlers[op](msgs, **extra)``
        # receives the whole run as a list of message dicts (op stripped).
        # Servers opt in per op; anything unregistered keeps the
        # per-message path below.
        self.stream_batch_handlers: dict[str, Callable] = {}
        self.connection_args = connection_args or {}
        self.deserialize = deserialize
        self.name = name
        self.id = f"{type(self).__name__}-{_new_uid()}"
        self.status = Status.created
        self.listeners: list = []
        self._comms: dict[Comm, str | None] = {}
        self._ongoing_background_tasks = AsyncTaskGroup()
        self.periodic_callbacks: dict[str, PeriodicCallback] = {}
        self.counters: dict[str, int] = {}
        self.digests: dict[str, float] = {}
        self.digests_tdigest: dict[str, Any] = {}
        self._startup_lock = asyncio.Lock()
        self._close_started = False
        self._event_finished = asyncio.Event()
        self.rpc = ConnectionPool(
            deserialize=deserialize,
            connection_args=self.connection_args,
            server=self,
        )
        # flight recorder (tracing.py): servers wrapping a state machine
        # (Scheduler, Worker) re-point this at their state's recorder
        # after construction so role HTTP routes and the sans-io engine
        # share one causal timeline.  The base-Server placeholder keeps
        # a tiny ring — nothing emits through it, and a full
        # default-size ring here would be ~MBs of dead preallocation
        # per Nanny/bare server
        self.trace = FlightRecorder(ring_size=256)
        self._start_time = time()

    # ------------------------------------------------------------ handlers

    async def identity(self) -> dict:
        return {"type": type(self).__name__, "id": self.id, "name": self.name}

    async def echo(self, data: Any = None) -> Any:
        return data

    async def get_trace(self, n: int = 200) -> list[dict]:
        """Newest flight-recorder events (JSON-safe dicts): the RPC twin
        of the HTTP ``/trace`` route, used by cluster dumps so chaos
        post-mortems ship every node's causal tail by default."""
        return self.trace.tail(n)

    # ------------------------------------------------------------ lifecycle

    @property
    def address(self) -> str:
        if self.listeners:
            return self.listeners[0].contact_address
        raise ValueError(f"{self!r} not listening yet")

    @property
    def listen_address(self) -> str:
        return self.listeners[0].listen_address

    @property
    def port(self) -> int:
        from distributed_tpu.comm import get_address_host_port

        return get_address_host_port(self.address)[1]

    async def listen(self, addr: str, **kwargs: Any) -> None:
        listener = listen(addr, self._handle_comm, deserialize=self.deserialize, **kwargs)
        await listener.start()
        self.listeners.append(listener)

    async def start_unsafe(self) -> "Server":
        return self

    async def start(self) -> "Server":
        async with self._startup_lock:
            if self.status == Status.running:
                return self
            if self.status == Status.failed:
                raise RuntimeError(f"{self!r} previously failed to start")
            self.status = Status.starting
            try:
                await self.start_unsafe()
                await self._start_config_preloads()
            except Exception:
                self.status = Status.failed
                await self.close()
                raise
            self.status = Status.running
        return self

    async def _start_config_preloads(self) -> None:
        if getattr(self, "_config_preloads_started", False):
            return  # a subclass ran them at its preferred point
        self._config_preloads_started = True
        self._config_preloads: list = []
        if not self.preload_config_prefix:
            return
        from distributed_tpu.preloading import process_preloads

        specs = config.get(f"{self.preload_config_prefix}.preload", None)
        argv = config.get(f"{self.preload_config_prefix}.preload-argv", None)
        self._config_preloads = process_preloads(self, specs, argv or None)
        for preload in self._config_preloads:
            await preload.start()

    async def _teardown_config_preloads(self) -> None:
        """Idempotent; subclasses call this FIRST in their close() so
        dtpu_teardown hooks still see a live cluster (matching the CLI
        flag ordering); Server.close is the backstop."""
        preloads, self._config_preloads = (
            getattr(self, "_config_preloads", []), []
        )
        for preload in preloads:
            try:
                await preload.teardown()
            except Exception:
                logger.exception("preload teardown failed")

    def start_periodic_callbacks(self) -> None:
        for pc in self.periodic_callbacks.values():
            if not pc.is_running:
                pc.start()

    async def finished(self) -> None:
        await self._event_finished.wait()

    async def close(self, timeout: float | None = None) -> None:
        # guarded by a flag, not status: subclasses set status=closing and
        # then delegate here, which must still run exactly once
        if self._close_started:
            await self._event_finished.wait()
            return
        self._close_started = True
        self.status = Status.closing
        await self._teardown_config_preloads()
        for pc in self.periodic_callbacks.values():
            pc.stop()
        self.periodic_callbacks.clear()
        for listener in self.listeners:
            listener.stop()
        for comm in list(self._comms):
            try:
                comm.abort()
            except Exception:
                pass
        await self._ongoing_background_tasks.stop()
        await self.rpc.close()
        self.status = Status.closed
        self._event_finished.set()

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, *exc):
        await self.close()

    # ----------------------------------------------------------- comm loop

    async def _handle_comm(self, comm: Comm) -> None:
        """Serve request/response ops on one comm until it closes
        (reference core.py:876)."""
        self._comms[comm] = None
        try:
            while not self.status.name.startswith("clos"):
                try:
                    msg = await comm.read()
                except CommClosedError:
                    break
                if not isinstance(msg, dict) or "op" not in msg:
                    await comm.write(error_message(
                        TypeError(f"bad message {type(msg)}: needs dict with 'op'")))
                    continue
                op = msg.pop("op")
                reply = msg.pop("reply", True)
                serializers = msg.pop("serializers", None)  # noqa: F841 - compat
                self.counters[op] = self.counters.get(op, 0) + 1
                handler = (
                    None if op in self._blocked_handlers
                    else self.handlers.get(op)
                )
                if handler is None:
                    result: Any = error_message(ValueError(
                        f"unknown operation {op!r} on {type(self).__name__}"))
                else:
                    try:
                        if _wants_comm(handler):
                            # comm handlers that write their own reply
                            # (get_data) must see the reply flag or a
                            # reply=False caller gets an unsolicited
                            # write that desyncs the pooled comm
                            if _wants_reply_flag(handler):
                                result = handler(comm, reply=reply, **msg)
                            else:
                                result = handler(comm, **msg)
                        else:
                            result = handler(**msg)
                        if inspect.isawaitable(result):
                            result = await result
                    except CommClosedError:
                        break
                    except Exception as e:
                        logger.debug("handler %s raised", op, exc_info=True)
                        result = error_message(e)
                if result is Status.dont_reply:
                    continue
                if reply:
                    try:
                        await comm.write(result)
                    except (CommClosedError, TypeError):
                        break
                if op == "connection_stream":
                    # handle_stream took over the comm and has returned:
                    # nothing more to serve
                    break
        finally:
            self._comms.pop(comm, None)
            if not comm.closed:
                await comm.close()

    async def handle_stream(self, comm: Comm, extra: dict | None = None) -> None:
        """Serve one-way batched-stream ops (reference core.py:1015)."""
        extra = extra or {}
        closed = False
        try:
            while not closed:
                msgs = await comm.read()
                if not isinstance(msgs, (tuple, list)):
                    msgs = (msgs,)
                i, n = 0, len(msgs)
                while i < n:
                    msg = msgs[i]
                    if msg == "OK":  # initial handshake ack
                        i += 1
                        continue
                    op = msg.pop("op", None)
                    if op is None:
                        raise ValueError(f"stream message without op: {msg!r}")
                    if op == "close-stream":
                        closed = True
                        break
                    batch_handler = self.stream_batch_handlers.get(op)
                    if batch_handler is not None:
                        # fold the whole consecutive same-op run (a
                        # task-finished flood, a free/release flood) into
                        # ONE dispatch: the handler sees the run as a
                        # list and drives the state machine in a single
                        # batched pass instead of once per message
                        j = i + 1
                        while (
                            j < n
                            and isinstance(msgs[j], dict)
                            and msgs[j].get("op") == op
                        ):
                            msgs[j].pop("op", None)
                            j += 1
                        batch = list(msgs[i:j])
                        i = j
                        # causal stimulus ids are minted AT INGRESS: any
                        # message folding into a batched engine pass
                        # without one (client-plane floods; worker
                        # messages always carry theirs) gets a fresh id
                        # here, so the flight recorder can join the
                        # inbound flood to the engine pass, the
                        # transitions it produced, and the envelopes
                        # those emitted (docs/observability.md)
                        for m in batch:
                            if not m.get("stimulus_id"):
                                m["stimulus_id"] = seq_name(f"igr-{op}")
                        try:
                            result = batch_handler(batch, **extra)
                            if result is not None and inspect.isawaitable(result):
                                await result
                        except Exception:
                            logger.exception(
                                "stream batch handler %r failed", op
                            )
                        continue
                    i += 1
                    handler = self.stream_handlers.get(op)
                    if handler is None:
                        logger.error("unknown stream op %r", op)
                        continue
                    try:
                        # stream context (worker=/client= address) fills in
                        # unless the message already carries the field
                        result = handler(**{**extra, **msg}) if extra else handler(**msg)
                        if result is not None and inspect.isawaitable(result):
                            await result
                    except Exception:
                        logger.exception("stream handler %r failed", op)
                # payload boundary: servers that coalesce stream stimuli
                # (the worker's event buffer) flush here, SYNCHRONOUSLY,
                # so a whole batched payload becomes one state-machine
                # batch and no locally-generated event can interleave
                flush = getattr(self, "stream_payload_flush", None)
                if flush is not None:
                    try:
                        flush()
                    except Exception:
                        logger.exception("stream payload flush failed")
        except CommClosedError:
            pass
        finally:
            await comm.close()

    # ------------------------------------------------------------- helpers

    def digest_metric(self, name: str, value: float) -> None:
        """Cumulative total + streaming quantile sketch per metric
        (reference core.py:1088; sketch = native t-digest, counter.py:40)."""
        self.digests[name] = self.digests.get(name, 0.0) + value
        digest = self.digests_tdigest.get(name)
        if digest is None:
            from distributed_tpu.utils.counter import Digest

            digest = self.digests_tdigest[name] = Digest()
        digest.add(value)

    def __repr__(self) -> str:
        try:
            addr = self.address
        except ValueError:
            addr = "not-listening"
        return f"<{type(self).__name__} {addr!r} {self.status.name}>"


def _wants_reply_flag(handler: Callable) -> bool:
    cached = getattr(handler, "_wants_reply_flag", None)
    if cached is None:
        try:
            params = inspect.signature(handler).parameters
        except (TypeError, ValueError):
            params = {}
        cached = "reply" in params
        try:
            handler.__dict__["_wants_reply_flag"] = cached
        except AttributeError:
            pass
    return cached


def _wants_comm(handler: Callable) -> bool:
    cached = getattr(handler, "_wants_comm", None)
    if cached is None:
        try:
            params = list(inspect.signature(handler).parameters)
        except (TypeError, ValueError):
            params = []
        cached = bool(params) and params[0] == "comm"
        try:
            handler.__dict__["_wants_comm"] = cached
        except AttributeError:
            pass
    return cached


_uid_counter = 0


def _new_uid() -> str:
    global _uid_counter
    _uid_counter += 1
    import uuid

    return f"{uuid.uuid4().hex[:8]}-{_uid_counter}"


# ---------------------------------------------------------------------------
# Client-side RPC
# ---------------------------------------------------------------------------


class RPCCall:
    """``rpc_obj.op_name(**kwargs)`` -> send {"op": "op_name", ...}, await reply."""

    def __getattr__(self, op: str):
        async def send_recv_op(**kwargs: Any):
            return await self.send_recv(op=op, **kwargs)

        return send_recv_op


async def run_user_function(server: Any, inject_kw: str, function: Any = None,
                            args: Any = None, kwargs: Any = None,
                            wait: bool = True) -> Any:
    """Shared body of the run-arbitrary-function handlers on scheduler,
    worker, and nanny (reference run handlers): unwrap, optionally inject
    the hosting server under ``inject_kw``, await coroutines, wrap errors."""
    import inspect

    from distributed_tpu.protocol.serialize import Serialize, unwrap

    fn = unwrap(function)
    a = unwrap(args) or ()
    kw = unwrap(kwargs) or {}
    try:
        if inject_kw in inspect.signature(fn).parameters:
            kw[inject_kw] = server
        result = fn(*a, **kw)
        if asyncio.iscoroutine(result):
            if wait:
                result = await result
            else:
                server._ongoing_background_tasks.call_soon(lambda: result)
                result = None
        return {"status": "OK", "result": Serialize(result)}
    except Exception as e:
        return error_message(e)


async def send_recv(comm: Comm, *, op: str, reply: bool = True, **kwargs: Any) -> Any:
    await comm.write({"op": op, "reply": reply, **kwargs})
    if not reply:
        return None
    resp = await comm.read()
    # only replies carrying a pickled exception are error ENVELOPES;
    # handlers may use status "error" as structured protocol data (e.g.
    # Scheduler.gather's missing-keys reply, which the client handles)
    if (
        isinstance(resp, dict)
        and resp.get("status") in ("error", "uncaught-error")
        and "exception" in resp
    ):
        raise_remote_error(resp)
    return resp


class rpc(RPCCall):
    """Dedicated (non-pooled) RPC proxy to one address; opens comms on
    demand and reuses idle ones (reference core.py:1201)."""

    def __init__(self, address: str, deserialize: bool = True,
                 connection_args: dict | None = None, timeout: float | None = None):
        self.address = address
        self.deserialize = deserialize
        self.connection_args = connection_args or {}
        self.timeout = timeout
        self.comms: dict[Comm, bool] = {}  # comm -> in_use
        self.status = Status.running

    async def live_comm(self) -> Comm:
        for comm, in_use in list(self.comms.items()):
            if comm.closed:
                del self.comms[comm]
            elif not in_use:
                self.comms[comm] = True
                return comm
        comm = await connect(self.address, timeout=self.timeout,
                             deserialize=self.deserialize, **self.connection_args)
        self.comms[comm] = True
        return comm

    async def send_recv(self, **kwargs: Any) -> Any:
        if self.status == Status.closed:
            raise RuntimeError(f"rpc to {self.address} is closed")
        comm = await self.live_comm()
        try:
            result = await send_recv(comm, **kwargs)
        except (CommClosedError, asyncio.CancelledError):
            self.comms.pop(comm, None)
            raise
        self.comms[comm] = False
        return result

    async def close_rpc(self) -> None:
        self.status = Status.closed
        for comm in list(self.comms):
            try:
                await comm.close()
            except Exception:
                pass
        self.comms.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        asyncio.ensure_future(self.close_rpc())

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close_rpc()

    def __repr__(self) -> str:
        return f"<rpc to {self.address!r}, {len(self.comms)} comms>"


class PooledRPCCall(RPCCall):
    """RPC proxy borrowing comms from a ConnectionPool (reference core.py:1369)."""

    def __init__(self, address: str, pool: "ConnectionPool", serializers=None):
        self.address = address
        self.pool = pool

    async def send_recv(self, **kwargs: Any) -> Any:
        comm = await self.pool.connect(self.address)
        prev_name, comm.name = comm.name, "rpc"
        try:
            result = await send_recv(comm, **kwargs)
        except BaseException:
            # cancellation or failure mid-request: the reply (if it ever
            # comes) is still in flight — returning this comm to the
            # pool would hand the NEXT caller a stale response and
            # desynchronize every later RPC on it.  Abort instead.
            comm.abort()
            self.pool.reuse(self.address, comm)  # pool discards closed comms
            comm.name = prev_name
            raise
        self.pool.reuse(self.address, comm)
        comm.name = prev_name
        return result

    def __repr__(self) -> str:
        return f"<pooled rpc to {self.address!r}>"


class ConnectionPool:
    """Comm pool with per-address reuse and a global open-connection limit
    (reference core.py ConnectionPool)."""

    def __init__(self, limit: int = 512, deserialize: bool = True,
                 connection_args: dict | None = None, timeout: float | None = None,
                 server: Server | None = None):
        self.limit = limit
        self.deserialize = deserialize
        self.connection_args = connection_args or {}
        self.timeout = timeout
        self.server = weakref.ref(server) if server else None
        self.available: dict[str, set[Comm]] = {}
        self.occupied: dict[str, set[Comm]] = {}
        self.semaphore = asyncio.Semaphore(limit)
        self._created: weakref.WeakSet = weakref.WeakSet()
        self.status = Status.init

    def __call__(self, address: str) -> PooledRPCCall:
        return PooledRPCCall(address, self)

    @property
    def active(self) -> int:
        return sum(map(len, self.occupied.values()))

    @property
    def open(self) -> int:
        return self.active + sum(map(len, self.available.values()))

    async def connect(self, address: str) -> Comm:
        if self.status == Status.closed:
            raise RuntimeError("ConnectionPool is closed")
        avail = self.available.setdefault(address, set())
        occ = self.occupied.setdefault(address, set())
        while avail:
            comm = avail.pop()
            if comm.closed:
                self.semaphore.release()
                continue
            occ.add(comm)
            return comm
        if self.semaphore.locked():
            self.collect()
        await self.semaphore.acquire()
        try:
            comm = await connect(address, timeout=self.timeout,
                                 deserialize=self.deserialize, **self.connection_args)
            comm.name = "ConnectionPool"
            self._created.add(comm)
        except BaseException:
            self.semaphore.release()
            raise
        occ.add(comm)
        return comm

    def reuse(self, address: str, comm: Comm) -> None:
        occ = self.occupied.get(address, set())
        occ.discard(comm)
        if comm.closed:
            self.semaphore.release()
        else:
            self.available.setdefault(address, set()).add(comm)

    def collect(self) -> None:
        """Drop idle comms to free slots."""
        for address, comms in list(self.available.items()):
            for comm in comms:
                comm.abort()
                self.semaphore.release()
            comms.clear()

    async def remove(self, address: str) -> None:
        for comm in self.available.pop(address, set()):
            comm.abort()
            self.semaphore.release()
        for comm in self.occupied.pop(address, set()):
            comm.abort()
            self.semaphore.release()

    async def close(self) -> None:
        self.status = Status.closed
        for d in (self.available, self.occupied):
            for comms in d.values():
                for comm in comms:
                    comm.abort()
            d.clear()


def clean_exception(exception, traceback_text: str = "") -> tuple:
    """Normalize an error payload into (type, exception, traceback_text)."""
    if isinstance(exception, (bytes, bytearray)):
        exception = _pickle.loads(exception)
    return type(exception), exception, traceback_text
