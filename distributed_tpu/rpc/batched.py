"""BatchedSend: coalesce many tiny messages onto one comm.

Reference batched.py:20 — the scheduler<->worker and scheduler<->client
event streams each push hundreds of tiny dicts per second; sending each in
its own write would syscall-storm.  ``send()`` appends to a buffer; a
background loop flushes the whole buffer as one list the moment it wakes.
Coalescing comes from messages accumulating while the previous
``comm.write`` awaits — there is deliberately NO timed window: any sleep
here sits inside every scheduler<->worker round trip.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Any

from distributed_tpu.comm.core import Comm
from distributed_tpu.exceptions import CommClosedError

logger = logging.getLogger("distributed_tpu.rpc")


class BatchedSend:
    def __init__(self):
        self.buffer: deque = deque()
        self.comm: Comm | None = None
        self.please_stop = False
        self.waker = asyncio.Event()
        self.stopped = asyncio.Event()
        self.stopped.set()
        self._background_task: asyncio.Task | None = None
        self.byte_count = 0
        self.batch_count = 0

    def start(self, comm: Comm) -> None:
        if self._background_task is not None and not self._background_task.done():
            raise RuntimeError("BatchedSend already running")
        self.comm = comm
        self.please_stop = False
        self.stopped.clear()
        self.waker.set()
        self._background_task = asyncio.create_task(self._background_send())

    def closed(self) -> bool:
        return self.comm is None or self.comm.closed

    def send(self, *msgs: Any) -> None:
        """Enqueue; raises if the stream was closed."""
        if self.comm is not None and self.comm.closed:
            raise CommClosedError(f"comm {self.comm!r} already closed")
        self.buffer.extend(msgs)
        self.waker.set()

    async def _background_send(self) -> None:
        # idle streams block on the waker with NO timer (the pre-r4
        # wait_for(..., interval) tick created a Task + timeout context +
        # heap timer per stream per 2 ms even with nothing to send), and
        # a ready message flushes IMMEDIATELY — any sleep in this loop
        # (before or after the flush) inserts its full length into every
        # scheduler<->worker request-response round trip and stalls the
        # whole pipeline (measured: a trailing interval-sleep cost
        # +66 us/task; a leading one +400).  Coalescing still happens:
        # messages arriving while comm.write awaits accumulate in the
        # buffer and go out as one list on the next iteration.
        try:
            while not self.please_stop:
                await self.waker.wait()
                self.waker.clear()
                if not self.buffer:
                    if self.please_stop:
                        break
                    continue
                payload, self.buffer = list(self.buffer), deque()
                try:
                    nbytes = await self.comm.write(payload)
                    self.byte_count += nbytes
                    self.batch_count += 1
                except CommClosedError:
                    # retain the payload for a possible restart on a new comm
                    payload.extend(self.buffer)
                    self.buffer = deque(payload)
                    break
        finally:
            self.stopped.set()

    async def close(self, timeout: float | None = None) -> None:
        """Flush and close the comm."""
        self.please_stop = True
        self.waker.set()
        if self._background_task is not None:
            try:
                await asyncio.wait_for(self.stopped.wait(), timeout)
            except asyncio.TimeoutError:
                self._background_task.cancel()
        if self.comm is not None and not self.comm.closed:
            try:
                if self.buffer:
                    payload, self.buffer = list(self.buffer), deque()
                    await self.comm.write(payload)
            except CommClosedError:
                pass
            await self.comm.close()

    def abort(self) -> None:
        self.please_stop = True
        self.buffer.clear()
        self.waker.set()
        if self.comm is not None and not self.comm.closed:
            self.comm.abort()

    def __repr__(self) -> str:
        n = len(self.buffer)
        state = "closed" if self.closed() else "open"
        return f"<BatchedSend {state}: {n} buffered>"
