"""SSH cluster: scheduler and workers launched on remote hosts over ssh.

Fills the reference's ``deploy/ssh.py`` role.  Where the reference drives
asyncssh connections, we drive the system ``ssh`` binary (zero extra
dependencies; respects the operator's ~/.ssh config, agents, jump hosts),
reusing the `ProcessHandle` machinery from deploy/subprocess.py — an ssh
launch is just a subprocess whose argv is ``ssh <host> '<remote cmd>'``.

Assumptions (same as the reference): ``distributed_tpu`` is importable by
``remote_python`` on every host, and hosts can reach each other's TCP
ports.  The first host runs the scheduler, the rest run workers
(reference deploy/ssh.py:380 ``SSHCluster(["host1", "host2", ...])``).

``connect_command`` is injectable so tests can substitute a local shell
for a real ssh client and still exercise the full command-construction
and address-discovery path.
"""

from __future__ import annotations

import logging
import shlex
import sys
from typing import Any, Sequence

from distributed_tpu.deploy.spec import SpecCluster
from distributed_tpu.deploy.subprocess import (
    ProcessHandle,
    SubprocessScheduler,
)

logger = logging.getLogger("distributed_tpu.deploy")


class SSHProcess(ProcessHandle):
    """A node on a remote host, launched as ``ssh <host> '<command>'``."""

    def __init__(
        self,
        host: str,
        connect_command: Sequence[str] = ("ssh",),
        remote_python: str = sys.executable,
        env_vars: dict[str, str] | None = None,
    ) -> None:
        super().__init__()
        self.host = host
        self.connect_command = list(connect_command)
        self.remote_python = remote_python
        self.env_vars = dict(env_vars or {})

    def _remote_argv(self) -> list[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def _argv(self) -> list[str]:
        exports = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in self.env_vars.items()
        )
        cmd = " ".join(shlex.quote(p) for p in self._remote_argv())
        remote = f"{exports} {cmd}" if exports else cmd
        return [*self.connect_command, self.host, remote]


class SSHScheduler(SSHProcess):
    """Scheduler on ``host``, bound to all interfaces, advertised as
    ``tcp://<host>:<port>`` so workers on other machines can reach it."""

    marker = "Scheduler at:"

    def __init__(
        self,
        host: str,
        port: int = 0,
        bind_host: str = "0.0.0.0",
        contact_host: str | None = None,
        extra_args: Sequence[str] = (),
        **ssh_kwargs: Any,
    ) -> None:
        super().__init__(host, **ssh_kwargs)
        self.port = port
        self.bind_host = bind_host
        # the name workers dial: the ssh destination minus any user@
        # prefix by default; pass contact_host when the ssh destination
        # is a ~/.ssh/config alias other machines cannot resolve
        self.contact_host = contact_host or host.rpartition("@")[2]
        self.extra_args = list(extra_args)

    def _remote_argv(self) -> list[str]:
        return [
            self.remote_python, "-m", "distributed_tpu.cli.scheduler",
            "--host", self.bind_host,
            "--port", str(self.port),
            *self.extra_args,
        ]

    async def start(self, timeout: float = 60.0) -> "SSHScheduler":
        await super().start(timeout)
        # the remote printed its BIND address (e.g. tcp://0.0.0.0:p);
        # rewrite to the address peers should dial
        assert self.address is not None
        proto, _, rest = self.address.partition("://")
        port = rest.rsplit(":", 1)[-1]
        self.address = f"{proto}://{self.contact_host}:{port}"
        return self

    # SpecCluster._correct_state retires through the scheduler handle
    retire_workers = SubprocessScheduler.retire_workers


class SSHWorker(SSHProcess):
    """Worker on ``host`` dialing a remote scheduler."""

    marker = "Worker at:"

    def __init__(
        self,
        scheduler_address: str,
        host: str = "",
        name: object = None,
        nthreads: int = 1,
        nanny: bool = False,
        memory_limit: str | int = "0",
        bind_host: str = "auto",
        extra_args: Sequence[str] = (),
        **ssh_kwargs: Any,
    ) -> None:
        super().__init__(host, **ssh_kwargs)
        self.scheduler_address = scheduler_address
        self.name = name
        self.nthreads = nthreads
        self.nanny = nanny
        self.memory_limit = memory_limit
        self.bind_host = bind_host
        self.extra_args = list(extra_args)

    @property
    def worker_address(self) -> str | None:
        return self.address

    def _remote_argv(self) -> list[str]:
        argv = [
            self.remote_python, "-m", "distributed_tpu.cli.worker",
            self.scheduler_address,
            "--nthreads", str(self.nthreads),
            "--memory-limit", str(self.memory_limit),
            # bind a cross-host-reachable interface, not loopback.  The
            # default "auto" binds whatever interface routes to the
            # scheduler — correct even when the ssh destination is a
            # ~/.ssh/config alias the worker machine itself can't resolve
            "--host", self.bind_host,
        ]
        if self.name is not None:
            argv += ["--name", str(self.name)]
        if self.nanny:
            argv += ["--nanny"]
        argv += self.extra_args
        return argv


class SSHCluster(SpecCluster):
    """Cluster over ssh: ``hosts[0]`` runs the scheduler, ``hosts[1:]``
    run one worker each (reference deploy/ssh.py:380).

    ``SSHCluster(["gateway", "node1", "node2"])`` brings up a 2-worker
    cluster; ``scale(n)`` round-robins new workers over the worker hosts.
    """

    def __init__(
        self,
        hosts: Sequence[str],
        connect_command: Sequence[str] = ("ssh",),
        remote_python: str = sys.executable,
        env_vars: dict[str, str] | None = None,
        nthreads: int = 1,
        nanny: bool = False,
        memory_limit: str | int = "0",
        scheduler_options: dict | None = None,
        worker_options: dict | None = None,
        adaptive: Any | None = None,
    ) -> None:
        if len(hosts) < 2:
            raise ValueError(
                "SSHCluster needs >= 2 hosts: [scheduler, worker, ...]"
            )
        self.hosts = list(hosts)
        ssh_kwargs = {
            "connect_command": list(connect_command),
            "remote_python": remote_python,
            "env_vars": dict(env_vars or {}),
        }
        self._ssh_kwargs = ssh_kwargs
        worker_hosts = self.hosts[1:]
        base_worker = {
            "nthreads": nthreads,
            "nanny": nanny,
            "memory_limit": memory_limit,
            **(worker_options or {}),
            **ssh_kwargs,
        }
        workers = {
            f"{host}-{i}": {
                "cls": SSHWorker,
                "options": {**base_worker, "host": host},
            }
            for i, host in enumerate(worker_hosts)
        }
        # template for scale(): round-robin over worker hosts
        self._worker_hosts = worker_hosts
        super().__init__(
            workers=workers,
            scheduler={
                "cls": SSHScheduler,
                "options": {
                    "host": self.hosts[0],
                    **(scheduler_options or {}),
                    **ssh_kwargs,
                },
            },
            worker={"cls": SSHWorker, "options": dict(base_worker)},
            adaptive=adaptive,
        )

    async def scale(self, n: int) -> None:
        """Grow/shrink like SpecCluster.scale, pinning each new spec to a
        concrete host (round-robin over the worker hosts)."""
        while len(self.worker_spec) > n:
            self.worker_spec.popitem()
        while len(self.worker_spec) < n:
            name = self._new_worker_name()
            host = self._worker_hosts[self._i % len(self._worker_hosts)]
            self.worker_spec[name] = {
                "cls": SSHWorker,
                "options": {**self.new_spec["options"], "host": host},
            }
        await self._correct_state()
