from distributed_tpu.deploy.local import LocalCluster
from distributed_tpu.deploy.spec import Adaptive, Cluster, SpecCluster
from distributed_tpu.deploy.ssh import SSHCluster
from distributed_tpu.deploy.subprocess import (
    SubprocessCluster,
    SubprocessScheduler,
    SubprocessWorker,
)

__all__ = [
    "Adaptive",
    "Cluster",
    "LocalCluster",
    "SSHCluster",
    "SpecCluster",
    "SubprocessCluster",
    "SubprocessWorker",
    "SubprocessScheduler",
]
