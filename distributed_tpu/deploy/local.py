"""LocalCluster: scheduler + workers in one process.

Equivalent of the reference's ``LocalCluster(processes=False)``
(deploy/local.py:23): the scheduler and every worker are Server objects
sharing one event loop, talking over ``inproc://`` comms — the workhorse
for tests and single-host use.  Multi-process workers arrive with the
Nanny (deploy/spec.py equivalent).
"""

from __future__ import annotations

import logging
from typing import Any

from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler
from distributed_tpu.worker.server import Worker

logger = logging.getLogger("distributed_tpu.deploy")


class LocalCluster:
    """In-process cluster (reference deploy/local.py:23)."""

    def __init__(
        self,
        n_workers: int = 2,
        threads_per_worker: int = 1,
        *,
        protocol: str = "inproc",
        security: Any | None = None,
        scheduler_kwargs: dict | None = None,
        worker_kwargs: dict | None = None,
    ):
        self.n_workers = n_workers
        self.threads_per_worker = threads_per_worker
        self.protocol = protocol
        self.security = security
        if protocol == "inproc":
            listen_addr = "inproc://"
        else:
            listen_addr = f"{protocol}://127.0.0.1:0"
        scheduler_kwargs = dict(scheduler_kwargs or {})
        if security is not None:
            scheduler_kwargs.setdefault("security", security)
        self.scheduler = Scheduler(
            listen_addr=listen_addr, **scheduler_kwargs
        )
        self._worker_kwargs = dict(worker_kwargs or {})
        if security is not None:
            self._worker_kwargs.setdefault("security", security)
        self.workers: list[Worker] = []
        self._started = False

    @property
    def scheduler_address(self) -> str:
        return self.scheduler.address

    async def _start(self) -> "LocalCluster":
        if self._started:
            return self
        await self.scheduler.start()
        for i in range(self.n_workers):
            await self.add_worker(name=f"worker-{i}")
        self._started = True
        return self

    async def add_worker(self, name: str | None = None, **kwargs: Any) -> Worker:
        kw = {**self._worker_kwargs, **kwargs}
        kw.setdefault("nthreads", self.threads_per_worker)
        if self.protocol == "inproc":
            kw.setdefault("listen_addr", "inproc://")
        elif self.protocol != "tcp":
            kw.setdefault("listen_addr", f"{self.protocol}://127.0.0.1:0")
        worker = Worker(self.scheduler.address, name=name, **kw)
        await worker.start()
        self.workers.append(worker)
        return worker

    async def scale(self, n: int) -> None:
        """Grow or shrink to ``n`` workers."""
        while len(self.workers) < n:
            await self.add_worker(name=f"worker-{len(self.workers)}")
        if len(self.workers) > n:
            victims = self.workers[n:]
            self.workers = self.workers[:n]
            await self.scheduler.retire_workers(
                workers=[w.address for w in victims]
            )
            for w in victims:
                await w.finished()

    def get_client(self) -> Client:
        return Client(self.scheduler.address, security=self.security)

    async def close(self) -> None:
        # flag shutdown BEFORE workers leave: per-departure recovery
        # (shuffle epoch restarts) is noise once the whole cluster is
        # going away.  A dedicated flag, NOT status=closing — flipping
        # status would stop the comm loop from serving in-flight client
        # RPCs during the drain window
        self.scheduler.draining = True
        for worker in self.workers:
            await worker.close()
        self.workers.clear()
        await self.scheduler.close()

    async def __aenter__(self) -> "LocalCluster":
        return await self._start()

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    def __repr__(self) -> str:
        return (
            f"<LocalCluster {len(self.workers)} workers, "
            f"scheduler={self.scheduler!r}>"
        )

    def _repr_html_(self) -> str:
        """Notebook widget (reference jinja2 ``widgets/`` role)."""
        threads = sum(
            getattr(w, "nthreads", 1) for w in self.workers
        )
        dash = getattr(self.scheduler, "dashboard_address", None)
        link = (
            f'<tr><th style="text-align:left">Dashboard</th>'
            f'<td><a href="{dash}">{dash}</a></td></tr>' if dash else ""
        )
        return (
            "<h4 style='margin-bottom:0'>LocalCluster</h4><table>"
            f"<tr><th style='text-align:left'>Scheduler</th>"
            f"<td><tt>{self.scheduler_address}</tt></td></tr>"
            f"<tr><th style='text-align:left'>Workers</th>"
            f"<td>{len(self.workers)}</td></tr>"
            f"<tr><th style='text-align:left'>Threads</th>"
            f"<td>{threads}</td></tr>{link}</table>"
        )
