"""Subprocess-backed clusters: scheduler and workers as real OS processes.

Fills the reference's ``deploy/subprocess.py`` role (SubprocessCluster):
every node is a separate Python process started through the ``dtpu-*``
CLI entry points, so the cluster exercises the same code path as a
production deployment (process isolation, TCP transport, signal-driven
shutdown) while remaining a one-liner to start locally.

Design: rather than re-implementing reconciliation, the process handles
(`SubprocessScheduler` / `SubprocessWorker`) satisfy the same small
start/close/address protocol that `SpecCluster` (deploy/spec.py) drives
for in-process workers, so scale()/Adaptive work unchanged on top of OS
processes.  Reference parity: deploy/subprocess.py:61 (SubprocessWorker),
:115 (SubprocessScheduler), :150 (SubprocessCluster).
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
from typing import Any, Sequence

from distributed_tpu.deploy.spec import SpecCluster
from distributed_tpu.rpc.core import rpc

logger = logging.getLogger("distributed_tpu.deploy")

_START_TIMEOUT = 60.0


def child_env(extra: dict[str, str] | None = None) -> dict[str, str]:
    """Environment for spawned nodes: repo importable, same backend."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    path = env.get("PYTHONPATH", "")
    if repo not in path.split(os.pathsep):
        env["PYTHONPATH"] = repo + (os.pathsep + path if path else "")
    if extra:
        env.update(extra)
    return env


class ProcessHandle:
    """A node living in a child process, started via a CLI entry point.

    Subclasses provide ``_argv()`` and a ``marker`` line prefix; ``start``
    spawns the process and scans merged stdout/stderr until the marker
    reveals the node's listen address (the CLIs print ``Scheduler at:`` /
    ``Worker at:`` exactly for this).
    """

    marker: str = ""

    def __init__(self, extra_env: dict[str, str] | None = None) -> None:
        self.process: asyncio.subprocess.Process | None = None
        self.address: str | None = None
        self.extra_env = dict(extra_env or {})
        self._drain_task: asyncio.Task | None = None

    def _argv(self) -> list[str]:  # pragma: no cover - interface
        raise NotImplementedError

    def _env(self) -> dict[str, str]:
        return child_env(self.extra_env)

    async def start(self, timeout: float = _START_TIMEOUT) -> "ProcessHandle":
        self.process = await asyncio.create_subprocess_exec(
            *self._argv(),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            env=self._env(),
        )
        try:
            self.address = await asyncio.wait_for(
                self._scan_for_marker(), timeout
            )
        except BaseException:
            # a failed start must not orphan the child (it would hold its
            # port forever); __aexit__ never runs for a failed __aenter__
            await self.close()
            raise
        self._drain_task = asyncio.create_task(self._drain())
        return self

    async def _scan_for_marker(self) -> str:
        assert self.process is not None and self.process.stdout is not None
        while True:
            raw = await self.process.stdout.readline()
            if not raw:
                rc = await self.process.wait()
                raise RuntimeError(
                    f"{type(self).__name__} exited rc={rc} before "
                    f"printing {self.marker!r}"
                )
            line = raw.decode(errors="replace").rstrip()
            logger.debug("%s: %s", type(self).__name__, line)
            if line.startswith(self.marker):
                return line.split()[-1]

    async def _drain(self) -> None:
        assert self.process is not None and self.process.stdout is not None
        while True:
            raw = await self.process.stdout.readline()
            if not raw:
                return
            logger.debug(
                "%s: %s", type(self).__name__,
                raw.decode(errors="replace").rstrip(),
            )

    async def finished(self) -> None:
        assert self.process is not None
        await self.process.wait()

    async def close(self, timeout: float = 10.0) -> None:
        proc = self.process
        if proc is None:
            return
        if proc.returncode is None:
            try:
                proc.terminate()
            except ProcessLookupError:
                pass
            try:
                await asyncio.wait_for(proc.wait(), timeout)
            except asyncio.TimeoutError:
                logger.warning(
                    "%s did not exit on SIGTERM; killing", type(self).__name__
                )
                try:
                    proc.kill()
                except ProcessLookupError:
                    pass
                await proc.wait()
        if self._drain_task is not None:
            await self._drain_task
            self._drain_task = None
        # release the pipe transport now: left to GC it may outlive the
        # event loop and warn "Event loop is closed" at interpreter exit
        transport = getattr(proc, "_transport", None)
        if transport is not None:
            transport.close()


class SubprocessScheduler(ProcessHandle):
    """Scheduler in a child process (reference deploy/subprocess.py:115)."""

    marker = "Scheduler at:"

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        protocol: str = "tcp",
        extra_args: Sequence[str] = (),
        extra_env: dict[str, str] | None = None,
    ) -> None:
        super().__init__(extra_env)
        self.host = host
        self.port = port
        self.protocol = protocol
        self.extra_args = list(extra_args)

    def _argv(self) -> list[str]:
        return [
            sys.executable, "-m", "distributed_tpu.cli.scheduler",
            "--host", self.host,
            "--port", str(self.port),
            "--protocol", self.protocol,
            *self.extra_args,
        ]

    async def retire_workers(
        self, workers: list[str] | None = None, **kwargs: Any
    ) -> Any:
        """RPC shim so SpecCluster._correct_state can retire through us."""
        async with rpc(self.address) as r:
            return await r.retire_workers(workers=workers, **kwargs)


class SubprocessWorker(ProcessHandle):
    """Worker (optionally under a nanny) in a child process
    (reference deploy/subprocess.py:61)."""

    marker = "Worker at:"

    def __init__(
        self,
        scheduler_address: str,
        name: object = None,
        nthreads: int = 1,
        nanny: bool = False,
        memory_limit: str | int = "0",
        extra_args: Sequence[str] = (),
        extra_env: dict[str, str] | None = None,
    ) -> None:
        super().__init__(extra_env)
        self.scheduler_address = scheduler_address
        self.name = name
        self.nthreads = nthreads
        self.nanny = nanny
        self.memory_limit = memory_limit
        self.extra_args = list(extra_args)

    @property
    def worker_address(self) -> str | None:
        return self.address

    def _argv(self) -> list[str]:
        argv = [
            sys.executable, "-m", "distributed_tpu.cli.worker",
            self.scheduler_address,
            "--nthreads", str(self.nthreads),
            "--memory-limit", str(self.memory_limit),
        ]
        if self.name is not None:
            argv += ["--name", str(self.name)]
        if self.nanny:
            argv += ["--nanny"]
        argv += self.extra_args
        return argv


class SubprocessCluster(SpecCluster):
    """Local cluster of OS processes (reference deploy/subprocess.py:150).

    ``async with SubprocessCluster(n_workers=2) as cluster`` gives a
    scheduler + workers each in their own process, connected over TCP;
    ``scale``/``Adaptive`` reconcile by spawning/terminating processes.
    """

    def __init__(
        self,
        n_workers: int = 0,
        nthreads: int = 1,
        host: str = "127.0.0.1",
        scheduler_port: int = 0,
        nanny: bool = False,
        memory_limit: str | int = "0",
        worker_options: dict | None = None,
        scheduler_options: dict | None = None,
        adaptive: Any | None = None,
    ) -> None:
        worker_opts = {
            "nthreads": nthreads,
            "nanny": nanny,
            "memory_limit": memory_limit,
            **(worker_options or {}),
        }
        template = {"cls": SubprocessWorker, "options": worker_opts}
        workers = {
            f"worker-{i}": {
                "cls": SubprocessWorker,
                "options": dict(worker_opts),
            }
            for i in range(n_workers)
        }
        super().__init__(
            workers=workers,
            scheduler={
                "cls": SubprocessScheduler,
                "options": {
                    "host": host,
                    "port": scheduler_port,
                    **(scheduler_options or {}),
                },
            },
            worker=template,
            adaptive=adaptive,
        )
