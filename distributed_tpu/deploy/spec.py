"""SpecCluster: declarative cluster from worker specs (reference deploy/spec.py).

A cluster is ``{name: {"cls": WorkerClass, "options": {...}}}`` plus a
scheduler spec.  ``_correct_state`` reconciles desired vs actual workers
(reference deploy/spec.py:346); ``scale`` edits the spec and reconciles.
``Adaptive`` drives ``scale`` from the scheduler's ``adaptive_target``.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

from distributed_tpu.client.client import Client
from distributed_tpu.scheduler.server import Scheduler

logger = logging.getLogger("distributed_tpu.deploy")


class Cluster:
    """Base cluster interface (reference deploy/cluster.py:36)."""

    def __init__(self) -> None:
        self.scheduler: Scheduler | None = None

    @property
    def scheduler_address(self) -> str:
        assert self.scheduler is not None
        return self.scheduler.address

    def get_client(self) -> Client:
        return Client(self.scheduler_address)

    async def scale(self, n: int) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    async def _start(self) -> "Cluster":
        raise NotImplementedError

    async def close(self) -> None:
        raise NotImplementedError

    async def __aenter__(self) -> "Cluster":
        return await self._start()

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()


class SpecCluster(Cluster):
    """Cluster described by {name: spec} (reference deploy/spec.py:128)."""

    def __init__(
        self,
        workers: dict[str, dict] | None = None,
        scheduler: dict | None = None,
        worker: dict | None = None,
        adaptive: "Adaptive | None" = None,
    ):
        super().__init__()
        self.worker_spec: dict[str, dict] = dict(workers or {})
        self.scheduler_spec = scheduler or {"cls": Scheduler, "options": {}}
        self.new_spec = worker or {"cls": None, "options": {}}
        self.workers: dict[str, Any] = {}  # name -> live Worker/Nanny
        self._i = 0
        self._adaptive = adaptive
        self._lock = asyncio.Lock()
        self._started = False

    async def _start(self) -> "SpecCluster":
        if self._started:
            return self
        cls = self.scheduler_spec["cls"]
        self.scheduler = cls(**self.scheduler_spec.get("options", {}))
        await self.scheduler.start()
        await self._correct_state()
        self._started = True
        if self._adaptive is not None:
            self._adaptive.cluster = self
            self._adaptive.start()
        return self

    async def _correct_state(self) -> None:
        """Reconcile live workers with the spec (reference deploy/spec.py:346)."""
        async with self._lock:
            # remove workers no longer in the spec
            to_close = [
                name for name in self.workers if name not in self.worker_spec
            ]

            async def _close_one(name: str) -> None:
                w = self.workers.pop(name)
                addr = getattr(w, "worker_address", None) or getattr(
                    w, "address", None
                )
                if addr is not None and self.scheduler is not None:
                    await self.scheduler.retire_workers(workers=[addr])
                await w.close()

            if to_close:
                results = await asyncio.gather(
                    *(_close_one(n) for n in to_close), return_exceptions=True
                )
                for r in results:
                    if isinstance(r, BaseException):
                        logger.warning("worker close failed: %r", r)

            # start workers in the spec but not yet live — concurrently,
            # so scale(N) pays ~one worker's startup latency
            async def _start_one(name: str, spec: dict) -> None:
                cls = spec["cls"]
                opts = dict(spec.get("options", {}))
                opts.setdefault("name", name)
                worker = cls(self.scheduler.address, **opts)
                await worker.start()
                self.workers[name] = worker

            pending = [
                (n, s) for n, s in self.worker_spec.items()
                if n not in self.workers
            ]
            if pending:
                # return_exceptions: let every sibling settle (and register
                # in self.workers) before re-raising the first failure, so
                # close() sees a complete view and orphans nothing
                results = await asyncio.gather(
                    *(_start_one(n, s) for n, s in pending),
                    return_exceptions=True,
                )
                for r in results:
                    if isinstance(r, BaseException):
                        raise r

    def _new_worker_name(self) -> str:
        while True:
            name = f"worker-{self._i}"
            self._i += 1
            if name not in self.worker_spec:
                return name

    async def scale(self, n: int) -> None:
        """Grow/shrink the spec to n workers, then reconcile
        (reference deploy/spec.py:538)."""
        while len(self.worker_spec) > n:
            self.worker_spec.popitem()
        while len(self.worker_spec) < n:
            if self.new_spec.get("cls") is None:
                raise ValueError("SpecCluster needs a `worker` template to scale up")
            self.worker_spec[self._new_worker_name()] = {
                "cls": self.new_spec["cls"],
                "options": dict(self.new_spec.get("options", {})),
            }
        await self._correct_state()

    async def close(self) -> None:
        if self._adaptive is not None:
            await self._adaptive.astop()
        # take the reconcile lock so no _correct_state is mid-flight
        async with self._lock:
            pass
        for w in list(self.workers.values()):
            await w.close()
        self.workers.clear()
        if self.scheduler is not None:
            await self.scheduler.close()

    def __repr__(self) -> str:
        return (
            f"<{type(self).__name__} workers={sorted(self.workers)} "
            f"spec={sorted(self.worker_spec)}>"
        )


class Adaptive:
    """Scale a cluster from the scheduler's adaptive target
    (reference deploy/adaptive.py:18, adaptive_core.py:26).

    Hysteresis: scale-down requires the same recommendation ``wait_count``
    consecutive intervals (reference distributed.yaml:209-215).
    """

    def __init__(
        self,
        cluster: Cluster | None = None,
        minimum: int | None = None,
        maximum: float | None = None,
        interval: float | None = None,
        wait_count: int | None = None,
        target_duration: float | None = None,
    ):
        from distributed_tpu import config

        self.cluster = cluster
        # config-backed defaults (reference distributed.yaml:209-215
        # adaptive.*): explicit arguments win
        self.minimum = (
            minimum if minimum is not None
            else int(config.get("adaptive.minimum") or 0)
        )
        cfg_max = config.get("adaptive.maximum")
        self.maximum = (
            maximum if maximum is not None
            else (float(cfg_max) if cfg_max not in (None, ".inf", "inf")
                  else float("inf"))
        )
        self.interval = (
            interval if interval is not None
            else config.parse_timedelta(config.get("adaptive.interval") or "1s")
        )
        self.wait_count = (
            wait_count if wait_count is not None
            else int(config.get("adaptive.wait-count") or 3)
        )
        self.target_duration = (
            target_duration if target_duration is not None
            else config.parse_timedelta(
                config.get("adaptive.target-duration") or "5s"
            )
        )
        self._task: asyncio.Task | None = None
        self._rpc: Any | None = None
        self._down_streak = 0
        self.log: list[tuple] = []

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def astop(self) -> None:
        """Cancel AND await the adapt task, so no scale is mid-flight when
        the cluster tears down."""
        task = self._task
        self.stop()
        if task is not None:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        if self._rpc is not None:
            await self._rpc.close_rpc()
            self._rpc = None

    async def target(self) -> int:
        """Desired worker count, from the scheduler's ``adaptive_target``
        (reference adaptive.py:18 driving scheduler.py:8400 over RPC).

        In-process schedulers (LocalCluster, SpecCluster) are asked
        directly; process-backed ones (SubprocessCluster, SSHCluster)
        over RPC."""
        assert self.cluster is not None and self.cluster.scheduler is not None
        scheduler = self.cluster.scheduler
        if hasattr(scheduler, "state"):
            cpu = scheduler.adaptive_target(
                target_duration=self.target_duration
            )
        else:
            if self._rpc is None:
                from distributed_tpu.rpc.core import rpc

                # one cached connection for the cluster's lifetime: a
                # fresh dial every interval would be a TCP (or full TLS)
                # handshake per second of pure overhead
                self._rpc = rpc(self.cluster.scheduler_address)
            cpu = await self._rpc.adaptive_target(
                target_duration=self.target_duration
            )
        return int(min(max(cpu, self.minimum), self.maximum))

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self.adapt()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("adaptive cycle failed")

    async def adapt(self) -> None:
        assert self.cluster is not None
        n_now = len(getattr(self.cluster, "workers", {}))
        n_want = await self.target()
        if n_want > n_now:
            self._down_streak = 0
            self.log.append(("up", n_now, n_want))
            await self.cluster.scale(n_want)
        elif n_want < n_now:
            self._down_streak += 1
            if self._down_streak >= self.wait_count:
                self._down_streak = 0
                self.log.append(("down", n_now, n_want))
                await self.cluster.scale(n_want)
        else:
            self._down_streak = 0
