"""Measured-truth telemetry plane: per-link transfer stats, task-prefix
priors, and the shadow cost-model divergence monitor.

ROADMAP item 3's standing indictment is that the static
``scheduler.bandwidth`` constant (config.py) was measured ~10x wrong
(PERF.md Round 4) while the cluster already measures the truth and
throws it away as unattributed global sums.  This module is the
*measurement* half of the fix — a strictly read-only observability
layer:

- **per-link transfer telemetry**: every ``get_data``/``gather_dep``
  transfer files ``(src, dst, nbytes, seconds)`` on both ends (the
  requesting end's sample is the authoritative bandwidth — it observes
  the full fetch the cost model prices; the serving end's true-wire
  bytes are the cross-check), folded into per-link EWMA bandwidth /
  latency plus native t-digests (``native/tdigest.cpp`` via
  ``utils.counter.Digest``) and shipped to the scheduler as heartbeat
  deltas next to the span fine-metrics;
- **per-task-prefix priors**: EWMA duration and output-nbytes per task
  prefix, aggregated scheduler-side from the same heartbeat stream
  (the worker's per-task ``execute`` fine-metric rows);
- **shadow cost-model divergence**: at each placement decision and
  steal pricing the scheduler computes the measured-model comm cost
  next to the constant model (same ``get_comm_cost`` shape, measured
  link bandwidth with constant fallback for unseen links) and records
  ``measured / constant`` in the ``dtpu_costmodel_divergence_ratio``
  histogram plus a sampled flight-recorder ``shadow`` event carrying
  the stimulus id — so Perfetto shows *which decisions the constants
  are lying about*.  **Decisions still use the constants**: swapping
  the kernel inputs is ROADMAP item 3's future PR, and a property test
  asserts bit-identical decisions with telemetry on/off.

Exposed via ``/metrics`` (per-link gauges, priors, the divergence
histogram), the ``/telemetry`` JSONL route on both roles, cluster
dumps, and Perfetto counter tracks (docs/observability.md).

This file is pure (no IO, no event loop, no threads of its own): both
roles' servers import it, and the monotonic-time lint covers it — the
snapshot timestamp is ``utils.misc.time`` (monotonic), so telemetry
records line up with flight-recorder events on one clock.
"""

from __future__ import annotations

from typing import Any

from distributed_tpu import config
from distributed_tpu.utils import time

#: schema version of /telemetry JSONL records (bump on field changes)
TELEMETRY_SCHEMA_VERSION = 1

#: divergence-ratio histogram layout (measured / constant cost): dense
#: around 1.0 (agreement), decades out to the ~10x-off regime Round 4
#: measured and beyond
RATIO_BUCKETS = (
    0.01, 0.03, 0.1, 0.2, 0.33, 0.5, 0.8, 1.0, 1.25, 2.0, 3.0, 5.0,
    10.0, 30.0, 100.0,
)

#: ratios are clamped here before observation: a zero constant cost
#: against a nonzero measured one is "infinitely" divergent, and +inf
#: would poison the histogram sum
RATIO_CLAMP = 1e6


class EWMA:
    """Exponentially weighted moving average with a weight-aware update
    (a heartbeat row aggregating N samples applies the N-fold decay in
    one step: ``alpha_eff = 1 - (1-alpha)**N``)."""

    __slots__ = ("alpha", "value", "count")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.value = 0.0
        self.count = 0

    def update(self, sample: float, weight: int = 1) -> None:
        if weight <= 0:
            return
        if self.count == 0:
            self.value = sample
        else:
            a = 1.0 - (1.0 - self.alpha) ** weight
            self.value += a * (sample - self.value)
        self.count += weight


class LinkStats:
    """One directed link (src worker -> dst worker).

    ``bandwidth``/``latency`` EWMAs and the t-digest fold only
    destination-observed samples (the full fetch the cost model
    prices); ``peer_*`` totals accumulate the serving end's true-wire
    bytes as the framing-overhead cross-check (docs/observability.md).
    """

    __slots__ = ("src", "dst", "bandwidth", "latency", "bytes_total",
                 "seconds_total", "digest", "peer_bytes", "peer_seconds",
                 "peer_count")

    def __init__(self, src: str, dst: str, alpha: float):
        self.src = src
        self.dst = dst
        self.bandwidth = EWMA(alpha)   # bytes/second
        self.latency = EWMA(alpha)     # residual seconds past bytes/bw
        self.bytes_total = 0
        self.seconds_total = 0.0
        self.digest: Any = None        # lazy Digest of per-sample bytes/s
        self.peer_bytes = 0            # serving-end-reported wire bytes
        self.peer_seconds = 0.0
        self.peer_count = 0

    def fold(self, nbytes: int, seconds: float, count: int = 1) -> None:
        """Fold one destination-observed sample (or a heartbeat row
        aggregating ``count`` of them)."""
        if seconds <= 0.0:
            seconds = 1e-9
        bw = nbytes / seconds
        self.bandwidth.update(bw, count)
        # residual latency: observed seconds minus the pure-transfer
        # time at the current bandwidth estimate — a crude but
        # monotone-clock-honest per-link fixed-cost estimate (count>1
        # rows average the residual over the row)
        per = seconds / count
        resid = per - (nbytes / count) / max(self.bandwidth.value, 1e-9)
        self.latency.update(max(resid, 0.0), count)
        self.bytes_total += int(nbytes)
        self.seconds_total += seconds
        if self.digest is None:
            from distributed_tpu.utils.counter import Digest

            self.digest = Digest()
        self.digest.add(bw, float(count))

    def fold_peer(self, nbytes: int, seconds: float, count: int = 1) -> None:
        """Fold a source-reported (serving-end) row: cross-check totals
        only — the serving end's clock never sees the request leg, so
        its bandwidth view must not dilute the destination EWMA."""
        self.peer_bytes += int(nbytes)
        self.peer_seconds += seconds
        self.peer_count += count

    def record(self) -> dict:
        out = {
            "v": TELEMETRY_SCHEMA_VERSION,
            "type": "link",
            "src": self.src,
            "dst": self.dst,
            "bandwidth": self.bandwidth.value,
            "latency": self.latency.value,
            "count": self.bandwidth.count,
            "bytes": self.bytes_total,
            "seconds": self.seconds_total,
            "peer_bytes": self.peer_bytes,
            "peer_seconds": self.peer_seconds,
            "peer_count": self.peer_count,
        }
        if self.digest is not None and self.digest.count():
            out["bw_q50"] = self.digest.quantile(0.5)
            out["bw_q90"] = self.digest.quantile(0.9)
            out["bw_q99"] = self.digest.quantile(0.99)
        return out


def parse_link_profile(records: list[dict]) -> dict[tuple[str, str], tuple[float, float]]:
    """Parse link-profile (or full ``/telemetry``) records into
    ``{(src, dst): (bandwidth_bytes_per_s, latency_s)}`` — the seedable
    form the simulator's ``LinkProfile`` consumes.  Rows that are not
    ``type == "link"`` or carry no measured bandwidth are skipped."""
    out: dict[tuple[str, str], tuple[float, float]] = {}
    for rec in records:
        if rec.get("type") != "link":
            continue
        bw = float(rec.get("bandwidth") or 0.0)
        if bw <= 0.0:
            continue
        out[(str(rec.get("src", "")), str(rec.get("dst", "")))] = (
            bw, max(float(rec.get("latency") or 0.0), 0.0)
        )
    return out


class PrefixPrior:
    """Measured per-task-prefix priors: EWMA duration and output bytes
    (the measured twin of ``TaskPrefix.duration_average`` /
    ``UNKNOWN_TASK_DURATION``, fed from realized executions)."""

    __slots__ = ("name", "duration", "nbytes", "n_tasks")

    def __init__(self, name: str, alpha: float):
        self.name = name
        self.duration = EWMA(alpha)
        self.nbytes = EWMA(alpha)
        self.n_tasks = 0

    def record(self) -> dict:
        return {
            "v": TELEMETRY_SCHEMA_VERSION,
            "type": "prior",
            "prefix": self.name,
            "duration": self.duration.value,
            "nbytes": self.nbytes.value,
            "n_tasks": self.n_tasks,
        }


class LinkTelemetry:
    """Per-node transfer-telemetry collector.

    Workers record transfers as they happen (``record``); the
    since-heartbeat delta buffer (``take``/``restore``/``rows``, the
    ``FineMetrics`` idiom) ships per-link aggregates to the scheduler,
    whose :class:`ClusterTelemetry` folds them fleet-wide.
    """

    def __init__(self, alpha: float | None = None,
                 enabled: bool | None = None):
        if alpha is None:
            alpha = float(config.get("scheduler.telemetry.ewma-alpha"))
        if enabled is None:
            enabled = bool(config.get("scheduler.telemetry.enabled"))
        self.alpha = alpha
        self.enabled = bool(enabled)
        # injectable clock (ROADMAP item 1 simulator): snapshots are the
        # only place this collector stamps time — the fold path takes
        # ``seconds`` as data, never reads a clock — so re-pointing this
        # at a VirtualClock keeps simulated-transfer EWMAs and their
        # /telemetry records entirely on virtual time.
        self.clock = time
        # deferred-materialization barrier: the scheduler's native
        # engine points this at its sync() so a live transfer record
        # lands AFTER any parked shadow-cost folds replay (ordering of
        # EWMA folds is observable in divergence telemetry).  The folds
        # that run DURING replay (shadow_comm_cost, join_row realized
        # costs) enter below the barrier, so replay never re-enters it.
        self.barrier: Any = None
        self.links: dict[tuple[str, str], LinkStats] = {}
        # since-heartbeat delta: (src, dst) -> [nbytes, seconds, count]
        self.since_heartbeat: dict[tuple[str, str], list] = {}

    def _link(self, src: str, dst: str) -> LinkStats:
        link = self.links.get((src, dst))
        if link is None:
            link = self.links[(src, dst)] = LinkStats(src, dst, self.alpha)
        return link

    def record(self, src: str, dst: str, nbytes: int,
               seconds: float) -> None:
        """File one transfer observed at its DESTINATION (the
        authoritative bandwidth sample: the full fetch the cost model
        prices)."""
        b = self.barrier
        if b is not None:
            b()
        if not self.enabled or not src or not dst:
            return
        self._link(src, dst).fold(nbytes, seconds)
        self._delta(src, dst, nbytes, seconds)

    def record_peer(self, src: str, dst: str, nbytes: int,
                    seconds: float) -> None:
        """File one transfer observed at its SOURCE (the get_data
        serving end): cross-check totals only, locally AND in the
        shipped delta — the serving clock stops when the OS accepts the
        write, not when the peer received the bytes, so this view must
        never fold into the dst-observed bandwidth EWMA (the scheduler
        re-classifies shipped rows by reporter; the local collector
        splits here)."""
        b = self.barrier
        if b is not None:
            b()
        if not self.enabled or not src or not dst:
            return
        self._link(src, dst).fold_peer(nbytes, seconds)
        self._delta(src, dst, nbytes, seconds)

    def _delta(self, src: str, dst: str, nbytes: int,
               seconds: float) -> None:
        d = self.since_heartbeat.get((src, dst))
        if d is None:
            self.since_heartbeat[(src, dst)] = [int(nbytes), seconds, 1]
        else:
            d[0] += int(nbytes)
            d[1] += seconds
            d[2] += 1

    # --------------------------------------------------- heartbeat delta

    def take(self) -> dict[tuple[str, str], list]:
        """Pop the heartbeat delta; pair with restore() on send failure."""
        out = self.since_heartbeat
        self.since_heartbeat = {}
        return out

    def restore(self, delta: dict[tuple[str, str], list]) -> None:
        for k, (nbytes, seconds, count) in delta.items():
            d = self.since_heartbeat.get(k)
            if d is None:
                self.since_heartbeat[k] = [nbytes, seconds, count]
            else:
                d[0] += nbytes
                d[1] += seconds
                d[2] += count

    @staticmethod
    def rows(delta: dict[tuple[str, str], list]) -> list[list]:
        """msgpack-friendly encoding: [src, dst, nbytes, seconds, count]."""
        return [[src, dst, *vals] for (src, dst), vals in delta.items()]

    def fold_rows(self, rows: list, reporter: str = "") -> None:
        """Fold heartbeat delta rows into the fleet view.

        ``reporter`` is the worker that shipped them: rows it reports as
        the transfer *destination* are authoritative bandwidth samples;
        rows it reports as the *source* (get_data serving end) fold into
        the cross-check totals only.
        """
        for row in rows:
            try:
                src, dst, nbytes, seconds, count = row
            except (TypeError, ValueError):
                continue
            link = self._link(src, dst)
            if reporter and reporter == src and src != dst:
                link.fold_peer(nbytes, seconds, count)
            else:
                link.fold(nbytes, seconds, max(int(count), 1))

    # ---------------------------------------------------------- snapshot

    def snapshot(self, now: float | None = None) -> list[dict]:
        """JSON-safe records for ``/telemetry`` and cluster dumps.  One
        monotonic ``ts`` per snapshot so records line up with
        flight-recorder events on the same in-process clock."""
        if now is None:
            now = self.clock()
        out = []
        for link in self.links.values():
            rec = link.record()
            rec["ts"] = now
            out.append(rec)
        return out

    # ------------------------------------------------------ link profiles

    def link_profile(self) -> list[dict]:
        """Export the measured per-link state as a *link profile*: the
        minimal ``{src, dst, bandwidth, latency, count}`` rows the
        ROADMAP item 1 simulator seeds its network model from
        (``distributed_tpu.sim.links.LinkProfile.from_records``).  Full
        ``/telemetry`` link records parse too — this export just strips
        the cross-check totals and digest quantiles a simulation cannot
        use."""
        out = []
        for link in self.links.values():
            if not link.bandwidth.count:
                continue
            out.append({
                "v": TELEMETRY_SCHEMA_VERSION,
                "type": "link",
                "src": link.src,
                "dst": link.dst,
                "bandwidth": link.bandwidth.value,
                "latency": link.latency.value,
                "count": link.bandwidth.count,
            })
        return out


class ClusterTelemetry(LinkTelemetry):
    """The scheduler's fleet-wide aggregate: links (folded from worker
    heartbeats) + per-worker heartbeat RTT + task-prefix priors + the
    shadow cost-model divergence monitor."""

    def __init__(self, alpha: float | None = None,
                 enabled: bool | None = None):
        super().__init__(alpha=alpha, enabled=enabled)
        from distributed_tpu.tracing import Histogram

        self.rtt: dict[str, float] = {}       # worker -> EWMA seconds
        self.priors: dict[str, PrefixPrior] = {}
        self.hist_divergence = Histogram(RATIO_BUCKETS)
        self.divergence_sample = max(
            int(config.get("scheduler.telemetry.divergence-sample")), 1
        )
        self._div_tick = 0
        self.shadow_evals = 0        # shadow cost evaluations performed
        self.shadow_measured = 0     # evals where >=1 measured link priced
        # extremes over MEASURED evals; None until one happens (a 1.0
        # initializer would report a never-observed perfect agreement)
        self.ratio_min: float | None = None
        self.ratio_max: float | None = None

    # --------------------------------------------------------------- rtt

    def record_rtt(self, worker: str, rtt: float) -> None:
        """Store a worker's heartbeat round-trip EWMA (measured at the
        worker with monotonic stamps around the heartbeat RPC)."""
        if rtt > 0.0:
            self.rtt[worker] = rtt

    def forget_worker(self, worker: str) -> None:
        """Drop a removed worker's RTT and every link touching it —
        restarted workers bind fresh ports, so dead-address LinkStats
        (each holding a native t-digest) would otherwise accumulate
        forever and crowd live links out of the /metrics top-N cut."""
        self.rtt.pop(worker, None)
        for key in [k for k in self.links if worker in k]:
            del self.links[key]

    # ------------------------------------------------------------ priors

    def fold_fine_rows(self, rows: list) -> None:
        """Derive per-prefix priors from one heartbeat's fine-metric
        rows (``[context, span_id, prefix, label, unit, value]``): the
        worker files per-task ``compute``/``output``/``count`` samples
        under the ``execute`` context, and each heartbeat's per-prefix
        mean folds in as one count-weighted EWMA step."""
        agg: dict[str, list] = {}  # prefix -> [seconds, bytes, count]
        for row in rows:
            try:
                context, _sid, prefix, label, _unit, value = row
            except (TypeError, ValueError):
                continue
            if context != "execute" or not prefix:
                continue
            a = agg.get(prefix)
            if a is None:
                a = agg[prefix] = [0.0, 0.0, 0]
            if label == "compute":
                a[0] += value
            elif label == "output":
                a[1] += value
            elif label == "count":
                a[2] += int(value)
        for prefix, (seconds, nbytes, count) in agg.items():
            if count <= 0:
                continue
            prior = self.priors.get(prefix)
            if prior is None:
                prior = self.priors[prefix] = PrefixPrior(prefix, self.alpha)
            prior.duration.update(seconds / count, count)
            prior.nbytes.update(nbytes / count, count)
            prior.n_tasks += count

    # ------------------------------------------------- shadow divergence

    def tick_divergence(self) -> bool:
        """1-in-N sampling gate for shadow evaluations
        (``scheduler.telemetry.divergence-sample``)."""
        t = self._div_tick + 1
        self._div_tick = t
        return not t % self.divergence_sample

    def observe_divergence(self, constant: float, measured: float,
                           used_measured: bool) -> float:
        """Record one shadow comparison; returns the (clamped) ratio.

        Strictly read-only with respect to scheduling: nothing here is
        ever consulted by a decision path.
        """
        if constant > 1e-12:
            ratio = min(measured / constant, RATIO_CLAMP)
        else:
            ratio = 1.0 if measured <= 1e-12 else RATIO_CLAMP
        self.hist_divergence.observe(ratio)
        self.shadow_evals += 1
        if used_measured:
            self.shadow_measured += 1
            if self.ratio_min is None or ratio < self.ratio_min:
                self.ratio_min = ratio
            if self.ratio_max is None or ratio > self.ratio_max:
                self.ratio_max = ratio
        return ratio

    # ---------------------------------------------------------- snapshot

    def snapshot(self, now: float | None = None) -> list[dict]:
        if now is None:
            now = self.clock()
        out = super().snapshot(now)
        for worker, rtt in self.rtt.items():
            out.append({
                "v": TELEMETRY_SCHEMA_VERSION,
                "type": "rtt",
                "ts": now,
                "worker": worker,
                "rtt": rtt,
            })
        for prior in self.priors.values():
            rec = prior.record()
            rec["ts"] = now
            out.append(rec)
        h = self.hist_divergence
        out.append({
            "v": TELEMETRY_SCHEMA_VERSION,
            "type": "divergence",
            "ts": now,
            "count": h.count,
            "sum": h.sum,
            "evals": self.shadow_evals,
            "measured": self.shadow_measured,
            "ratio_min": self.ratio_min,
            "ratio_max": self.ratio_max,
        })
        return out
