"""Preload: run user code at server startup (reference preloading.py).

A preload is a module name, a file path, or raw source text.  At server
start it is imported/exec'd and its ``dtpu_setup(server)`` /
``dtpu_teardown(server)`` hooks are called (the reference's
``dask_setup``/``dask_teardown``, preloading.py:154,225).  Configured per
server class via ``scheduler.preload`` / ``worker.preload`` / CLI flags.
"""

from __future__ import annotations

import asyncio
import importlib
import logging
import os
import sys
import types
from typing import Any

logger = logging.getLogger("distributed_tpu.preload")


def _exec_module(source: str, filename: str, key: str) -> types.ModuleType:
    name = f"_dtpu_preload_{abs(hash(key)) % 10**8}"
    module = types.ModuleType(name)
    exec(compile(source, filename, "exec"), module.__dict__)
    sys.modules[name] = module
    return module


def _load_module(spec: str) -> types.ModuleType:
    if spec.endswith(".py") or os.path.sep in spec and os.path.exists(spec):
        with open(spec) as f:
            return _exec_module(f.read(), spec, spec)
    if "\n" in spec or ";" in spec:
        return _exec_module(spec, "<preload>", spec)
    return importlib.import_module(spec)


class Preload:
    """One preload attached to one server (reference preloading.py:154)."""

    def __init__(self, server: Any, spec: str, argv: list[str] | None = None):
        self.server = server
        self.spec = spec
        self.argv = argv or []
        self.module: types.ModuleType | None = None

    async def start(self) -> None:
        logger.info("loading preload %r", self.spec)
        self.module = _load_module(self.spec)
        setup = getattr(self.module, "dtpu_setup", None)
        if setup is not None:
            result = setup(self.server)
            if asyncio.iscoroutine(result):
                await result

    async def teardown(self) -> None:
        if self.module is None:
            return
        teardown = getattr(self.module, "dtpu_teardown", None)
        if teardown is not None:
            result = teardown(self.server)
            if asyncio.iscoroutine(result):
                await result


def process_preloads(server: Any, specs: list[str] | str | None,
                     argv: list[str] | None = None) -> list[Preload]:
    if not specs:
        return []
    if isinstance(specs, str):
        specs = [specs]
    return [Preload(server, spec, argv) for spec in specs]
