"""TCP / TLS comm backend over asyncio streams.

Wire format per message (reference comm/tcp.py:372 shape):

    uint64  n_frames
    uint64  length[n_frames]
    bytes   frame[0] ... frame[n_frames-1]

Frames come from ``protocol.dumps`` (msgpack header + body + payload).
Writes of large frames go straight to the transport without an extra copy;
reads use ``readexactly``.  TLS wraps the same streams with an
``ssl.SSLContext`` built by ``distributed_tpu.security.Security``.
"""

from __future__ import annotations

import asyncio
import socket
import struct
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.comm.addressing import parse_host_port, unparse_host_port
from distributed_tpu.comm.core import Backend, Comm, Connector, Listener, register_backend
from distributed_tpu.exceptions import CommClosedError, FatalCommClosedError
from distributed_tpu.protocol import dumps, loads

_u64 = struct.Struct("<Q")

MAX_FRAME_COUNT = 2**20  # sanity bound on header


def _set_tcp_options(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)


class TCP(Comm):
    scheme = "tcp"

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 local_addr: str, peer_addr: str, deserialize: bool = True):
        super().__init__(deserialize=deserialize)
        self._reader = reader
        self._writer = writer
        self._local_addr = local_addr
        self._peer_addr = peer_addr
        self._closed = False
        self._write_lock = asyncio.Lock()

    async def read(self) -> Any:
        try:
            head = await self._reader.readexactly(8)
            (n_frames,) = _u64.unpack(head)
            if n_frames > MAX_FRAME_COUNT:
                raise CommClosedError(f"bad frame count {n_frames}")
            lengths_raw = await self._reader.readexactly(8 * n_frames)
            lengths = struct.unpack(f"<{n_frames}Q", lengths_raw)
            frames = [await self._reader.readexactly(n) for n in lengths]
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError,
                OSError) as e:
            self.abort()
            raise CommClosedError(f"read failed: {e!r}") from e
        try:
            return loads(frames, deserializers=self.deserialize)
        except Exception:
            self.abort()
            raise

    async def write(self, msg: Any, on_error: str = "message") -> int:
        compression = self.handshake_options.get("compression", "auto")
        try:
            frames = dumps(msg, compression=compression)
        except Exception:
            if on_error == "raise":
                raise
            from distributed_tpu.utils import format_exception

            # graft-lint: allow[handler-parity] comm-layer sentinel surfaced to the reader, not a dispatched op
            frames = dumps({"op": "protocol-error", "error": format_exception()})
        lengths = [memoryview(f).nbytes for f in frames]
        header = _u64.pack(len(frames)) + struct.pack(f"<{len(frames)}Q", *lengths)
        async with self._write_lock:
            try:
                self._writer.write(header)
                for f in frames:
                    self._writer.write(bytes(f) if isinstance(f, memoryview) else f)
                await self._writer.drain()
            except (ConnectionResetError, BrokenPipeError, RuntimeError, OSError) as e:
                self.abort()
                raise CommClosedError(f"write failed: {e!r}") from e
        return sum(lengths) + len(header)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._writer.can_write_eof():
                self._writer.write_eof()
            self._writer.close()
            await asyncio.wait_for(self._writer.wait_closed(), 1.0)
        except Exception:
            pass

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._writer.transport.abort()
            except Exception:
                pass

    @property
    def local_address(self) -> str:
        return self._local_addr

    @property
    def peer_address(self) -> str:
        return self._peer_addr

    @property
    def closed(self) -> bool:
        return self._closed or self._reader.at_eof()


class TLS(TCP):
    scheme = "tls"


def _sock_addrs(writer: asyncio.StreamWriter, scheme: str) -> tuple[str, str]:
    sock = writer.get_extra_info("sockname")
    peer = writer.get_extra_info("peername")

    def fmt(sa):
        if sa is None:
            return f"{scheme}://<closed>"
        host, port = sa[0], sa[1]
        return f"{scheme}://{unparse_host_port(host, port)}"

    return fmt(sock), fmt(peer)


class TCPConnector(Connector):
    scheme = "tcp"
    ssl_context = None
    use_ssl = False  # the SCHEME decides: tcp:// never handshakes TLS,
    # even when connection_args carry an ssl_context (a secured client
    # talking to a plain endpoint must not TLS a plaintext listener)

    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm:
        host, port = parse_host_port(address)
        ssl_ctx = (
            kwargs.get("ssl_context", self.ssl_context)
            if self.use_ssl else None
        )
        try:
            reader, writer = await asyncio.open_connection(
                host, port, ssl=ssl_ctx, limit=2**24
            )
        except ConnectionRefusedError as e:
            raise CommClosedError(f"connection refused: {address}") from e
        except (ssl_error_types()) as e:
            raise FatalCommClosedError(f"TLS failure connecting to {address}: {e!r}") from e
        sock = writer.get_extra_info("socket")
        if sock is not None and ssl_ctx is None:
            _set_tcp_options(sock)
        local, peer = _sock_addrs(writer, self.scheme)
        cls = TLS if ssl_ctx is not None else TCP
        return cls(reader, writer, local, f"{self.scheme}://{address}", deserialize)


def ssl_error_types():
    import ssl

    return (ssl.SSLError, ssl.CertificateError)


class TLSConnector(TCPConnector):
    scheme = "tls"
    use_ssl = True

    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm:
        if kwargs.get("ssl_context") is None:
            from distributed_tpu.security import Security

            kwargs["ssl_context"] = Security().get_connection_args("client").get("ssl_context")
        if kwargs["ssl_context"] is None:
            raise FatalCommClosedError("tls:// requires an ssl_context (configure comm.tls)")
        return await super().connect(address, deserialize, **kwargs)


class TCPListener(Listener):
    scheme = "tcp"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        host, port = parse_host_port(loc or "0.0.0.0:0")
        self.host = host or "0.0.0.0"
        self.port = port
        self.handle_comm = handle_comm
        self.deserialize = deserialize
        self.server: asyncio.AbstractServer | None = None
        # scheme decides: a tcp:// listener serves plaintext even when
        # listen_args carry an ssl_context (the address must not lie)
        self.ssl_context = (
            kwargs.get("ssl_context") if self.scheme == "tls" else None
        )
        self._comms: set[Comm] = set()

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None and self.ssl_context is None:
            _set_tcp_options(sock)
        local, peer = _sock_addrs(writer, self.scheme)
        cls = TLS if self.ssl_context is not None else TCP
        comm = cls(reader, writer, local, peer, self.deserialize)
        try:
            await self.on_connection(comm)
        except CommClosedError:
            return
        self._comms.add(comm)
        try:
            await self.handle_comm(comm)
        finally:
            self._comms.discard(comm)

    async def start(self) -> None:
        backlog = config.get("comm.socket-backlog")
        self.server = await asyncio.start_server(
            self._on_connection, self.host, self.port or None,
            ssl=self.ssl_context, backlog=backlog, limit=2**24, reuse_address=True,
        )
        if self.port == 0:
            self.port = self.server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None

    @property
    def listen_address(self) -> str:
        return f"{self.scheme}://{unparse_host_port(self.host, self.port)}"

    @property
    def contact_address(self) -> str:
        host = self.host
        if host in ("0.0.0.0", ""):
            from distributed_tpu.utils import get_ip

            host = get_ip()
        return f"{self.scheme}://{unparse_host_port(host, self.port)}"


class TLSListener(TCPListener):
    scheme = "tls"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        super().__init__(loc, handle_comm, deserialize, **kwargs)
        if self.ssl_context is None:
            from distributed_tpu.security import Security

            self.ssl_context = Security().get_listen_args("scheduler").get("ssl_context")
        if self.ssl_context is None:
            raise ValueError("tls:// listener requires ssl_context (configure comm.tls)")


class TCPBackend(Backend):
    _connector_cls = TCPConnector
    _listener_cls = TCPListener

    def get_connector(self) -> Connector:
        return self._connector_cls()

    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener:
        return self._listener_cls(loc, handle_comm, deserialize, **kwargs)


class TLSBackend(TCPBackend):
    _connector_cls = TLSConnector
    _listener_cls = TLSListener


register_backend("tcp", TCPBackend())
register_backend("tls", TLSBackend())
