"""TCP / TLS comm backend over asyncio streams.

Wire format per message (reference comm/tcp.py:372 shape):

    uint64  n_frames
    uint64  length[n_frames]
    bytes   frame[0] ... frame[n_frames-1]

Frames come from ``protocol.dumps`` (msgpack header + body + payload).
Zero-copy contract (docs/wire.md): the send side builds one scatter
list — packed preamble plus the frames as-is — and hands memoryviews
straight to the transport (small pieces coalesce into the preamble to
bound syscalls; payload-sized frames are NEVER materialized).  The
receive side reads the whole payload section into one pooled contiguous
buffer and carves frames as read-only memoryview slices.  TLS wraps the
same streams with an ``ssl.SSLContext`` built by
``distributed_tpu.security.Security``.
"""

from __future__ import annotations

import asyncio
import socket
import struct
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.comm.addressing import parse_host_port, unparse_host_port
from distributed_tpu.comm.core import Backend, Comm, Connector, Listener, register_backend
from distributed_tpu.exceptions import CommClosedError, FatalCommClosedError
from distributed_tpu.protocol import dumps, loads
from distributed_tpu.protocol.buffers import WIRE, max_message_bytes, recv_pool

_u64 = struct.Struct("<Q")

MAX_FRAME_COUNT = 2**20  # sanity bound on header

#: frames at or below this coalesce into the preamble write (one small
#: gather copy instead of one syscall-sized write per tiny frame); above
#: it a frame always rides the wire as its own zero-copy buffer
COALESCE_MAX = 4096


def scatter_frames(frames: list) -> tuple[list, int]:
    """Build the scatter list for one message: packed preamble + frames.

    Returns ``(buffers, total_bytes)``.  Small frames are gathered into
    the preamble bytearray; large frames append as memoryviews with no
    materialization (the ``dtpu_wire_payload_copies`` contract)."""
    lengths = []
    views = []
    for f in frames:
        if isinstance(f, (bytes, bytearray)):
            lengths.append(len(f))
            views.append(f)
            continue
        mv = f if isinstance(f, memoryview) else memoryview(f)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        lengths.append(mv.nbytes)
        views.append(mv)
    head = bytearray(_u64.pack(len(views)))
    head += struct.pack(f"<{len(views)}Q", *lengths)
    total = len(head) + sum(lengths)  # before coalescing grows `head`
    out: list = [head]
    # only ever extend scratch bytearrays WE created (head, or a fresh
    # coalesce buffer): a large caller-owned bytearray frame sits in
    # `out` too, and growing it would corrupt the caller's data
    scratch: bytearray | None = head
    for n, v in zip(lengths, views):
        if n > COALESCE_MAX:
            out.append(v)
            scratch = None
        elif scratch is not None:
            scratch += v
        else:
            scratch = bytearray(v)
            out.append(scratch)
    return out, total


async def readinto_exactly(reader: asyncio.StreamReader, view: memoryview) -> None:
    """Fill ``view`` from the stream — the ``readinto`` asyncio's
    StreamReader never grew.  Drains the reader's internal buffer
    directly (one user-space copy, no per-read allocation); falls back
    to chunked public-API reads if the internals ever move."""
    n = view.nbytes
    pos = 0
    buf = getattr(reader, "_buffer", None)
    if buf is None or not hasattr(reader, "_wait_for_data"):
        while pos < n:  # pragma: no cover - exercised only off-CPython
            chunk = await reader.read(n - pos)
            if not chunk:
                # graft-lint: allow[wire-no-copy] error-path partial for IncompleteReadError, connection is dead
                raise asyncio.IncompleteReadError(bytes(view[:pos]), n)
            view[pos : pos + len(chunk)] = chunk
            pos += len(chunk)
        return
    while pos < n:
        # set_exception with no pending waiter (peer RST between reads)
        # leaves _buffer empty and _eof unset: without this check the
        # _wait_for_data below would block forever.  readexactly makes
        # the same raise-before-drain check.
        exc = reader.exception()
        if exc is not None:
            raise exc
        if not buf:
            if reader.at_eof():
                # graft-lint: allow[wire-no-copy] error-path partial for IncompleteReadError, connection is dead
                raise asyncio.IncompleteReadError(bytes(view[:pos]), n)
            await reader._wait_for_data("readinto_exactly")
            continue
        take = min(len(buf), n - pos)
        with memoryview(buf) as src:  # released before the resize below
            view[pos : pos + take] = src[:take]
        del buf[:take]
        reader._maybe_resume_transport()
        pos += take


def _set_tcp_options(sock: socket.socket) -> None:
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)


class TCP(Comm):
    scheme = "tcp"

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 local_addr: str, peer_addr: str, deserialize: bool = True):
        super().__init__(deserialize=deserialize)
        self._reader = reader
        self._writer = writer
        self._local_addr = local_addr
        self._peer_addr = peer_addr
        self._closed = False
        self._write_lock = asyncio.Lock()

    async def read(self) -> Any:
        buf = view = ro = frames = None
        try:
            try:
                head = await self._reader.readexactly(8)
            except (asyncio.IncompleteReadError, ConnectionResetError,
                    BrokenPipeError, OSError) as e:
                self.abort()
                raise CommClosedError(f"read failed: {e!r}") from e
            # CancelledError above propagates WITHOUT abort: readexactly
            # is all-or-nothing, so a cancelled idle wait leaves the
            # stream at a message boundary and the comm reusable —
            # teardown paths cancel pending reads on comms they then
            # close in an orderly way
            try:
                (n_frames,) = _u64.unpack(head)
                if n_frames > MAX_FRAME_COUNT:
                    raise CommClosedError(f"bad frame count {n_frames}")
                lengths_raw = await self._reader.readexactly(8 * n_frames)
                lengths = struct.unpack(f"<{n_frames}Q", lengths_raw)
                total = sum(lengths)
                if total > max_message_bytes():
                    raise CommClosedError(
                        f"message of {total} bytes exceeds "
                        f"comm.max-message-bytes ({max_message_bytes()})"
                    )
                # one pooled contiguous buffer for the whole payload
                # section; frames are read-only zero-copy slices of it
                buf = recv_pool().acquire(total)
                view = memoryview(buf)[:total]
                await readinto_exactly(self._reader, view)
                WIRE.bytes_recv += total + 8 + 8 * n_frames
                ro = view.toreadonly()
                frames = []
                off = 0
                for n in lengths:
                    frames.append(ro[off : off + n])
                    off += n
            except CommClosedError:
                # our own guards (frame count / message size): the
                # stream is desynced — abort, don't re-wrap
                self.abort()
                raise
            except (asyncio.IncompleteReadError, ConnectionResetError,
                    BrokenPipeError, OSError) as e:
                self.abort()
                raise CommClosedError(f"read failed: {e!r}") from e
            except BaseException:
                # anything else (MemoryError from the pool acquire,
                # cancellation mid-message): the 8-byte count header is
                # already consumed, so the stream is desynced — the
                # next read would parse payload bytes as a frame count
                self.abort()
                raise
            try:
                return loads(frames, deserializers=self.deserialize)
            except Exception:
                self.abort()
                raise
        finally:
            # drop our exports before offering the buffer back: if the
            # message pinned zero-copy views (numpy frames, opaque
            # Serialized payloads) the pool's export probe drops the
            # buffer instead of ever reusing it under a live view
            view = ro = frames = None
            if buf is not None:
                recv_pool().release(buf)

    async def write(self, msg: Any, on_error: str = "message") -> int:
        compression = self.handshake_options.get("compression", "auto")
        try:
            frames = dumps(msg, compression=compression)
        except Exception as e:
            if on_error == "raise":
                raise
            from distributed_tpu.utils import format_exception

            # graft-lint: allow[handler-parity] comm-layer sentinel surfaced to the reader, not a dispatched op
            frames = dumps({"op": "protocol-error", "error": format_exception(e)})
        bufs, total = scatter_frames(frames)
        async with self._write_lock:
            try:
                for b in bufs:
                    self._writer.write(b)
                await self._writer.drain()
            except (ConnectionResetError, BrokenPipeError, RuntimeError, OSError) as e:
                self.abort()
                raise CommClosedError(f"write failed: {e!r}") from e
        WIRE.bytes_sent += total
        return total

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._writer.can_write_eof():
                self._writer.write_eof()
            self._writer.close()
            await asyncio.wait_for(self._writer.wait_closed(), 1.0)
        except Exception:
            pass

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._writer.transport.abort()
            except Exception:
                pass

    @property
    def local_address(self) -> str:
        return self._local_addr

    @property
    def peer_address(self) -> str:
        return self._peer_addr

    @property
    def closed(self) -> bool:
        return self._closed or self._reader.at_eof()


class TLS(TCP):
    scheme = "tls"


def _sock_addrs(writer: asyncio.StreamWriter, scheme: str) -> tuple[str, str]:
    sock = writer.get_extra_info("sockname")
    peer = writer.get_extra_info("peername")

    def fmt(sa):
        if sa is None:
            return f"{scheme}://<closed>"
        host, port = sa[0], sa[1]
        return f"{scheme}://{unparse_host_port(host, port)}"

    return fmt(sock), fmt(peer)


class TCPConnector(Connector):
    scheme = "tcp"
    ssl_context = None
    use_ssl = False  # the SCHEME decides: tcp:// never handshakes TLS,
    # even when connection_args carry an ssl_context (a secured client
    # talking to a plain endpoint must not TLS a plaintext listener)

    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm:
        host, port = parse_host_port(address)
        ssl_ctx = (
            kwargs.get("ssl_context", self.ssl_context)
            if self.use_ssl else None
        )
        try:
            reader, writer = await asyncio.open_connection(
                host, port, ssl=ssl_ctx, limit=2**24
            )
        except ConnectionRefusedError as e:
            raise CommClosedError(f"connection refused: {address}") from e
        except (ssl_error_types()) as e:
            raise FatalCommClosedError(f"TLS failure connecting to {address}: {e!r}") from e
        sock = writer.get_extra_info("socket")
        if sock is not None and ssl_ctx is None:
            _set_tcp_options(sock)
        local, peer = _sock_addrs(writer, self.scheme)
        cls = TLS if ssl_ctx is not None else TCP
        return cls(reader, writer, local, f"{self.scheme}://{address}", deserialize)


def ssl_error_types():
    import ssl

    return (ssl.SSLError, ssl.CertificateError)


class TLSConnector(TCPConnector):
    scheme = "tls"
    use_ssl = True

    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm:
        if kwargs.get("ssl_context") is None:
            from distributed_tpu.security import Security

            kwargs["ssl_context"] = Security().get_connection_args("client").get("ssl_context")
        if kwargs["ssl_context"] is None:
            raise FatalCommClosedError("tls:// requires an ssl_context (configure comm.tls)")
        return await super().connect(address, deserialize, **kwargs)


class TCPListener(Listener):
    scheme = "tcp"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        host, port = parse_host_port(loc or "0.0.0.0:0")
        self.host = host or "0.0.0.0"
        self.port = port
        self.handle_comm = handle_comm
        self.deserialize = deserialize
        self.server: asyncio.AbstractServer | None = None
        # scheme decides: a tcp:// listener serves plaintext even when
        # listen_args carry an ssl_context (the address must not lie)
        self.ssl_context = (
            kwargs.get("ssl_context") if self.scheme == "tls" else None
        )
        self._comms: set[Comm] = set()

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None and self.ssl_context is None:
            _set_tcp_options(sock)
        local, peer = _sock_addrs(writer, self.scheme)
        cls = TLS if self.ssl_context is not None else TCP
        comm = cls(reader, writer, local, peer, self.deserialize)
        try:
            await self.on_connection(comm)
        except CommClosedError:
            return
        self._comms.add(comm)
        try:
            await self.handle_comm(comm)
        finally:
            self._comms.discard(comm)

    async def start(self) -> None:
        backlog = config.get("comm.socket-backlog")
        self.server = await asyncio.start_server(
            self._on_connection, self.host, self.port or None,
            ssl=self.ssl_context, backlog=backlog, limit=2**24, reuse_address=True,
        )
        if self.port == 0:
            self.port = self.server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None

    @property
    def listen_address(self) -> str:
        return f"{self.scheme}://{unparse_host_port(self.host, self.port)}"

    @property
    def contact_address(self) -> str:
        host = self.host
        if host in ("0.0.0.0", ""):
            from distributed_tpu.utils import get_ip

            host = get_ip()
        return f"{self.scheme}://{unparse_host_port(host, self.port)}"


class TLSListener(TCPListener):
    scheme = "tls"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        super().__init__(loc, handle_comm, deserialize, **kwargs)
        if self.ssl_context is None:
            from distributed_tpu.security import Security

            self.ssl_context = Security().get_listen_args("scheduler").get("ssl_context")
        if self.ssl_context is None:
            raise ValueError("tls:// listener requires ssl_context (configure comm.tls)")


class TCPBackend(Backend):
    _connector_cls = TCPConnector
    _listener_cls = TCPListener

    def get_connector(self) -> Connector:
        return self._connector_cls()

    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener:
        return self._listener_cls(loc, handle_comm, deserialize, **kwargs)


class TLSBackend(TCPBackend):
    _connector_cls = TLSConnector
    _listener_cls = TLSListener


register_backend("tcp", TCPBackend())
register_backend("tls", TLSBackend())
