"""Address parsing and normalization (reference comm/addressing.py).

Addresses look like ``scheme://host:port`` (``tcp://127.0.0.1:8786``,
``tls://...``, ``inproc://<uuid>/<n>``).  A bare ``host:port`` gets the
configured default scheme.
"""

from __future__ import annotations

from distributed_tpu import config


def parse_address(addr: str, strict: bool = False) -> tuple[str, str]:
    """Split ``scheme://loc`` -> (scheme, loc)."""
    if not isinstance(addr, str):
        raise TypeError(f"expected str address, got {addr!r}")
    if "://" in addr:
        scheme, loc = addr.split("://", 1)
        return scheme, loc
    if strict:
        raise ValueError(f"invalid address {addr!r}: missing scheme")
    return config.get("comm.default-scheme"), addr


def unparse_address(scheme: str, loc: str) -> str:
    return f"{scheme}://{loc}"


def normalize_address(addr: str) -> str:
    return unparse_address(*parse_address(addr))


def parse_host_port(loc: str, default_port: int = 0) -> tuple[str, int]:
    """``host:port`` (with [v6] brackets) -> (host, port)."""
    if loc.startswith("["):  # IPv6
        host, _, rest = loc[1:].partition("]")
        port = int(rest.lstrip(":") or default_port)
        return host, port
    if ":" in loc:
        host, _, port_s = loc.rpartition(":")
        return host, int(port_s or default_port)
    return loc, default_port


def unparse_host_port(host: str, port: int | None = None) -> str:
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"
    return f"{host}:{port}" if port is not None else host


def get_address_host(addr: str) -> str:
    scheme, loc = parse_address(addr)
    if scheme == "inproc":
        return loc.split("/")[0]
    return parse_host_port(loc)[0]


def get_address_host_port(addr: str) -> tuple[str, int]:
    _, loc = parse_address(addr)
    return parse_host_port(loc)


def resolve_address(addr: str) -> str:
    """Resolve hostname to IP, keeping scheme and port."""
    import socket

    scheme, loc = parse_address(addr)
    if scheme == "inproc":
        return addr
    host, port = parse_host_port(loc)
    try:
        host = socket.gethostbyname(host)
    except OSError:
        pass
    return unparse_address(scheme, unparse_host_port(host, port))
