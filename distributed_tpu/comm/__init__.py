from distributed_tpu.comm.addressing import (
    get_address_host,
    get_address_host_port,
    normalize_address,
    parse_address,
    parse_host_port,
    resolve_address,
    unparse_address,
    unparse_host_port,
)
from distributed_tpu.comm.core import (
    Comm,
    Connector,
    Listener,
    backends,
    connect,
    get_backend,
    listen,
    register_backend,
)

__all__ = [
    "Comm", "Connector", "Listener", "connect", "listen",
    "backends", "get_backend", "register_backend",
    "parse_address", "unparse_address", "normalize_address",
    "parse_host_port", "unparse_host_port", "resolve_address",
    "get_address_host", "get_address_host_port",
]
