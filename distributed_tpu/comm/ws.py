"""WebSocket comm backend (reference comm/ws.py).

Minimal RFC 6455 over asyncio streams — no web-framework dependency.
Each protocol message (the same ``dumps`` frame list as tcp) is packed
into ONE binary WebSocket message with internal length prefixes:

    uint64 n_frames, uint64 length[n], frame bytes...

Client->server frames are masked per the RFC; fragmentation uses 8 MiB
continuation frames like the reference's shards (comm/ws.py 8MiB).
Useful where only HTTP-shaped traffic traverses a proxy/ingress.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import os
import struct
from typing import Any, Callable

from distributed_tpu.comm.addressing import parse_host_port, unparse_host_port
from distributed_tpu.comm.core import Backend, Comm, Connector, Listener, register_backend
from distributed_tpu.exceptions import CommClosedError, FatalCommClosedError
from distributed_tpu.protocol import dumps, loads

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
_u64 = struct.Struct("<Q")
FRAGMENT_SIZE = 8 * 2**20  # reference comm/ws.py shards at 8 MiB


def _accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()
    ).decode()


async def _read_ws_message(reader: asyncio.StreamReader,
                           pong: Callable[[bytes], None] | None = None) -> bytes:
    """Read one complete (possibly fragmented) binary message; answers
    pings via ``pong`` (RFC 6455 §5.5.2 — proxies health-check with them)."""
    parts: list[bytes] = []
    while True:
        head = await reader.readexactly(2)
        fin = head[0] & 0x80
        opcode = head[0] & 0x0F
        masked = head[1] & 0x80
        length = head[1] & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", await reader.readexactly(2))
        elif length == 127:
            (length,) = struct.unpack(">Q", await reader.readexactly(8))
        mask = await reader.readexactly(4) if masked else None
        payload = await reader.readexactly(length) if length else b""
        if mask:
            payload = bytes(
                b ^ mask[i % 4] for i, b in enumerate(payload)
            ) if length < 65536 else _unmask(payload, mask)
        if opcode == 0x8:  # close
            raise CommClosedError("ws close frame")
        if opcode == 0x9:  # ping -> pong with the same payload
            if pong is not None:
                pong(payload)
            continue
        if opcode == 0xA:  # pong
            continue
        parts.append(payload)
        if fin:
            return b"".join(parts)


def _unmask(payload: bytes, mask: bytes) -> bytes:
    import numpy as np

    data = np.frombuffer(payload, np.uint8).copy()
    m = np.frombuffer((mask * ((len(payload) + 3) // 4))[: len(payload)], np.uint8)
    return (data ^ m).tobytes()


def _mask_payload(payload: bytes, mask: bytes) -> bytes:
    if len(payload) < 65536:
        return bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return _unmask(payload, mask)  # xor is symmetric


def _ws_frames(payload: bytes, *, mask: bool) -> bytes:
    """Encode one binary message, fragmenting at FRAGMENT_SIZE."""
    out = bytearray()
    offset = 0
    first = True
    total = len(payload)
    while first or offset < total:
        chunk = payload[offset:offset + FRAGMENT_SIZE]
        offset += len(chunk)
        fin = 0x80 if offset >= total else 0
        opcode = 0x2 if first else 0x0
        first = False
        head = bytearray([fin | opcode])
        n = len(chunk)
        mask_bit = 0x80 if mask else 0
        if n < 126:
            head.append(mask_bit | n)
        elif n < 65536:
            head.append(mask_bit | 126)
            head += struct.pack(">H", n)
        else:
            head.append(mask_bit | 127)
            head += struct.pack(">Q", n)
        if mask:
            mkey = os.urandom(4)
            head += mkey
            chunk = _mask_payload(chunk, mkey)
        out += head
        out += chunk
    return bytes(out)


class WS(Comm):
    scheme = "ws"

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 local_addr: str, peer_addr: str, *, is_client: bool,
                 deserialize: bool = True):
        super().__init__(deserialize=deserialize)
        self._reader = reader
        self._writer = writer
        self._local_addr = local_addr
        self._peer_addr = peer_addr
        self._is_client = is_client  # clients mask their frames
        self._closed = False
        self._write_lock = asyncio.Lock()

    def _send_pong(self, payload: bytes) -> None:
        try:
            head = bytearray([0x8A])  # FIN + pong
            n = len(payload)
            if self._is_client:
                head.append(0x80 | n)
                mkey = os.urandom(4)
                head += mkey
                payload = _mask_payload(payload, mkey)
            else:
                head.append(n)
            self._writer.write(bytes(head) + payload)
        except Exception:
            pass

    async def read(self) -> Any:
        try:
            payload = await _read_ws_message(self._reader, pong=self._send_pong)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError,
                CommClosedError) as e:
            self.abort()
            raise CommClosedError(f"ws read failed: {e!r}") from e
        try:
            (n_frames,) = _u64.unpack(payload[:8])
            lengths = struct.unpack_from(f"<{n_frames}Q", payload, 8)
            frames = []
            offset = 8 + 8 * n_frames
            for n in lengths:
                frames.append(payload[offset:offset + n])
                offset += n
            return loads(frames, deserializers=self.deserialize)
        except Exception:
            self.abort()
            raise

    async def write(self, msg: Any, on_error: str = "message") -> int:
        compression = self.handshake_options.get("compression", "auto")
        frames = dumps(msg, compression=compression)
        lengths = [memoryview(f).nbytes for f in frames]
        payload = (
            _u64.pack(len(frames))
            + struct.pack(f"<{len(frames)}Q", *lengths)
            + b"".join(bytes(f) for f in frames)
        )
        encoded = _ws_frames(payload, mask=self._is_client)
        async with self._write_lock:
            try:
                self._writer.write(encoded)
                await self._writer.drain()
            except (ConnectionResetError, BrokenPipeError, RuntimeError,
                    OSError) as e:
                self.abort()
                raise CommClosedError(f"ws write failed: {e!r}") from e
        return len(encoded)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # close frame (masked if client)
            self._writer.write(
                b"\x88\x80" + os.urandom(4) if self._is_client else b"\x88\x00"
            )
            self._writer.close()
            await asyncio.wait_for(self._writer.wait_closed(), 1.0)
        except Exception:
            pass

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._writer.transport.abort()
            except Exception:
                pass

    @property
    def local_address(self) -> str:
        return self._local_addr

    @property
    def peer_address(self) -> str:
        return self._peer_addr

    @property
    def closed(self) -> bool:
        return self._closed or self._reader.at_eof()


class WSListener(Listener):
    prefix = "ws"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        self.loc = loc
        self.handle_comm = handle_comm
        self.deserialize = deserialize
        self._server: asyncio.AbstractServer | None = None
        self.bound_port: int | None = None

    async def start(self) -> None:
        host, port = parse_host_port(self.loc, 0)
        self._server = await asyncio.start_server(
            self._handle_connection, host or "127.0.0.1", port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        try:
            # HTTP upgrade handshake
            request = await asyncio.wait_for(reader.readline(), 10)
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            key = headers.get("sec-websocket-key")
            if not key or b"GET" not in request:
                writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
                await writer.drain()
                writer.close()
                return
            writer.write(
                (
                    "HTTP/1.1 101 Switching Protocols\r\n"
                    "Upgrade: websocket\r\n"
                    "Connection: Upgrade\r\n"
                    f"Sec-WebSocket-Accept: {_accept_key(key)}\r\n"
                    "\r\n"
                ).encode()
            )
            await writer.drain()
        except (asyncio.TimeoutError, OSError):
            writer.close()
            return
        peer = writer.get_extra_info("peername") or ("unknown", 0)
        comm = WS(
            reader, writer,
            local_addr=self.contact_address,
            peer_addr=f"ws://{peer[0]}:{peer[1]}",
            is_client=False,
            deserialize=self.deserialize,
        )
        try:
            await self.on_connection(comm)
        except CommClosedError:
            return
        await self.handle_comm(comm)

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None

    @property
    def listen_address(self) -> str:
        host, _ = parse_host_port(self.loc, 0)
        return f"ws://{unparse_host_port(host or '127.0.0.1', self.bound_port)}"

    @property
    def contact_address(self) -> str:
        return self.listen_address


class WSConnector(Connector):
    async def connect(self, address: str, deserialize: bool = True,
                      **kwargs: Any) -> Comm:
        host, port = parse_host_port(address, 80)
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            raise CommClosedError(f"ws connect to {address} failed: {e}") from e
        key = base64.b64encode(os.urandom(16)).decode()
        writer.write(
            (
                f"GET / HTTP/1.1\r\n"
                f"Host: {host}:{port}\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n"
                "\r\n"
            ).encode()
        )
        await writer.drain()
        status = await reader.readline()
        if b"101" not in status:
            writer.close()
            raise FatalCommClosedError(f"ws handshake rejected: {status!r}")
        accept = None
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin1").partition(":")
            if k.strip().lower() == "sec-websocket-accept":
                accept = v.strip()
        if accept != _accept_key(key):
            writer.close()
            raise FatalCommClosedError("ws handshake: bad accept key")
        sock = writer.get_extra_info("sockname")
        return WS(
            reader, writer,
            local_addr=f"ws://{sock[0]}:{sock[1]}" if sock else "ws://local",
            peer_addr=f"ws://{host}:{port}",
            is_client=True,
            deserialize=deserialize,
        )


class WSBackend(Backend):
    def get_connector(self) -> Connector:
        return WSConnector()

    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener:
        return WSListener(loc, handle_comm, deserialize, **kwargs)

    def get_address_host(self, loc: str) -> str:
        return parse_host_port(loc, 0)[0]

    def get_local_address_for(self, loc: str) -> str:
        return "ws://" + loc


register_backend("ws", WSBackend())
