"""WebSocket comm backend (reference comm/ws.py).

Minimal RFC 6455 over asyncio streams — no web-framework dependency.
Each protocol message (the same ``dumps`` frame list as tcp) is packed
into ONE binary WebSocket message with internal length prefixes:

    uint64 n_frames, uint64 length[n], frame bytes...

Client->server frames are masked per the RFC; fragmentation uses 8 MiB
continuation frames like the reference's shards (comm/ws.py 8MiB).
Useful where only HTTP-shaped traffic traverses a proxy/ingress.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import os
import struct
from typing import Any, Callable

from distributed_tpu.comm.addressing import parse_host_port, unparse_host_port
from distributed_tpu.comm.core import Backend, Comm, Connector, Listener, register_backend
from distributed_tpu.comm.tcp import (
    MAX_FRAME_COUNT,
    readinto_exactly,
    scatter_frames,
)
from distributed_tpu.exceptions import CommClosedError, FatalCommClosedError
from distributed_tpu.protocol import dumps, loads
from distributed_tpu.protocol.buffers import WIRE, max_message_bytes, recv_pool

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
_u64 = struct.Struct("<Q")
FRAGMENT_SIZE = 8 * 2**20  # reference comm/ws.py shards at 8 MiB


def _accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()
    ).decode()


async def _read_ws_message(
    reader: asyncio.StreamReader,
    pong: Callable[[bytes], None] | None = None,
) -> tuple[memoryview, bytearray | None]:
    """Read one complete (possibly fragmented) binary message; answers
    pings via ``pong`` (RFC 6455 §5.5.2 — proxies health-check with them).

    Returns ``(payload_view, pool_buf)``: a single-fragment message —
    the common case, since our own sender only fragments above 8 MiB —
    lands in one pooled buffer via ``readinto`` (unmasked in place);
    ``pool_buf`` must be released by the caller after parsing.
    Fragmented messages gather into one bytearray (``pool_buf`` None).
    """
    parts: bytearray | None = None
    total = 0
    limit = max_message_bytes()
    while True:
        idle = parts is None and total == 0
        try:
            head = await reader.readexactly(2)
        except asyncio.CancelledError as e:
            if idle:
                # readexactly is all-or-nothing: a cancelled wait before
                # any data fragment leaves the stream at a frame
                # boundary — the comm is still usable (teardown paths
                # cancel pending reads on comms they then close cleanly)
                e._dtpu_idle_cancel = True
            raise
        fin = head[0] & 0x80
        opcode = head[0] & 0x0F
        masked = head[1] & 0x80
        length = head[1] & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", await reader.readexactly(2))
        elif length == 127:
            (length,) = struct.unpack(">Q", await reader.readexactly(8))
        if opcode in (0x8, 0x9, 0xA):
            if length > 125:
                # RFC 6455 §5.5: control payloads cap at 125 bytes and
                # never use extended lengths — a longer one is a
                # corrupt/hostile header, not a big message, so it must
                # not reach the readexactly allocation below
                raise CommClosedError(
                    f"ws control frame of {length} bytes"
                )
            mask = await reader.readexactly(4) if masked else None
            payload = await reader.readexactly(length) if length else b""
            if mask:
                # graft-lint: allow[wire-no-copy] tiny control frame; RFC masking is a transform, not a payload copy
                payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            if opcode == 0x8:  # close
                raise CommClosedError("ws close frame")
            if opcode == 0x9 and pong is not None:  # ping -> echo payload
                pong(payload)
            continue
        total += length
        if total > limit:
            raise CommClosedError(
                f"ws message exceeds comm.max-message-bytes ({limit})"
            )
        mask = await reader.readexactly(4) if masked else None
        if parts is None and fin:
            # single-fragment fast path: pooled buffer + readinto,
            # unmasked in place — no per-message allocation, no copy
            buf = recv_pool().acquire(length)
            view = memoryview(buf)[:length]
            try:
                if length:
                    await readinto_exactly(reader, view)
                    if mask:
                        _unmask_into(view, mask)
            except BaseException:
                view = None  # release the export before the pool probe
                recv_pool().release(buf)
                raise
            return view, buf
        payload = await reader.readexactly(length) if length else b""
        if mask:
            payload = _unmask(payload, mask)
        if parts is None:
            parts = bytearray()
        parts += payload
        if fin:
            return memoryview(parts), None


def _unmask(payload: bytes, mask: bytes) -> bytes:
    import numpy as np

    data = np.frombuffer(payload, np.uint8).copy()
    m = np.frombuffer((mask * ((len(payload) + 3) // 4))[: len(payload)], np.uint8)
    return (data ^ m).tobytes()


def _unmask_into(view: memoryview, mask: bytes) -> None:
    """XOR-unmask ``view`` in place (no output allocation)."""
    import numpy as np

    data = np.frombuffer(view, np.uint8)
    m = np.frombuffer((mask * ((len(data) + 3) // 4))[: len(data)], np.uint8)
    data ^= m


def _mask_payload(payload: bytes, mask: bytes) -> bytes:
    # control frames only: payloads are RFC-capped at 125 bytes (the
    # data plane masks in place via _unmask_into)
    # graft-lint: allow[wire-no-copy] tiny control frame; RFC masking is a transform, not a payload copy
    return bytes(b ^ mask[i % 4] for i, b in enumerate(payload))


def _ws_head(flags: int, length: int, mkey: bytes | None) -> bytearray:
    """One WebSocket frame header."""
    head = bytearray([flags])
    mask_bit = 0x80 if mkey is not None else 0
    if length < 126:
        head.append(mask_bit | length)
    elif length < 65536:
        head.append(mask_bit | 126)
        head += struct.pack(">H", length)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", length)
    if mkey is not None:
        head += mkey
    return head


class _PieceCursor:
    """Walk a scatter list as one logical byte string, zero-copy."""

    def __init__(self, bufs: list):
        self._bufs = [
            b if isinstance(b, memoryview) else memoryview(b) for b in bufs
        ]
        self._i = 0
        self._off = 0

    def take(self, n: int) -> list[memoryview]:
        out: list[memoryview] = []
        while n:
            mv = self._bufs[self._i]
            avail = mv.nbytes - self._off
            if not avail:
                self._i += 1
                self._off = 0
                continue
            t = min(avail, n)
            out.append(mv[self._off : self._off + t])
            self._off += t
            n -= t
        return out


class WS(Comm):
    scheme = "ws"

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 local_addr: str, peer_addr: str, *, is_client: bool,
                 deserialize: bool = True):
        super().__init__(deserialize=deserialize)
        self._reader = reader
        self._writer = writer
        self._local_addr = local_addr
        self._peer_addr = peer_addr
        self._is_client = is_client  # clients mask their frames
        self._closed = False
        self._write_lock = asyncio.Lock()

    def _send_pong(self, payload: bytes) -> None:
        try:
            head = bytearray([0x8A])  # FIN + pong
            n = len(payload)
            if self._is_client:
                head.append(0x80 | n)
                mkey = os.urandom(4)
                head += mkey
                payload = _mask_payload(payload, mkey)
            else:
                head.append(n)
            # graft-lint: allow[wire-no-copy] pong control frame is <=125 bytes by RFC
            self._writer.write(bytes(head) + payload)
        except Exception:
            pass

    async def read(self) -> Any:
        payload = pool_buf = ro = frames = None
        try:
            try:
                payload, pool_buf = await _read_ws_message(
                    self._reader, pong=self._send_pong
                )
            except (asyncio.IncompleteReadError, ConnectionResetError, OSError,
                    CommClosedError) as e:
                self.abort()
                raise CommClosedError(f"ws read failed: {e!r}") from e
            except BaseException as e:
                if getattr(e, "_dtpu_idle_cancel", False):
                    raise  # cancelled idle wait — still at a frame boundary
                # anything else (MemoryError from the pool acquire,
                # cancellation mid-frame): ws frame headers are already
                # consumed, so the stream is desynced
                self.abort()
                raise
            try:
                (n_frames,) = _u64.unpack(payload[:8])
                if n_frames > MAX_FRAME_COUNT:
                    raise CommClosedError(f"bad frame count {n_frames}")
                lengths = struct.unpack_from(f"<{n_frames}Q", payload, 8)
                WIRE.bytes_recv += payload.nbytes
                ro = payload.toreadonly()
                frames = []
                offset = 8 + 8 * n_frames
                for n in lengths:
                    frames.append(ro[offset : offset + n])
                    offset += n
                return loads(frames, deserializers=self.deserialize)
            except struct.error as e:
                # corrupt preamble (short payload, bogus counts): same
                # orderly-disconnect surface as the tcp guards
                self.abort()
                raise CommClosedError(f"ws corrupt preamble: {e!r}") from e
            except Exception:
                self.abort()
                raise
        finally:
            # drop our exports, then offer the pooled buffer back (the
            # pool's probe keeps it out of circulation while any
            # deserialized value still views it — docs/wire.md)
            payload = ro = frames = None
            if pool_buf is not None:
                recv_pool().release(pool_buf)

    async def write(self, msg: Any, on_error: str = "message") -> int:
        compression = self.handshake_options.get("compression", "auto")
        frames = dumps(msg, compression=compression)
        bufs, total = scatter_frames(frames)
        cursor = _PieceCursor(bufs)
        n_frag = max(1, -(-total // FRAGMENT_SIZE))
        wire_bytes = 0
        async with self._write_lock:
            try:
                sent = 0
                for i in range(n_frag):
                    frag_len = min(FRAGMENT_SIZE, total - sent)
                    flags = (0x80 if i == n_frag - 1 else 0) | (
                        0x2 if i == 0 else 0x0
                    )
                    pieces = cursor.take(frag_len)
                    if self._is_client:
                        # RFC 6455 client frames mask every byte: the
                        # one place the ws data plane must materialize
                        mkey = os.urandom(4)
                        WIRE.payload_copies += 1
                        import numpy as np

                        # np.empty, not bytearray: no zero-fill memset
                        # of up to 8 MiB that the gather loop below
                        # fully overwrites anyway
                        body = memoryview(np.empty(frag_len, np.uint8))
                        pos = 0
                        for p in pieces:
                            body[pos : pos + p.nbytes] = p
                            pos += p.nbytes
                        _unmask_into(body, mkey)  # xor is symmetric
                        head = _ws_head(flags, frag_len, mkey)
                        self._writer.write(head)
                        self._writer.write(body)
                    else:
                        head = _ws_head(flags, frag_len, None)
                        self._writer.write(head)
                        for p in pieces:
                            self._writer.write(p)
                    wire_bytes += len(head) + frag_len
                    sent += frag_len
                await self._writer.drain()
            except (ConnectionResetError, BrokenPipeError, RuntimeError,
                    OSError) as e:
                self.abort()
                raise CommClosedError(f"ws write failed: {e!r}") from e
        WIRE.bytes_sent += wire_bytes
        return wire_bytes

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            # close frame (masked if client)
            self._writer.write(
                b"\x88\x80" + os.urandom(4) if self._is_client else b"\x88\x00"
            )
            self._writer.close()
            await asyncio.wait_for(self._writer.wait_closed(), 1.0)
        except Exception:
            pass

    def abort(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._writer.transport.abort()
            except Exception:
                pass

    @property
    def local_address(self) -> str:
        return self._local_addr

    @property
    def peer_address(self) -> str:
        return self._peer_addr

    @property
    def closed(self) -> bool:
        return self._closed or self._reader.at_eof()


class WSListener(Listener):
    prefix = "ws"

    def __init__(self, loc: str, handle_comm: Callable, deserialize: bool = True,
                 **kwargs: Any):
        self.loc = loc
        self.handle_comm = handle_comm
        self.deserialize = deserialize
        self._server: asyncio.AbstractServer | None = None
        self.bound_port: int | None = None

    async def start(self) -> None:
        host, port = parse_host_port(self.loc, 0)
        self._server = await asyncio.start_server(
            self._handle_connection, host or "127.0.0.1", port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        try:
            # HTTP upgrade handshake
            request = await asyncio.wait_for(reader.readline(), 10)
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            key = headers.get("sec-websocket-key")
            if not key or b"GET" not in request:
                writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
                await writer.drain()
                writer.close()
                return
            writer.write(
                (
                    "HTTP/1.1 101 Switching Protocols\r\n"
                    "Upgrade: websocket\r\n"
                    "Connection: Upgrade\r\n"
                    f"Sec-WebSocket-Accept: {_accept_key(key)}\r\n"
                    "\r\n"
                ).encode()
            )
            await writer.drain()
        except (asyncio.TimeoutError, OSError):
            writer.close()
            return
        peer = writer.get_extra_info("peername") or ("unknown", 0)
        comm = WS(
            reader, writer,
            local_addr=self.contact_address,
            peer_addr=f"ws://{peer[0]}:{peer[1]}",
            is_client=False,
            deserialize=self.deserialize,
        )
        try:
            await self.on_connection(comm)
        except CommClosedError:
            return
        await self.handle_comm(comm)

    def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None

    @property
    def listen_address(self) -> str:
        host, _ = parse_host_port(self.loc, 0)
        return f"ws://{unparse_host_port(host or '127.0.0.1', self.bound_port)}"

    @property
    def contact_address(self) -> str:
        return self.listen_address


class WSConnector(Connector):
    async def connect(self, address: str, deserialize: bool = True,
                      **kwargs: Any) -> Comm:
        host, port = parse_host_port(address, 80)
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError as e:
            raise CommClosedError(f"ws connect to {address} failed: {e}") from e
        key = base64.b64encode(os.urandom(16)).decode()
        writer.write(
            (
                f"GET / HTTP/1.1\r\n"
                f"Host: {host}:{port}\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n"
                "\r\n"
            ).encode()
        )
        await writer.drain()
        status = await reader.readline()
        if b"101" not in status:
            writer.close()
            raise FatalCommClosedError(f"ws handshake rejected: {status!r}")
        accept = None
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode("latin1").partition(":")
            if k.strip().lower() == "sec-websocket-accept":
                accept = v.strip()
        if accept != _accept_key(key):
            writer.close()
            raise FatalCommClosedError("ws handshake: bad accept key")
        sock = writer.get_extra_info("sockname")
        return WS(
            reader, writer,
            local_addr=f"ws://{sock[0]}:{sock[1]}" if sock else "ws://local",
            peer_addr=f"ws://{host}:{port}",
            is_client=True,
            deserialize=deserialize,
        )


class WSBackend(Backend):
    def get_connector(self) -> Connector:
        return WSConnector()

    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener:
        return WSListener(loc, handle_comm, deserialize, **kwargs)

    def get_address_host(self, loc: str) -> str:
        return parse_host_port(loc, 0)[0]

    def get_local_address_for(self, loc: str) -> str:
        return "ws://" + loc


register_backend("ws", WSBackend())
