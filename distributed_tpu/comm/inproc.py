"""In-process comm: queue pairs between objects in one process.

Reference comm/inproc.py: no serialization, messages pass by reference
through a pair of deques with asyncio wakeups.  Used by
``LocalCluster(processes=False)`` and unit tests.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import threading
import uuid
import weakref
from collections import deque
from typing import Any, Callable

from distributed_tpu.comm.core import Backend, Comm, Connector, Listener, register_backend
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.protocol.serialize import nested_deserialize

_counter = itertools.count()
_namespace = f"{os.getpid()}/{uuid.uuid4().hex[:8]}"

_listeners: "weakref.WeakValueDictionary[str, InProcListener]" = weakref.WeakValueDictionary()
_lock = threading.Lock()


def new_address() -> str:
    return f"inproc://{_namespace}/{next(_counter)}"


class _Channel:
    """One direction: a deque + event for the reader.

    The writer may live on a different thread/loop (sync Client inside a
    worker task, LoopRunner threads): waking the reader must then go
    through ``call_soon_threadsafe`` — a bare ``Event.set()`` from a
    foreign thread never wakes the waiting loop.
    """

    def __init__(self):
        self.queue: deque = deque()
        self.event = asyncio.Event()
        self.closed = False
        self._reader_loop: asyncio.AbstractEventLoop | None = None

    def _wake(self) -> None:
        loop = self._reader_loop
        if loop is None or loop.is_closed():
            self.event.set()
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            self.event.set()
        else:
            try:
                loop.call_soon_threadsafe(self.event.set)
            except RuntimeError:
                pass  # reader loop shut down

    def put(self, msg: Any) -> None:
        self.queue.append(msg)
        self._wake()

    async def get(self):
        self._reader_loop = asyncio.get_running_loop()
        while not self.queue:
            if self.closed:
                raise CommClosedError("inproc channel closed")
            self.event.clear()
            await self.event.wait()
        return self.queue.popleft()

    def close(self) -> None:
        self.closed = True
        self._wake()


class InProc(Comm):
    # both endpoints live in this process: connect()/on_connection skip
    # the handshake message exchange entirely
    same_process = True

    def __init__(self, local_addr: str, peer_addr: str, read_q: _Channel,
                 write_q: _Channel, deserialize: bool = True):
        super().__init__(deserialize=deserialize)
        self._local_addr = local_addr
        self._peer_addr = peer_addr
        self._read_q = read_q
        self._write_q = write_q
        self._closed = False

    async def read(self) -> Any:
        if self._closed:
            raise CommClosedError("comm closed")
        msg = await self._read_q.get()
        if msg is _CLOSE:
            self._closed = True
            raise CommClosedError("peer closed the comm")
        # Serialize leaves pass by reference; unwrap for parity with
        # networked comms (reference inproc.py same behavior).
        # nested_deserialize is copy-on-write, so payloads BELOW the top
        # level may be the sender's own objects — receivers treat message
        # contents as read-only (the reference shares leaves the same
        # way).  The top level is always copied here: handle_stream pops
        # "op" from each message, and broadcast paths (report, pubsub)
        # send one dict to many inproc streams.
        if self.deserialize:
            msg = nested_deserialize(msg)
        if type(msg) is list:
            msg = [dict(m) if type(m) is dict else m for m in msg]
        elif type(msg) is dict:
            msg = dict(msg)
        return msg

    async def write(self, msg: Any, on_error: str = "message") -> int:
        if self._closed or self._write_q.closed:
            raise CommClosedError("comm closed")
        self._write_q.put(msg)
        return 1

    async def close(self) -> None:
        self.abort()

    def abort(self) -> None:
        if not self._closed:
            self._write_q.put(_CLOSE)
            self._write_q.close()
            self._read_q.close()
            self._closed = True

    @property
    def local_address(self) -> str:
        return self._local_addr

    @property
    def peer_address(self) -> str:
        return self._peer_addr

    @property
    def closed(self) -> bool:
        return self._closed


_CLOSE = object()


class InProcListener(Listener):
    def __init__(self, loc: str | None, handle_comm: Callable, deserialize: bool = True):
        self.loc = loc or f"{_namespace}/{next(_counter)}"
        self.handle_comm = handle_comm
        self.deserialize = deserialize
        self._loop: asyncio.AbstractEventLoop | None = None

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        with _lock:
            _listeners[self.loc] = self

    def stop(self) -> None:
        with _lock:
            _listeners.pop(self.loc, None)

    async def _accept(self, comm: InProc) -> None:
        try:
            await self.on_connection(comm)
        except CommClosedError:
            return
        await self.handle_comm(comm)

    def connect_threadsafe(self, client_comm_factory) -> InProc:
        """Called from the connector (possibly another thread/loop)."""
        a2b = _Channel()
        b2a = _Channel()
        addr = f"inproc://{self.loc}"
        server_comm = InProc(addr, new_address(), a2b, b2a, self.deserialize)
        client_comm = client_comm_factory(server_comm.peer_address, addr, b2a, a2b)
        assert self._loop is not None
        self._loop.call_soon_threadsafe(
            lambda: self._loop.create_task(self._accept(server_comm))
        )
        return client_comm

    @property
    def listen_address(self) -> str:
        return f"inproc://{self.loc}"

    contact_address = listen_address


class InProcConnector(Connector):
    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm:
        with _lock:
            listener = _listeners.get(address)
        if listener is None:
            raise CommClosedError(f"no inproc listener at {address!r}")
        comm = listener.connect_threadsafe(
            lambda local, peer, rq, wq: InProc(local, peer, rq, wq, deserialize)
        )
        return comm


class InProcBackend(Backend):
    def get_connector(self) -> Connector:
        return InProcConnector()

    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener:
        return InProcListener(loc or None, handle_comm, deserialize)

    def get_address_host(self, loc: str) -> str:
        return loc.split("/")[0]

    def get_local_address_for(self, loc: str) -> str:
        return new_address()


register_backend("inproc", InProcBackend())
