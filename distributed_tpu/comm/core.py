"""Comm abstraction: message-oriented async channels.

The shape follows the reference (comm/core.py): an abstract ``Comm`` whose
``read``/``write`` carry *messages* (arbitrary msgpack-able structures with
``Serialize`` leaves), not bytes; ``Listener``/``Connector`` per scheme in a
registry; ``connect()`` with retry/backoff and a version/compression
handshake; ``listen()``.

Backends in this package:

- ``tcp://`` / ``tls://`` — asyncio streams (comm/tcp.py).  The reference
  uses tornado IOStream; asyncio's loop is the idiomatic substrate here and
  removes the tornado dependency.
- ``inproc://``           — in-process queue pairs (comm/inproc.py)

The TPU data plane does NOT go through these comms: bulk array movement
between chips rides XLA collectives over ICI (see shuffle/ and parallel/),
exactly as the reference routes bulk GPU traffic over UCX instead of its
TCP control plane.  Comms carry control messages and host-side data.
"""

from __future__ import annotations

import asyncio
import logging
import random
from abc import ABC, abstractmethod
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.exceptions import CommClosedError, FatalCommClosedError

logger = logging.getLogger("distributed_tpu.comm")


class Comm(ABC):
    """A message-oriented bidirectional channel."""

    _instances: "set[Comm]" = set()

    def __init__(self, deserialize: bool = True):
        self.deserialize = deserialize
        self.name: str | None = None
        self.handshake_options: dict = {}
        Comm._instances.add(self)

    @abstractmethod
    async def read(self) -> Any:
        """Read one message; raises CommClosedError on a closed comm."""

    @abstractmethod
    async def write(self, msg: Any, on_error: str = "message") -> int:
        """Write one message; returns bytes written."""

    @abstractmethod
    async def close(self) -> None:
        """Flush and close."""

    @abstractmethod
    def abort(self) -> None:
        """Close immediately, discarding buffered data."""

    @property
    @abstractmethod
    def local_address(self) -> str: ...

    @property
    @abstractmethod
    def peer_address(self) -> str: ...

    @property
    @abstractmethod
    def closed(self) -> bool: ...

    # -------------------------------------------------------- handshake

    @staticmethod
    def handshake_info() -> dict:
        from distributed_tpu import __version__
        from distributed_tpu.protocol.compression import get_default_compression

        return {
            "compression": get_default_compression()
            if config.get("comm.compression")
            else None,
            "python": tuple(__import__("sys").version_info[:3]),
            "pickle-protocol": 5,
            "version": __version__,
        }

    @staticmethod
    def handshake_configuration(local: dict, remote: dict) -> dict:
        """Negotiate: no compression unless both ends support it."""
        out = {
            "pickle-protocol": min(
                local.get("pickle-protocol", 5), remote.get("pickle-protocol", 5)
            )
        }
        if local.get("compression") == remote.get("compression"):
            out["compression"] = local.get("compression")
        else:
            out["compression"] = None
        return out

    def __repr__(self) -> str:
        clsname = type(self).__name__
        state = " [closed]" if self.closed else ""
        return f"<{clsname}{state} local={self.local_address} remote={self.peer_address}>"


class Listener(ABC):
    @abstractmethod
    async def start(self) -> None: ...

    @abstractmethod
    def stop(self) -> None: ...

    @property
    @abstractmethod
    def listen_address(self) -> str: ...

    @property
    @abstractmethod
    def contact_address(self) -> str: ...

    async def __aenter__(self):
        await self.start()
        return self

    async def __aexit__(self, *exc):
        self.stop()

    async def on_connection(self, comm: Comm) -> None:
        """Server side of the handshake."""
        if getattr(comm, "same_process", False):
            # inproc: both ends share this process — there is nothing to
            # negotiate, and the two-message exchange costs two loop
            # round trips per connection (a 128-worker shuffle opens
            # ~16k pair comms; the handshake storm alone was ~2 s of
            # loop time on the config-4 bench)
            local = Comm.handshake_info()
            comm.remote_info = local
            comm.local_info = local
            comm.handshake_options = Comm.handshake_configuration(local, local)
            return
        try:
            local = Comm.handshake_info()
            timeout = config.parse_timedelta(config.get("comm.timeouts.connect"))
            write = asyncio.create_task(comm.write(local))
            remote = await asyncio.wait_for(comm.read(), timeout)
            await asyncio.wait_for(write, timeout)
        except Exception as e:
            with _ignoring():
                await comm.close()
            raise CommClosedError(f"handshake failed: {e!r}") from e
        comm.remote_info = remote
        comm.local_info = local
        comm.handshake_options = Comm.handshake_configuration(local, remote)


class Connector(ABC):
    @abstractmethod
    async def connect(self, address: str, deserialize: bool = True, **kwargs: Any) -> Comm: ...


class Backend(ABC):
    """Scheme entry: produces connectors/listeners and address helpers."""

    @abstractmethod
    def get_connector(self) -> Connector: ...

    @abstractmethod
    def get_listener(self, loc: str, handle_comm: Callable, deserialize: bool,
                     **kwargs: Any) -> Listener: ...

    def get_address_host(self, loc: str) -> str:
        from distributed_tpu.comm.addressing import parse_host_port

        return parse_host_port(loc)[0]

    def resolve_address(self, loc: str) -> str:
        return loc

    def get_local_address_for(self, loc: str) -> str:
        from distributed_tpu.utils import get_ip

        return get_ip()


backends: dict[str, Backend] = {}


def register_backend(scheme: str, backend: Backend) -> None:
    backends[scheme] = backend


def get_backend(scheme: str) -> Backend:
    _ensure_default_backends()
    try:
        return backends[scheme]
    except KeyError:
        raise ValueError(
            f"unknown address scheme {scheme!r} (known: {sorted(backends)})"
        ) from None


_defaults_loaded = False


def _ensure_default_backends() -> None:
    global _defaults_loaded
    if _defaults_loaded:
        return
    _defaults_loaded = True
    import distributed_tpu.comm.inproc  # noqa: F401 registers inproc
    import distributed_tpu.comm.tcp  # noqa: F401 registers tcp/tls
    import distributed_tpu.comm.ws  # noqa: F401 registers ws


from contextlib import contextmanager


@contextmanager
def _ignoring():
    try:
        yield
    except Exception:
        pass


async def connect(
    addr: str,
    timeout: float | None = None,
    deserialize: bool = True,
    handshake_overrides: dict | None = None,
    **connection_args: Any,
) -> Comm:
    """Connect with exponential backoff until ``timeout`` (reference
    comm/core.py:309)."""
    from distributed_tpu.comm.addressing import parse_address

    if timeout is None:
        timeout = config.parse_timedelta(config.get("comm.timeouts.connect"))
    scheme, loc = parse_address(addr)
    connector = get_backend(scheme).get_connector()

    deadline = asyncio.get_running_loop().time() + timeout
    backoff = 0.01
    error = None
    while True:
        try:
            comm = await asyncio.wait_for(
                connector.connect(loc, deserialize=deserialize, **connection_args),
                max(0.05, deadline - asyncio.get_running_loop().time()),
            )
            break
        except FatalCommClosedError:
            raise
        except (asyncio.TimeoutError, OSError, CommClosedError) as e:
            error = e
            if asyncio.get_running_loop().time() >= deadline:
                raise OSError(
                    f"Timed out trying to connect to {addr} after {timeout} s: {error!r}"
                ) from error
            await asyncio.sleep(backoff * (1 + random.random()))
            backoff = min(backoff * 1.5, 1.0)

    # client side of the handshake
    if getattr(comm, "same_process", False):
        # see Listener.on_connection: inproc skips the exchange on BOTH
        # sides unconditionally (a one-sided skip would deadlock), so
        # handshake_overrides cannot apply to inproc comms
        local = Comm.handshake_info()
        if handshake_overrides:
            local.update(handshake_overrides)
        comm.remote_info = local
        comm.local_info = local
        comm.handshake_options = Comm.handshake_configuration(local, local)
        return comm
    try:
        local = Comm.handshake_info()
        if handshake_overrides:
            local.update(handshake_overrides)
        write = asyncio.create_task(comm.write(local))
        remote = await asyncio.wait_for(
            comm.read(), max(0.05, deadline - asyncio.get_running_loop().time())
        )
        await write
    except Exception as e:
        with _ignoring():
            comm.abort()
        raise OSError(f"connection to {addr} failed during handshake: {e!r}") from e
    comm.remote_info = remote
    comm.local_info = local
    comm.handshake_options = Comm.handshake_configuration(local, remote)
    return comm


def listen(
    addr: str,
    handle_comm: Callable,
    deserialize: bool = True,
    **kwargs: Any,
) -> Listener:
    """Create (not start) a listener on ``addr``: ``handle_comm(comm)`` is
    spawned per accepted connection after the handshake."""
    from distributed_tpu.comm.addressing import parse_address

    scheme, loc = parse_address(addr, strict=False)
    backend = get_backend(scheme)
    return backend.get_listener(loc, handle_comm, deserialize, **kwargs)
