"""Worker memory management (reference worker_memory.py).

``WorkerMemoryManager`` polls every 100 ms and applies the four-threshold
model (reference distributed.yaml:155-160):

- target   (0.60 of memory_limit): spill by *managed* bytes — evict the
  spill buffer's fast layer down to the budget
- spill    (0.70): spill by *process* memory (RSS)
- pause    (0.80): stop executing / fetching; announce 'paused' to the
  scheduler, which takes the worker out of the running pool
- terminate(0.95): enforced from *outside* the process by the Nanny
  (``NannyMemoryManager``, reference worker_memory.py:368) — the worker
  itself may be too wedged to act.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any

from distributed_tpu import config
from distributed_tpu.rpc.core import PeriodicCallback

if TYPE_CHECKING:
    from distributed_tpu.worker.nanny import Nanny
    from distributed_tpu.worker.server import Worker

logger = logging.getLogger("distributed_tpu.worker.memory")
# the monitor re-evaluates every 100 ms: without a limiter a worker
# camped over the spill threshold logs the same line 10x/s
# (reference utils.py RateLimiterFilter, applied the same way)
from distributed_tpu.utils.misc import RateLimiterFilter  # noqa: E402

logger.addFilter(RateLimiterFilter(r"> spill threshold", rate=10.0))


def _process_rss() -> int:
    try:
        import psutil

        return psutil.Process().memory_info().rss
    except Exception:
        return 0


class WorkerMemoryManager:
    """In-process thresholds: spill / pause (reference worker_memory.py:74)."""

    def __init__(self, worker: "Worker", memory_limit: int):
        self.worker = worker
        self.memory_limit = memory_limit
        mem_cfg = config.get("worker.memory")
        self.target = mem_cfg["target"]
        self.spill = mem_cfg["spill"]
        self.pause = mem_cfg["pause"]
        self.monitor_interval = config.parse_timedelta(
            mem_cfg["monitor-interval"]
        )
        self._paused = False
        self.pc = PeriodicCallback(self.check, self.monitor_interval)
        worker.periodic_callbacks["memory-manager"] = self.pc

    async def check(self) -> None:
        if not self.memory_limit:
            return
        worker = self.worker
        data = worker.data
        # spill by managed memory
        if (
            self.target
            and hasattr(data, "evict")
            and getattr(data, "fast_bytes", 0) > self.target * self.memory_limit
        ):
            await self._spill_to(self.target * self.memory_limit)
        # spill + pause by process memory
        rss = _process_rss()
        frac = rss / self.memory_limit
        if (
            self.spill
            and frac > self.spill
            and hasattr(data, "evict")
            # only if there is actually managed memory left to free —
            # unmanaged RSS pressure can't be spilled and would spam logs
            and getattr(data, "fast_bytes", 0)
            > self.target * self.memory_limit * 0.8
        ):
            logger.info(
                "process memory %.0f%% > spill threshold; spilling", frac * 100
            )
            await self._spill_to(self.target * self.memory_limit * 0.8)
        if self.pause and frac > self.pause and not self._paused:
            self._paused = True
            logger.warning(
                "process memory %.0f%% > pause threshold; pausing worker",
                frac * 100,
            )
            self._set_status("paused")
        elif self._paused and frac < self.pause * 0.95:
            self._paused = False
            logger.info("memory recovered; unpausing worker")
            self._set_status("running")

    async def _spill_to(self, budget: float) -> None:
        data = self.worker.data
        import asyncio

        count = 0
        while getattr(data, "fast_bytes", 0) > budget:
            freed = data.evict()
            if freed < 0:
                break
            count += 1
            if count % 8 == 0:
                await asyncio.sleep(0)  # yield the loop during long spills
        if count:
            logger.info("spilled %d keys to disk", count)

    def _set_status(self, status: str) -> None:
        from distributed_tpu.utils.misc import seq_name
        from distributed_tpu.worker.state_machine import PauseEvent, UnpauseEvent

        worker = self.worker
        stimulus_id = seq_name("memory-monitor")
        # the seq is bumped BEFORE either send path: the stream message
        # and every later heartbeat carry the same ordering stamp
        worker._status_seq += 1
        worker.handle_stimulus(
            PauseEvent(stimulus_id=stimulus_id)
            if status == "paused"
            else UnpauseEvent(stimulus_id=stimulus_id)
        )
        try:
            worker.batched_stream.send(
                {"op": "worker-status-change", "status": status,
                 "status_seq": worker._status_seq,
                 "stimulus_id": stimulus_id}
            )
        except Exception:
            # the batched stream may not exist yet at startup — the pause
            # still applies locally and the next heartbeat reconciles
            logger.debug("status-change send failed (stream not up yet)",
                         exc_info=True)


class NannyMemoryManager:
    """Out-of-process terminate enforcement (reference worker_memory.py:368)."""

    def __init__(self, nanny: "Nanny", memory_limit: int):
        self.nanny = nanny
        self.memory_limit = memory_limit
        mem_cfg = config.get("worker.memory")
        self.terminate = mem_cfg["terminate"]
        self.pc = PeriodicCallback(
            self.check, config.parse_timedelta(mem_cfg["monitor-interval"])
        )
        nanny.periodic_callbacks["memory-manager"] = self.pc

    async def check(self) -> None:
        if not self.memory_limit or not self.terminate:
            return
        process = self.nanny.process
        if process is None or not process.is_alive() or process.pid is None:
            return
        try:
            import psutil

            rss = psutil.Process(process.pid).memory_info().rss
        except Exception:
            return
        if rss > self.terminate * self.memory_limit:
            logger.warning(
                "worker %s rss %.0f MiB exceeded terminate threshold; killing",
                self.nanny.worker_address, rss / 2**20,
            )
            await process.kill()  # exit callback triggers the auto-restart
