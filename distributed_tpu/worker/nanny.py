"""Nanny: supervises a Worker subprocess (reference nanny.py).

The Nanny is a small Server that spawns the real Worker in a child
process (spawn context), reports its address back, restarts it when it
dies unexpectedly (reference ``_on_worker_exit`` nanny.py:546), and kills
it with escalation (graceful close -> SIGTERM -> SIGKILL, nanny.py:393).
Scheduler-initiated restarts go through the ``restart``/``kill`` RPCs.
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing
import os
from typing import Any

from distributed_tpu import config
from distributed_tpu.rpc.core import Server, Status
from distributed_tpu.worker.process import AsyncProcess

logger = logging.getLogger("distributed_tpu.nanny")


def _run_worker_process(scheduler_addr: str, worker_kwargs: dict,
                        env: dict, q: multiprocessing.Queue) -> None:
    """Child-process entry: run a Worker until it closes."""
    for k, v in env.items():
        os.environ[k] = str(v)

    import asyncio as _asyncio

    async def main() -> None:
        from distributed_tpu.worker.server import Worker

        worker = Worker(scheduler_addr, **worker_kwargs)
        try:
            await worker.start()
        except Exception as e:  # startup failure: tell the parent
            q.put({"op": "start-failed", "error": repr(e)})
            raise
        q.put({"op": "started", "address": worker.address})
        await worker.finished()

    try:
        _asyncio.run(main())
    except KeyboardInterrupt:
        pass


class Nanny(Server):
    """Worker supervisor process (reference nanny.py:69)."""

    blocked_handlers_config_key = "nanny.blocked-handlers"
    preload_config_prefix = "nanny"

    def __init__(
        self,
        scheduler_addr: str,
        *,
        nthreads: int = 1,
        name: object = None,
        memory_limit: int = 0,
        auto_restart: bool = True,
        worker_kwargs: dict | None = None,
        env: dict | None = None,
        listen_addr: str | None = None,
        lifetime: float | None = None,
        lifetime_stagger: float | None = None,
        lifetime_restart: bool | None = None,
        security: Any | None = None,
        **server_kwargs: Any,
    ):
        self.security = security
        if security is not None:
            # the nanny's own control channel (kill/restart/terminate)
            # and its scheduler rpc must ride TLS like everything else
            server_kwargs.setdefault(
                "connection_args", security.get_connection_args("worker")
            )
        self.scheduler_addr = scheduler_addr
        self.nthreads = nthreads
        self.worker_name = name
        self.memory_limit = memory_limit
        self.auto_restart = auto_restart
        from distributed_tpu.worker import resolve_lifetime

        self.lifetime, self.lifetime_stagger, self.lifetime_restart = (
            resolve_lifetime(lifetime, lifetime_stagger, lifetime_restart)
        )
        self._lifetime_task: Any | None = None
        self.env = dict(config.get("nanny.environ") or {})
        self.env.update(env or {})
        self.worker_kwargs = dict(worker_kwargs or {})
        self._listen_addr = listen_addr
        self.process: AsyncProcess | None = None
        self.worker_address: str | None = None
        self._start_queue: multiprocessing.Queue | None = None
        self._restart_attempts = 0
        self.MAX_RESTART_ATTEMPTS = 3

        handlers = {
            "instantiate": self.instantiate_rpc,
            "kill": self.kill_rpc,
            "restart": self.restart_rpc,
            "terminate": self.close_rpc,
            "worker_address": self.get_worker_address,
            "run": self.run_function,
            "plugin_add": self.plugin_add,
            "plugin_remove": self.plugin_remove,
        }
        self.plugins: dict[str, Any] = {}
        self._local_directory: Any | None = None
        super().__init__(handlers=handlers, name=name, **server_kwargs)

    @property
    def local_directory(self) -> str:
        """Per-nanny scratch directory (lazy WorkSpace claim) — the
        extraction target for NannyPlugins like UploadDirectory, kept
        out of the process CWD and purged when stale."""
        if self._local_directory is None:
            from distributed_tpu.utils.diskutils import WorkSpace

            self._local_directory = WorkSpace().new_work_dir(prefix="nanny")
        return self._local_directory.path

    # ------------------------------------------------------------ lifecycle

    async def start_unsafe(self) -> "Nanny":
        addr = self._listen_addr or (
            "tls://127.0.0.1:0" if self.security is not None
            else "tcp://127.0.0.1:0"
        )
        listen_args = (
            self.security.get_listen_args("worker")
            if self.security is not None else {}
        )
        await self.listen(addr, **listen_args)
        await self.instantiate()
        if self.memory_limit:
            from distributed_tpu.worker.memory import NannyMemoryManager

            self.memory_manager = NannyMemoryManager(self, self.memory_limit)
        if self.lifetime:
            self._lifetime_task = asyncio.create_task(self._lifetime_loop())
        self.start_periodic_callbacks()
        return self

    async def _lifetime_loop(self) -> None:
        """Bounded worker lifetime (reference dask-worker --lifetime):
        after ``lifetime`` (± a uniform stagger so a fleet doesn't cycle
        in lock-step), the worker is retired gracefully; with
        ``lifetime_restart`` a fresh one is spawned, else the nanny shuts
        down.  The tool for bounded-preemption environments."""
        from distributed_tpu.worker import sample_lifetime_delay

        while True:
            delay = sample_lifetime_delay(self.lifetime, self.lifetime_stagger)
            await asyncio.sleep(delay)
            logger.info(
                "worker %s reached its lifetime (%.0fs); %s",
                self.worker_address, delay,
                "restarting" if self.lifetime_restart else "retiring",
            )
            # disarm auto-restart FIRST: retire_workers terminates the
            # worker over RPC, and an armed exit callback would race this
            # loop to spawn a second (or zombie) worker
            if self.process is not None:
                self.process.set_exit_callback(lambda code: None)
            try:
                # retire first: the scheduler replicates unique data away
                # and reschedules queued work before the process dies
                if self.worker_address:
                    await self.rpc(self.scheduler_addr).retire_workers(
                        workers=[self.worker_address]
                    )
            except Exception:
                logger.warning("lifetime retire failed", exc_info=True)
            try:
                await self.kill(graceful=True)
            except Exception:
                logger.exception("lifetime kill failed")
            if not self.lifetime_restart:
                self._ongoing_background_tasks.call_soon(self.close)
                return
            # bounded retry with backoff, like the crash-restart path —
            # a single transient spawn failure must not leave a zombie
            # nanny supervising nothing
            for attempt in range(1, self.MAX_RESTART_ATTEMPTS + 1):
                try:
                    await self.instantiate()
                    break
                except Exception:
                    logger.exception(
                        "lifetime restart failed (attempt %d/%d)",
                        attempt, self.MAX_RESTART_ATTEMPTS,
                    )
                    if attempt < self.MAX_RESTART_ATTEMPTS:
                        await asyncio.sleep(0.5 * attempt)
            else:
                self.status = Status.failed
                self._ongoing_background_tasks.call_soon(self.close)
                return

    async def instantiate(self, timeout: float = 60.0) -> str:
        """Spawn the worker subprocess, wait for its address
        (reference nanny.py:363 / WorkerProcess.start nanny.py:708)."""
        ctx = multiprocessing.get_context("spawn")
        q: multiprocessing.Queue = ctx.Queue()
        self._start_queue = q
        kwargs = dict(self.worker_kwargs)
        kwargs.setdefault("nthreads", self.nthreads)
        kwargs.setdefault("name", self.worker_name)
        kwargs.setdefault("memory_limit", self.memory_limit)
        # the NANNY owns the lifetime (it can restart); zero the child's
        # own config-read timer or both would fire independently
        kwargs.setdefault("lifetime", 0)
        kwargs.setdefault("nanny_addr", self.address)
        if self.security is not None:
            kwargs.setdefault("security", self.security)
        env = dict(config.get("nanny.pre-spawn-environ") or {})
        env.update(self.env)
        self.process = AsyncProcess(
            target=_run_worker_process,
            args=(self.scheduler_addr, kwargs, env, q),
            name=f"dtpu-worker-{self.worker_name or self.id}",
        )
        self.process.set_exit_callback(self._on_worker_exit)
        await self.process.start()
        loop = asyncio.get_running_loop()
        import queue as _queue

        # q.get with its own timeout so the executor thread always exits
        def _get_startup_msg():
            try:
                return q.get(timeout=timeout)
            except _queue.Empty:
                return None

        msg = await loop.run_in_executor(None, _get_startup_msg)
        if msg is None:
            # child hung during startup: reap it, don't leak the process
            self.process.set_exit_callback(lambda code: None)
            await self.process.kill()
            raise TimeoutError(
                f"worker did not start within {timeout}s; killed pid "
                f"{self.process.pid}"
            )
        if msg.get("op") != "started":
            # disarm auto-restart: the caller decides what happens next
            self.process.set_exit_callback(lambda code: None)
            raise RuntimeError(f"worker failed to start: {msg!r}")
        self._restart_attempts = 0
        self.worker_address = msg["address"]
        logger.info(
            "nanny %s started worker %s (pid %s)",
            self.address, self.worker_address, self.process.pid,
        )
        return self.worker_address

    def _on_worker_exit(self, exitcode: int | None) -> None:
        """The worker process died (reference nanny.py:546)."""
        if self.status in (Status.closing, Status.closed, Status.failed):
            return
        logger.warning(
            "worker process %s exited with code %s", self.worker_address, exitcode
        )
        if self.auto_restart:
            logger.info("nanny restarting worker")
            self._ongoing_background_tasks.call_soon(self._restart_on_exit)

    async def _restart_on_exit(self) -> None:
        self._restart_attempts += 1
        if self._restart_attempts > self.MAX_RESTART_ATTEMPTS:
            logger.error(
                "worker failed to start %d times; nanny giving up",
                self._restart_attempts - 1,
            )
            self.status = Status.failed
            return
        await asyncio.sleep(0.5 * self._restart_attempts)  # backoff
        try:
            await self.instantiate()
        except Exception:
            logger.exception("nanny failed to restart worker")
            self._on_worker_exit(None)

    async def kill(self, timeout: float = 5.0, *, graceful: bool = True) -> None:
        """Stop the worker with escalation (reference nanny.py:393)."""
        process = self.process
        if process is None or not process.is_alive():
            return
        process.set_exit_callback(lambda code: None)  # no auto-restart
        if graceful and self.worker_address:
            from distributed_tpu.exceptions import CommClosedError

            try:
                await asyncio.wait_for(
                    self.rpc(self.worker_address).terminate(), timeout / 2
                )
            except (CommClosedError, OSError, asyncio.TimeoutError, RuntimeError):
                pass
        try:
            await asyncio.wait_for(process.join(), timeout / 2)
            return
        except asyncio.TimeoutError:
            pass
        await process.terminate()
        try:
            await asyncio.wait_for(process.join(), timeout / 2)
            return
        except asyncio.TimeoutError:
            pass
        logger.warning("escalating to SIGKILL for pid %s", process.pid)
        await process.kill()
        await process.join()

    async def restart(self, timeout: float = 30.0) -> str:
        await self.kill(timeout=timeout / 2)
        return await self.instantiate(timeout=timeout)

    async def close(self, timeout: float | None = None) -> None:
        if self.status in (Status.closed, Status.closing):
            await self.finished()
            return
        self.status = Status.closing
        await self._teardown_config_preloads()
        logger.info("closing nanny %s", self.address)
        if self._lifetime_task is not None:
            self._lifetime_task.cancel()
            self._lifetime_task = None
        await self.kill()
        await super().close()

    # ------------------------------------------------------------- handlers

    async def instantiate_rpc(self) -> str:
        return await self.instantiate()

    async def kill_rpc(self, timeout: float = 5.0) -> str:
        await self.kill(timeout=timeout)
        return "OK"

    async def restart_rpc(self, timeout: float = 30.0) -> str:
        await self.restart(timeout=timeout)
        return "OK"

    async def close_rpc(self, reason: str = "") -> str:
        self._ongoing_background_tasks.call_soon(self.close)
        return "OK"

    async def run_function(self, function: Any = None, args: Any = None,
                           kwargs: Any = None, wait: bool = True) -> Any:
        """Run an arbitrary function on this nanny (client.run(nanny=True),
        reference nanny run handler)."""
        from distributed_tpu.rpc.core import run_user_function

        return await run_user_function(
            self, "dtpu_nanny", function, args, kwargs, wait
        )

    async def plugin_add(self, plugin: Any = None, name: str = "") -> dict:
        """Install a NannyPlugin (reference nanny.py plugin_add):
        idempotent per name (the scheduler re-pushes its plugin set on
        every worker registration), and honors ``plugin.restart`` by
        cycling the worker process so the change reaches the child."""
        from distributed_tpu.protocol.serialize import unwrap
        from distributed_tpu.rpc.core import error_message

        plugin = unwrap(plugin)
        name = name or getattr(plugin, "name", type(plugin).__name__)
        if name in self.plugins:
            return {"status": "OK"}
        self.plugins[name] = plugin
        try:
            setup = getattr(plugin, "setup", None)
            if setup is not None:
                res = setup(self)
                if asyncio.iscoroutine(res):
                    await res
            if getattr(plugin, "restart", False):
                await self.kill(graceful=True)
                await self.instantiate()
        except Exception as e:
            return error_message(e)
        return {"status": "OK"}

    async def plugin_remove(self, name: str = "") -> dict:
        """Uninstall a NannyPlugin (teardown hook honored)."""
        from distributed_tpu.rpc.core import error_message

        plugin = self.plugins.pop(name, None)
        try:
            teardown = getattr(plugin, "teardown", None)
            if teardown is not None:
                res = teardown(self)
                if asyncio.iscoroutine(res):
                    await res
        except Exception as e:
            return error_message(e)
        return {"status": "OK"}

    async def get_worker_address(self) -> str | None:
        return self.worker_address

    def __repr__(self) -> str:
        return f"<Nanny worker={self.worker_address!r} status={self.status.name}>"
