"""Fine-grained performance metrics (reference metrics.py:159 ContextMeter).

``context_meter.meter("label")`` brackets a block and reports its wall
seconds to every callback installed on the current (context-local)
stack; ``digest_metric`` reports arbitrary (label, value, unit) samples,
e.g. transferred bytes.  The worker installs a callback around each
activity (execute / gather-dep / get-data) that files samples under
``(context, span_id, prefix, label, unit)`` — shipped to the scheduler
with heartbeats and aggregated onto spans (reference metrics.py:336,
spans.py cumulative_worker_metrics).

User task code can emit custom samples too:

    from distributed_tpu.worker.metrics import context_meter
    with context_meter.meter("my-phase"):
        ...
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Callable, Iterator

from distributed_tpu.utils.misc import time


class ContextMeter:
    def __init__(self) -> None:
        self._cbs: contextvars.ContextVar[tuple[Callable, ...]] = (
            contextvars.ContextVar("dtpu_meter_cbs", default=())
        )

    @contextlib.contextmanager
    def add_callback(self, cb: Callable[[str, float, str], None]) -> Iterator[None]:
        token = self._cbs.set(self._cbs.get() + (cb,))
        try:
            yield
        finally:
            self._cbs.reset(token)

    def digest_metric(self, label: str, value: float, unit: str = "seconds") -> None:
        for cb in self._cbs.get():
            try:
                cb(label, value, unit)
            except Exception:  # metrics must never break the data path
                pass

    @contextlib.contextmanager
    def meter(self, label: str) -> Iterator[None]:
        t0 = time()
        try:
            yield
        finally:
            self.digest_metric(label, time() - t0, "seconds")


context_meter = ContextMeter()


class FineMetrics:
    """Per-worker accumulator: cumulative totals plus a since-last-
    heartbeat delta buffer (reference worker.py
    digests_total_since_heartbeat)."""

    def __init__(self) -> None:
        self.total: dict[tuple, float] = {}
        self.since_heartbeat: dict[tuple, float] = {}

    def add(self, context: str, span_id: str | None, prefix: str,
            label: str, unit: str, value: float) -> None:
        key = (context, span_id or "", prefix, label, unit)
        self.total[key] = self.total.get(key, 0.0) + value
        self.since_heartbeat[key] = self.since_heartbeat.get(key, 0.0) + value

    def take(self) -> dict[tuple, float]:
        """Pop the heartbeat delta; pair with restore() on send failure."""
        out = self.since_heartbeat
        self.since_heartbeat = {}
        return out

    def restore(self, delta: dict[tuple, float]) -> None:
        """Merge a failed heartbeat's delta back in (samples must never
        be lost to a transient comm error)."""
        for k, v in delta.items():
            self.since_heartbeat[k] = self.since_heartbeat.get(k, 0.0) + v

    @staticmethod
    def rows(delta: dict[tuple, float]) -> list[list[Any]]:
        """msgpack-friendly encoding of a delta."""
        return [[*k, v] for k, v in delta.items()]


class DelayedMetricsLedger:
    """Metrics collector for one ASYNC instruction (reference
    metrics.py:336 DelayedMetricsLedger).

    A gather-dep or execute spans many event-loop iterations; samples
    produced while it runs (network reads, deserialize, disk writes)
    must be attributed to THAT instruction even though other coroutines
    interleave.  ``activity()`` installs a context-local callback (so
    only awaits on this coroutine's context record here), and
    ``finalize`` files everything plus the un-metered remainder as
    ``other`` — the time the instruction spent scheduled but not inside
    any bracket (loop contention, executor queueing).
    """

    def __init__(self, sink: Callable[[str, float, str], None]):
        self._sink = sink
        self.samples: list[tuple[str, float, str]] = []
        self.start = time()

    def record(self, label: str, value: float, unit: str) -> None:
        self.samples.append((label, value, unit))

    @contextlib.contextmanager
    def activity(self) -> Iterator[None]:
        with context_meter.add_callback(self.record):
            yield

    def finalize(self, other_label: str = "other") -> None:
        elapsed = time() - self.start
        metered = sum(
            v for _, v, unit in self.samples if unit == "seconds"
        )
        for label, value, unit in self.samples:
            self._sink(label, value, unit)
        remainder = elapsed - metered
        if remainder > 0:
            self._sink(other_label, remainder, "seconds")
