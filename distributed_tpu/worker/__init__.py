"""Worker package: sans-IO state machine + async shells (Worker, Nanny)."""

from __future__ import annotations


def resolve_lifetime(
    lifetime: float | None,
    stagger: float | None,
    restart: bool | None = None,
) -> tuple[float | None, float, bool]:
    """(duration, stagger, restart): explicit args win, else the
    ``worker.lifetime.*`` config keys (the single place this fallback
    lives — Worker and Nanny both construct from it)."""
    from distributed_tpu import config

    cfg = config.get("worker.lifetime") or {}
    if lifetime is None:
        lifetime = config.parse_timedelta(cfg.get("duration"))
    if stagger is None:
        stagger = config.parse_timedelta(cfg.get("stagger")) or 0
    if restart is None:
        restart = bool(cfg.get("restart"))
    return lifetime, stagger, restart


def sample_lifetime_delay(lifetime: float, stagger: float) -> float:
    """One lifetime deadline with uniform +/- stagger (never below 0.1 s)
    so a fleet doesn't cycle in lock-step."""
    import random

    return max(lifetime + random.uniform(-stagger, stagger), 0.1)
