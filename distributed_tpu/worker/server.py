"""Worker server: the async shell around the sans-IO state machine.

Equivalent of the reference's ``Worker`` (worker.py:264) +
``BaseWorker`` (worker_state_machine.py:3589): a ``Server`` with RPC
handlers (get_data, run, ...) and stream handlers that translate scheduler
ops into state-machine events; instructions coming back out of
``WorkerState.handle_stimulus`` are turned into asyncio tasks
(Execute -> thread pool, GatherDep -> peer RPC) whose outcomes are fed
back in as new events — the only bridge between the pure state machine
and IO.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any

from distributed_tpu import config
from distributed_tpu.comm.core import Comm, connect
from distributed_tpu.diagnostics import device_profile
from distributed_tpu.exceptions import CommClosedError, Reschedule, WorkerClosedError
from distributed_tpu.graph.spec import Key
from distributed_tpu.protocol.serialize import Serialize, unwrap
from distributed_tpu.rpc.batched import BatchedSend
from distributed_tpu.rpc.core import PeriodicCallback, Server, Status, error_message
from distributed_tpu.utils.misc import (
    format_exception,
    seq_name,
    time,
    truncate_exception,
)
from distributed_tpu.utils.sizeof import sizeof
from distributed_tpu.worker.state_machine import (
    AcquireReplicasEvent,
    ComputeTaskEvent,
    Execute,
    ExecuteFailureEvent,
    ExecuteSuccessEvent,
    FindMissingEvent,
    FreeKeysEvent,
    GatherDep,
    GatherDepBusyEvent,
    GatherDepFailureEvent,
    GatherDepNetworkFailureEvent,
    GatherDepSuccessEvent,
    Instruction,
    PauseEvent,
    RefreshWhoHasEvent,
    RemoveReplicasEvent,
    RescheduleEvent,
    RetryBusyWorkerEvent,
    RetryBusyWorkerLater,
    SendMessageToScheduler,
    StateMachineEvent,
    StealRequestEvent,
    UnpauseEvent,
    UpdateDataEvent,
    WorkerState,
)

logger = logging.getLogger("distributed_tpu.worker")


class Worker(Server):
    """Executes tasks, stores results, serves peers (reference worker.py:264)."""

    blocked_handlers_config_key = "worker.blocked-handlers"
    preload_config_prefix = "worker"

    def __init__(
        self,
        scheduler_addr: str,
        *,
        nthreads: int | None = None,
        name: object = None,
        memory_limit: int = 0,
        resources: dict[str, float] | None = None,
        validate: bool | None = None,
        heartbeat_interval: float | None = None,
        listen_addr: str | None = None,
        http_port: int | None = 0,
        security: Any | None = None,
        lifetime: float | None = None,
        lifetime_stagger: float | None = None,
        nanny_addr: str | None = None,
        jax_coordinator: str | None = None,
        jax_process_id: int | None = None,
        jax_num_processes: int | None = None,
        jax_cpu_devices: int | None = None,
        **server_kwargs: Any,
    ):
        self.nanny_addr = nanny_addr
        # multi-host device plane: when a coordinator is given, this
        # process joins a pod-wide jax runtime at start (parallel/
        # multihost.py) and reports its global mesh device indices to
        # the scheduler so device-plane shuffles pin work to owners
        self.jax_coordinator = jax_coordinator
        self.jax_process_id = jax_process_id
        self.jax_num_processes = jax_num_processes
        self.jax_cpu_devices = jax_cpu_devices
        self.jax_device_indices: list[int] | None = None
        self._http_port = http_port
        self.http_server = None
        self.monitor = None
        self.scheduler_addr = scheduler_addr
        self.security = security
        if security is not None:
            server_kwargs.setdefault(
                "connection_args", security.get_connection_args("worker")
            )
        self.nthreads = nthreads or 1
        self.memory_limit = memory_limit
        self._listen_addr = listen_addr
        from distributed_tpu.worker import resolve_lifetime

        self.lifetime, self.lifetime_stagger, _ = resolve_lifetime(
            lifetime, lifetime_stagger
        )
        self._lifetime_task: Any | None = None
        data = None
        if memory_limit:
            from distributed_tpu.utils.diskutils import WorkSpace
            from distributed_tpu.worker.spill import SpillBuffer

            mem_cfg = config.get("worker.memory")
            self._work_dir = WorkSpace().new_work_dir(prefix="spill")
            data = SpillBuffer(
                self._work_dir.path,
                target=int(mem_cfg["target"] * memory_limit),
                metrics_cb=lambda label, value, unit: self._fine_metric(
                    "spill", None, "", label, unit, value
                ),
            )
        self.state = WorkerState(
            nthreads=self.nthreads,
            # config fallback mirrors the reference's worker.resources
            # yaml knob: a fleet-wide resource advertisement without
            # per-worker CLI flags
            resources=(
                resources
                if resources is not None
                else dict(config.get("worker.resources") or {})
            ),
            validate=validate,
            data=data,
            execute_pipeline=int(config.get("worker.execute-pipeline") or 0),
            execute_pipeline_threshold=config.parse_timedelta(
                config.get("worker.execute-pipeline-threshold") or "5ms"
            ),
        )
        self.data = self.state.data
        # unique prefix per worker: the statistical profiler samples by
        # thread-name match, and with many in-process workers
        # (LocalCluster) each profiler must see only ITS OWN executor
        # threads — a shared prefix makes sampling O(workers^2)
        self._exec_prefix = f"dtpu-worker-exec-{id(self):x}"
        self.executor = ThreadPoolExecutor(
            self.nthreads, thread_name_prefix=self._exec_prefix
        )
        # actors serialize state access on their own single thread
        # (reference worker.py "actor" executor)
        self.actor_executor = ThreadPoolExecutor(
            1, thread_name_prefix="dtpu-worker-actor"
        )
        self.batched_stream = BatchedSend()
        self._stream_event_buffer: list[StateMachineEvent] = []
        self._stream_flush_scheduled = False
        # inline fast path: per-prefix EMA of IN-THREAD task duration
        # (measured around the bare fn call, executor overhead excluded)
        # + a loop-budget window so inlining can never starve the loop
        self._inline_threshold = config.parse_timedelta(
            config.get("worker.inline-threshold") or "0"
        )
        self._prefix_inner_ema: dict[str, float] = {}
        self._inline_window_t0 = 0.0
        self._inline_spent = 0.0
        # cumulative peer-serve counters (observability + benchmarks:
        # placement quality shows up directly as fewer get_data serves)
        self.get_data_requests = 0
        self.get_data_keys_served = 0
        self.get_data_wire_bytes = 0
        # concurrent get_data serves (reply writes included); beyond the
        # limit peers get {"status": "busy"} (reference
        # connections.outgoing, worker.py:~1740)
        self._outgoing_serves = 0
        self._outgoing_limit = int(
            (config.get("worker.connections") or {}).get("outgoing") or 50
        )
        self.scheduler_comm: Comm | None = None
        self.heartbeat_interval = (
            heartbeat_interval if heartbeat_interval is not None else 1.0
        )
        # monotonic count of local pause/unpause flips; stamped onto
        # worker-status-change messages and every heartbeat so the
        # scheduler can order a delayed heartbeat's status view against
        # stream-delivered flips (see Scheduler.heartbeat_worker)
        self._status_seq = 0
        self.plugins: dict[str, Any] = {}
        self._pubsub_subs: dict[str, list] = {}
        self._async_instructions: set[asyncio.Task] = set()
        self._local_directory: Any | None = None
        from distributed_tpu.worker.metrics import FineMetrics

        self.fine_metrics = FineMetrics()
        # measured-truth transfer telemetry (telemetry.py): both ends of
        # every get_data/gather_dep transfer file (src, dst, nbytes,
        # seconds) here; heartbeats ship the since-last delta to the
        # scheduler's fleet aggregate (docs/observability.md)
        from distributed_tpu.telemetry import EWMA, LinkTelemetry

        self.telemetry = LinkTelemetry()
        # heartbeat round-trip EWMA, measured with monotonic stamps
        # around the heartbeat RPC; shipped on the NEXT heartbeat and
        # exposed as dtpu_link_heartbeat_rtt_seconds scheduler-side
        self._hb_rtt = EWMA(self.telemetry.alpha)

        handlers = {
            "get_data": self.get_data,
            "gather": self.gather,
            "run": self.run_function,
            "update_data": self.update_data_handler,
            "free_keys": self.handle_free_keys_rpc,
            "actor_execute": self.actor_execute,
            "actor_attribute": self.actor_attribute,
            "profile": self.get_profile,
            "versions": self.get_versions,
            "benchmark_hardware": self.benchmark_hardware_handler,
            "memory_trace": self.memory_trace_handler,
            "device_profile": self.device_profile_handler,
            "terminate": self.close_rpc,
            "plugin_add": self.plugin_add,
            "plugin_remove": self.plugin_remove,
            "get_telemetry": self.get_telemetry,
            "get_census": self.get_census,
        }
        stream_handlers = {
            "compute-task": self._stream_compute_task,
            "compute-tasks": self._stream_compute_tasks,
            "free-keys": self._stream_free_keys,
            "remove-replicas": self._stream_remove_replicas,
            "acquire-replicas": self._stream_acquire_replicas,
            "steal-request": self._stream_steal_request,
            "refresh-who-has": self._stream_refresh_who_has,
            "worker-status-change": self._stream_status_change,
            "close-worker": self._stream_close,
            "pubsub-msg": self._stream_pubsub_msg,
        }
        super().__init__(
            handlers=handlers, stream_handlers=stream_handlers, name=name,
            **server_kwargs,
        )
        # one causal timeline for the role: the server's flight recorder
        # IS the state machine's (the /trace route and get_trace RPC
        # serve the sans-io engine's stimulus events)
        self.trace = self.state.trace
        self.name = name if name is not None else self.id
        from distributed_tpu.shuffle.core import ShuffleWorkerExtension

        self.shuffle = ShuffleWorkerExtension(self)
        self.profiler = None
        if config.get("worker.profile.enabled"):
            from distributed_tpu.diagnostics.profile import Profiler

            # sample exactly our executor threads, and only while
            # something is executing — N in-proc workers enumerating
            # every process thread at 100 Hz starved the event loop.
            # _threads is ThreadPoolExecutor-private: if a future
            # executor lacks it, fall back to the name-filter path
            # rather than silently sampling nothing
            idents = None
            if hasattr(self.executor, "_threads"):

                def idents() -> list:
                    # the pool grows its _threads set concurrently with
                    # submit(); retry the snapshot instead of letting a
                    # transient RuntimeError kill this worker's profiling
                    for _ in range(3):
                        try:
                            return [t.ident for t in self.executor._threads]
                        except RuntimeError:
                            continue
                    return []

            self.profiler = Profiler(
                thread_filter=self._exec_prefix,
                idents=idents,
                active=lambda: bool(self.state.executing),
            )
        # control-plane self-profiling (diagnostics/selfprofile.py):
        # this worker's EVENT-LOOP thread — the gather_dep/execute
        # dispatch plane the executor profiler above cannot see.  Wired
        # at start_unsafe (the loop ident is only known there).
        self.cp_profiler: Any | None = None
        self.watchdog: Any | None = None
        self.memory_manager = None
        if memory_limit:
            from distributed_tpu.worker.memory import WorkerMemoryManager

            self.memory_manager = WorkerMemoryManager(self, memory_limit)

    # ------------------------------------------------------------ lifecycle

    async def start_unsafe(self) -> "Worker":
        from distributed_tpu import native

        native.prebuild_async()
        self.loop = asyncio.get_running_loop()
        if self.jax_coordinator is not None:
            # join the pod-wide jax runtime BEFORE any task can touch
            # jax; blocking rendezvous runs off-loop
            from distributed_tpu.parallel import multihost

            def _join():
                import jax

                if (
                    self.jax_cpu_devices
                    and jax.config.jax_num_cpu_devices
                    != int(self.jax_cpu_devices)
                ):
                    # no-op when the CLI already set it pre-backend
                    jax.config.update(
                        "jax_num_cpu_devices", int(self.jax_cpu_devices)
                    )
                multihost.maybe_initialize(
                    self.jax_coordinator,
                    process_id=self.jax_process_id,
                    num_processes=self.jax_num_processes,
                )
                return multihost.local_device_indices()

            self.jax_device_indices = await asyncio.get_running_loop(
            ).run_in_executor(None, _join)
        addr = self._listen_addr
        if addr is None:
            addr = "tcp://127.0.0.1:0"
        listen_args = (
            self.security.get_listen_args("worker")
            if self.security is not None else {}
        )
        await self.listen(addr, **listen_args)
        self.state.address = self.address
        from distributed_tpu.diagnostics.system_monitor import SystemMonitor
        from distributed_tpu.http.server import HTTPServer, worker_metrics

        self.monitor = SystemMonitor(
            maxlen=int(config.get("admin.system-monitor.log-length"))
        )
        self.periodic_callbacks["monitor"] = PeriodicCallback(
            self.monitor.update,
            config.parse_timedelta(
                config.get("admin.system-monitor.interval")
            ),
        )
        # control-plane self-profiling: sample this worker's loop thread
        # + stall watchdog (same scheduler.profile subtree as the
        # scheduler's, like the shared trace config)
        if config.get("scheduler.profile.enabled", True):
            from distributed_tpu.diagnostics.selfprofile import (
                ControlPlaneProfiler,
                LoopWatchdog,
            )

            loop_ident = threading.get_ident()  # we run ON the loop here
            self.cp_profiler = ControlPlaneProfiler(
                idents=lambda: [loop_ident], wall=self.state.wall
            )
            self.cp_profiler.start()
            self.watchdog = LoopWatchdog(trace=self.trace, wall=self.state.wall)
            self.periodic_callbacks["loop-watchdog"] = PeriodicCallback(
                self.watchdog.tick, self.watchdog.interval
            )
            self.watchdog.start(loop_ident)
        # retention sentinel over this worker's state census — same
        # contract as the scheduler role (diagnostics/census.py;
        # docs/observability.md "State census & retention")
        if config.get("scheduler.census.enabled", True):
            from distributed_tpu.diagnostics.census import RetentionSentinel

            census = self.state.census
            census.sentinel = sentinel = RetentionSentinel(
                census, trace=self.trace,
            )

            def _enriched(fut) -> None:
                exc = fut.exception()
                if exc is not None:
                    logger.warning(
                        "census finding enrichment failed: %r", exc
                    )

            def _census_tick() -> None:
                fresh = sentinel.tick()
                if fresh:
                    asyncio.get_running_loop().run_in_executor(
                        None, census.enrich_findings, fresh
                    ).add_done_callback(_enriched)

            self.periodic_callbacks["census-sentinel"] = PeriodicCallback(
                _census_tick,
                config.parse_timedelta(
                    config.get("scheduler.census.interval")
                ),
            )
        if self._http_port is not None:
            from distributed_tpu.diagnostics.selfprofile import profile_jsonl
            from distributed_tpu.tracing import to_jsonl

            routes: dict = {
                    "/health": lambda: "ok",
                    "/info": self.identity,
                    "/metrics": lambda: worker_metrics(self),
                    "/sysmon": lambda: self.monitor.range_query(),
                    # flight-recorder tail (docs/observability.md)
                    "/trace": lambda: (
                        to_jsonl(self.trace.tail()),
                        "application/x-ndjson",
                    ),
                    # measured-truth telemetry snapshot: this node's
                    # per-link EWMAs + t-digest quantiles as JSONL
                    # (telemetry.py; docs/observability.md)
                    "/telemetry": lambda: (
                        to_jsonl(self.telemetry.snapshot()),
                        "application/x-ndjson",
                    ),
                    # state census: this worker's per-family resident
                    # counts + findings (diagnostics/census.py;
                    # docs/observability.md "State census & retention")
                    "/census": lambda: (
                        to_jsonl(self.state.census.snapshot()),
                        "application/x-ndjson",
                    ),
                    # control-plane self-profile (loop tree + wall
                    # budget + stalls) plus the executor profile tree
                    # (docs/observability.md "Self-profiling")
                    "/profile": lambda: (
                        profile_jsonl(
                            "worker", self.cp_profiler, self.state.wall,
                            self.watchdog,
                            extra_trees=(
                                {"exec": self.profiler.get_profile()}
                                if self.profiler is not None else None
                            ),
                        ),
                        "application/x-ndjson",
                    ),
            }
            # route index at "/": same discoverability contract as the
            # scheduler role — one GET lists every route this node
            # serves (the scheduler's index additionally lists /ledger;
            # decisions are scheduler-side, so workers have no ledger)
            routes["/"] = lambda: {
                "role": "worker",
                "id": self.id,
                "routes": sorted(r for r in routes if r != "/"),
            }
            self.http_server = HTTPServer(routes, port=self._http_port)
            await self.http_server.start()
        # config preloads run BEFORE registration (reference worker
        # ordering): the scheduler may assign tasks the moment the
        # worker registers, and dtpu_setup must have prepared the
        # environment by then.  Idempotent: Server.start's later call
        # becomes a no-op.
        await self._start_config_preloads()
        await self._register_with_scheduler()
        if self.heartbeat_interval > 0:
            self.periodic_callbacks["heartbeat"] = PeriodicCallback(
                self.heartbeat, self.heartbeat_interval
            )
        self.periodic_callbacks["find-missing"] = PeriodicCallback(
            self.find_missing, 1.0
        )
        if self.profiler is not None:
            self.profiler.start()
        if self.lifetime:
            self._lifetime_task = asyncio.create_task(self._lifetime_close())
        self.start_periodic_callbacks()
        return self

    async def _lifetime_close(self) -> None:
        """Standalone --lifetime: retire gracefully after the deadline
        (reference worker.py lifetime / close_gracefully).  Under a Nanny
        the NANNY owns the lifetime (it can also restart); this path is
        for bare workers."""
        from distributed_tpu.worker import sample_lifetime_delay

        delay = sample_lifetime_delay(self.lifetime, self.lifetime_stagger)
        await asyncio.sleep(delay)
        logger.info(
            "worker %s reached its lifetime (%.0fs); retiring", self.address,
            delay,
        )
        try:
            await self.rpc(self.scheduler_addr).retire_workers(
                workers=[self.address]
            )
        except Exception:
            logger.warning("lifetime retire failed", exc_info=True)
        self._ongoing_background_tasks.call_soon(self.close)

    def _register_backoff(self, purpose: str):
        """One backoff policy for both registration loops: exponential
        from ``worker.register.base-delay`` capped at ``.max-delay``,
        jittered in [0.5, 1.5) by an rng seeded per (worker id,
        purpose) — deterministic in tests, decorrelated across a fleet
        re-registering after a scheduler bounce.  Returns
        ``delay(attempt)`` with attempts counted from 1."""
        import random

        base = config.parse_timedelta(
            config.get("worker.register.base-delay")
        )
        max_delay = config.parse_timedelta(
            config.get("worker.register.max-delay")
        )
        rng = random.Random(f"{self.id}-{purpose}")

        def delay(attempt: int) -> float:
            return min(max_delay, base * 2 ** (attempt - 1)) * (
                0.5 + rng.random()
            )

        return delay

    async def _register_with_scheduler(self) -> None:
        """Handshake + dual stream with the scheduler (reference
        worker.py:1164), with retry/backoff + jitter: a handshake that
        times out (or whose reply is lost) retries on a fresh comm —
        safe because the scheduler side is idempotent per ``server_id``
        (a retry after a half-applied registration reuses the state
        row; replicas and occupancy never double-count)."""
        retries = int(config.get("worker.register.retries"))
        backoff = self._register_backoff("register")
        attempt = 0
        while True:
            try:
                await self._register_once()
                return
            except (CommClosedError, OSError, asyncio.TimeoutError) as exc:
                attempt += 1
                if attempt > retries:
                    raise
                delay = backoff(attempt)
                logger.info(
                    "register-worker attempt %d/%d failed (%s); retrying "
                    "in %.2fs", attempt, retries, exc, delay,
                )
                await asyncio.sleep(delay)

    async def _register_once(self) -> None:
        comm = await connect(self.scheduler_addr, **self.connection_args)
        from distributed_tpu.scheduler.durability import worker_held_keys
        from distributed_tpu.versions import get_versions

        try:
            await comm.write(
                {
                    "op": "register-worker",
                    "address": self.address,
                    "nthreads": self.nthreads,
                    "nanny": self.nanny_addr,
                    "name": self.name,
                    "memory_limit": self.memory_limit,
                    "resources": self.state.total_resources,
                    "server_id": self.id,
                    "versions": get_versions(),
                    "jax_devices": self.jax_device_indices,
                    # stored data inventory: a restarted scheduler's
                    # recovery window rebuilds/cross-checks who_has
                    # from this (scheduler/durability.py)
                    "held_keys": worker_held_keys(self.state),
                    "reply": False,
                }
            )
            # bounded read: a scheduler that accepted the connection but
            # wedged before replying must not hang registration forever
            # — the retry loop above owns recovery
            resp = await asyncio.wait_for(
                comm.read(),
                timeout=config.parse_timedelta(
                    config.get("comm.timeouts.connect")
                ) or 30.0,
            )
        except BaseException:
            await comm.close()
            raise
        if resp.get("status") != "OK":
            await comm.close()
            raise ValueError(f"scheduler rejected worker: {resp!r}")
        self.scheduler_comm = comm
        self.batched_stream.start(comm)
        self._ongoing_background_tasks.call_soon(self.handle_scheduler, comm)
        logger.info("%s registered with scheduler %s", self.address, self.scheduler_addr)

    async def handle_scheduler(self, comm: Comm) -> None:
        """Read scheduler->worker stream ops until the comm dies."""
        try:
            await self.handle_stream(comm)
        finally:
            if self.status not in (Status.closing, Status.closed, Status.failed):
                attempts = int(config.get("worker.reconnect-attempts"))
                if attempts > 0 and await self._reconnect_to_scheduler(attempts):
                    return
                logger.info("connection to scheduler lost; closing %s", self.address)
                await self.close()

    async def _reconnect_to_scheduler(self, attempts: int) -> bool:
        """Scheduler-bounce survival: the stream died but this worker
        keeps its data and state machine — re-register with backoff +
        jitter (carrying ``held_keys``) so a restarted scheduler's
        recovery window can rebuild ``who_has`` instead of recomputing
        everything this worker already holds."""
        backoff = self._register_backoff("reconnect")
        for attempt in range(1, attempts + 1):
            await asyncio.sleep(backoff(attempt))
            if self.status in (Status.closing, Status.closed, Status.failed):
                return False
            # the old stream is dead: tear it down and hand the state
            # machine a fresh buffering BatchedSend before the handshake
            await self.batched_stream.close()
            self.batched_stream = BatchedSend()
            if self.scheduler_comm is not None:
                await self.scheduler_comm.close()
                self.scheduler_comm = None
            try:
                await self._register_once()
            except (CommClosedError, OSError, asyncio.TimeoutError,
                    ValueError) as exc:
                logger.info(
                    "scheduler reconnect attempt %d/%d failed: %s",
                    attempt, attempts, exc,
                )
                continue
            logger.info(
                "%s reconnected to scheduler after %d attempt(s)",
                self.address, attempt,
            )
            return True
        return False

    async def heartbeat(self) -> None:
        if self.batched_stream.closed():
            return
        delta = self.fine_metrics.take()
        link_delta = self.telemetry.take()
        t0 = time()
        try:
            resp = await self.rpc(self.scheduler_addr).heartbeat_worker(
                address=self.address,
                now=time(),
                metrics=self.metrics(),
                fine_metrics=self.fine_metrics.rows(delta),
                link_telemetry=self.telemetry.rows(link_delta),
                # last-known round-trip EWMA: the CURRENT trip's rtt is
                # only known after this call returns, so each heartbeat
                # carries the previous measurement (0.0 until the
                # second heartbeat; the scheduler skips zeros)
                rtt=self._hb_rtt.value if self._hb_rtt.count else 0.0,
                # paused/running travels with every heartbeat: the
                # event-driven worker-status-change message is lossy at
                # the edges (a pause during startup fires before the
                # batched stream exists and is swallowed), and a
                # scheduler that thinks a paused worker is running never
                # frees its tasks for stealing
                executing_status="paused" if not self.state.running
                else "running",
                status_seq=self._status_seq,
            )
            self._hb_rtt.update(time() - t0)
            if resp.get("status") == "missing":
                # scheduler forgot us (e.g. after its restart): re-register
                await self.close()
        except (CommClosedError, OSError):
            # don't lose the activity samples to a transient blip
            self.fine_metrics.restore(delta)
            self.telemetry.restore(link_delta)

    def data_store_summary(self) -> dict:
        """One source of truth for the data-store/spill snapshot
        (metrics heartbeats and memory-trace reports both use it)."""
        out = {
            "keys": len(self.data),
            "managed_bytes": self.state.nbytes_in_memory,
        }
        if hasattr(self.data, "spilled_count"):
            out["spilled_count"] = self.data.spilled_count
            out["spilled_bytes"] = self.data.slow_bytes
        return out

    def metrics(self) -> dict:
        store = self.data_store_summary()
        out = {
            "executing": len(self.state.executing),
            "ready": len(self.state.ready),
            "in_flight": len(self.state.in_flight_tasks),
            "in_memory": store["keys"],
            "memory": store["managed_bytes"],
        }
        if self.monitor is not None:
            out["host"] = self.monitor.recent()
        if "spilled_count" in store:
            out["spilled_count"] = store["spilled_count"]
            out["spilled_bytes"] = store["spilled_bytes"]
        return out

    async def find_missing(self) -> None:
        if any(ts.state == "missing" for ts in self.state.tasks.values()):
            self.handle_stimulus(FindMissingEvent(stimulus_id=seq_name("find-missing")))

    async def close(self, timeout: float | None = None, *, report: bool = True) -> None:
        if self.status in (Status.closed, Status.closing):
            await self.finished()
            return
        self.status = Status.closing
        await self._teardown_config_preloads()
        logger.info("closing worker %s", self.address)
        if self._lifetime_task is not None:
            self._lifetime_task.cancel()
            self._lifetime_task = None
        for pc in self.periodic_callbacks.values():
            pc.stop()
        for plugin in list(self.plugins.values()):
            teardown = getattr(plugin, "teardown", None)
            if teardown is not None:
                try:
                    res = teardown(self)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    logger.exception("plugin teardown failed")
        for task in list(self._async_instructions):
            task.cancel()
        if self._async_instructions:
            await asyncio.gather(*self._async_instructions, return_exceptions=True)
        await self.batched_stream.close()
        if self.scheduler_comm is not None:
            await self.scheduler_comm.close()
        if self.profiler is not None:
            self.profiler.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.cp_profiler is not None:
            self.cp_profiler.stop()  # flushes the in-flight cycle
        self.executor.shutdown(wait=False)
        self.actor_executor.shutdown(wait=False)
        # release any memory-trace hold this server owns: a worker
        # closed mid-trace must not leave the process-global
        # tracemalloc unstoppable (diagnostics/memtrace.py refcounts
        # per owner; discard is a no-op when we never started one)
        from distributed_tpu.diagnostics import memtrace

        memtrace.stop_trace(owner=self.id)
        if hasattr(self.data, "close"):
            self.data.close()
        if self.http_server is not None:
            await self.http_server.stop()
        await super().close()

    async def close_rpc(self, reason: str = "") -> str:
        self._ongoing_background_tasks.call_soon(self.close)
        return "OK"

    async def _stream_close(self, **kwargs: Any) -> None:
        self._ongoing_background_tasks.call_soon(self.close)

    # --------------------------------------------------------- RPC handlers

    async def get_data(
        self, comm: Comm, keys: tuple = (), who: str | None = None,
        reply: bool = True, **kwargs: Any
    ) -> Any:
        """Serve locally-held task data to a peer (reference worker.py:1722).

        Outgoing-serve backpressure (reference connections.outgoing=50):
        the handler writes its own reply so the WRITE — where a slow
        peer's TCP window actually blocks — counts against the limit;
        over the limit the peer gets ``{"status": "busy"}`` and retries
        elsewhere or later (GatherDepBusyEvent path)."""
        if self._outgoing_serves >= self._outgoing_limit:
            return {"status": "busy"} if reply else Status.dont_reply
        self._outgoing_serves += 1
        try:
            t0 = time()
            data = {}
            for k in keys:
                if k in self.data:
                    data[k] = Serialize(self.data[k])
            self.get_data_requests += 1
            self.get_data_keys_served += len(data)
            nbytes = {k: self.state.tasks[k].nbytes if k in self.state.tasks
                      else sizeof(self.data[k]) for k in data}
            self._fine_metric(
                "get-data", None, "", "serve", "seconds", time() - t0
            )
            self._fine_metric(
                "get-data", None, "", "serve", "bytes",
                float(sum(nbytes.values())),
            )
            if reply:
                # comm.write returns true wire bytes (post-compression,
                # incl. framing): the gap between this and the nbytes
                # sum above is the zero-copy data plane's effectiveness
                wire_bytes = await comm.write(
                    {"status": "OK", "data": data, "nbytes": nbytes}
                )
                self.get_data_wire_bytes += wire_bytes
                # serving-end link sample: true wire bytes attributed to
                # (us -> requester), as the peer CROSS-CHECK only — this
                # clock stops when comm.write returns (OS buffer), not
                # when the peer received the bytes, so it must never
                # fold into the dst-observed bandwidth EWMA.  The
                # requesting end files the authoritative sample; the
                # scheduler classifies the shipped rows by reporter
                # (telemetry.py)
                # `and data`: an empty OK reply (keys already released)
                # files nothing on the requesting end either, so the
                # two ends' per-link sample counts stay in lockstep
                if who and data:
                    self.telemetry.record_peer(
                        self.address, who, wire_bytes, time() - t0
                    )
            return Status.dont_reply
        finally:
            self._outgoing_serves -= 1

    async def get_telemetry(self) -> list[dict]:
        """This node's telemetry snapshot (JSON-safe records): the RPC
        twin of the HTTP ``/telemetry`` route (telemetry.py)."""
        return self.telemetry.snapshot()

    async def get_census(self, deep: bool = False) -> list[dict]:
        """This worker's state census (head + per-family records +
        findings): the RPC twin of the HTTP ``/census`` route
        (diagnostics/census.py; docs/observability.md)."""
        return self.state.census.snapshot(deep=deep)

    async def gather(self, who_has: dict[Key, list[str]] | None = None) -> dict:
        """Pull keys from peers into local memory (reference worker.py:1274)."""
        who_has = who_has or {}
        from distributed_tpu.utils.comm import gather_from_workers

        data, missing, busy, _ = await gather_from_workers(who_has, rpc=self.rpc)
        self.handle_stimulus(
            UpdateDataEvent(stimulus_id=seq_name("gather"), data=data)
        )
        if missing or busy:
            # busy keys exist on their (saturated) holders — reported
            # separately so callers can retry them without a who_has
            # refresh
            return {"status": "partial-fail",
                    "keys": sorted(missing | busy),
                    "busy": sorted(busy)}
        return {"status": "OK"}

    async def run_function(
        self, function: Any = None, args: Any = None, kwargs: Any = None,
        wait: bool = True,
    ) -> Any:
        """Run an arbitrary function on this worker (reference worker.py run)."""
        from distributed_tpu.rpc.core import run_user_function

        return await run_user_function(
            self, "dtpu_worker", function, args, kwargs, wait
        )

    async def update_data_handler(self, data: Any = None, report: bool = True) -> dict:
        """Receive scattered data (reference worker.py update_data)."""
        data = {k: unwrap(v) for k, v in (unwrap(data) or {}).items()}
        self.handle_stimulus(
            UpdateDataEvent(
                stimulus_id=seq_name("update-data"), data=data, report=report
            )
        )
        return {"status": "OK", "nbytes": {k: sizeof(v) for k, v in data.items()}}

    async def handle_free_keys_rpc(self, keys: tuple = (), stimulus_id: str = "") -> str:
        self.handle_stimulus(
            FreeKeysEvent(stimulus_id=stimulus_id or seq_name("free-keys"),
                          keys=tuple(keys))
        )
        return "OK"

    async def actor_execute(self, actor: str = "", function: str = "",
                            args: Any = None, kwargs: Any = None) -> dict:
        """Run a method on a resident actor (reference worker.py:2159)."""
        instance = self.state.actors.get(actor)
        if instance is None:
            return error_message(ValueError(f"no actor {actor!r} on this worker"))
        a = unwrap(args) or ()
        kw = unwrap(kwargs) or {}
        try:
            fn = getattr(instance, function)
            if asyncio.iscoroutinefunction(fn):
                result = await fn(*a, **kw)
            else:
                result = await asyncio.get_running_loop().run_in_executor(
                    self.actor_executor, lambda: fn(*a, **kw)
                )
            return {"status": "OK", "result": Serialize(result)}
        except Exception as e:
            return error_message(e)

    async def actor_attribute(self, actor: str = "", attribute: str = "") -> dict:
        instance = self.state.actors.get(actor)
        if instance is None:
            return error_message(ValueError(f"no actor {actor!r} on this worker"))
        try:
            return {"status": "OK", "result": Serialize(getattr(instance, attribute))}
        except Exception as e:
            return error_message(e)

    async def get_versions(self) -> dict:
        from distributed_tpu.versions import get_versions

        return get_versions()

    @property
    def local_directory(self) -> str:
        """Per-worker scratch directory (reference worker.py
        local_directory): claimed lazily from the managed WorkSpace so
        plugins (UploadDirectory) and user tasks never collide in the
        process CWD — many workers on one host each get their own dir
        with stale-dir purge on restart."""
        if self._local_directory is None:
            from distributed_tpu.utils.diskutils import WorkSpace

            self._local_directory = WorkSpace().new_work_dir(
                prefix="worker"
            )
        return self._local_directory.path

    async def memory_trace_handler(self, action: str = "report",
                                   top_n: int = 10) -> dict:
        """tracemalloc-backed memory introspection (the reference's
        memray role, diagnostics/memray.py:26): action = start | stop |
        report.  start/stop are refcounted per server id: with
        in-process workers (LocalCluster) one worker's stop no longer
        kills the process-global trace for every other server."""
        from distributed_tpu.diagnostics import memtrace

        if action == "start":
            return memtrace.start_trace(owner=self.id)
        if action == "stop":
            return memtrace.stop_trace(owner=self.id)
        return memtrace.worker_report(self, top_n=top_n)

    async def device_profile_handler(self, action: str = "stop",
                                     logdir: str | None = None) -> dict:
        """XLA device-timeline tracing (the reference's low-level
        profiler role, profile.py:550): action = start | stop.  While a
        trace runs, every executed task is annotated with its key on the
        device timeline (see diagnostics/device_profile.py)."""
        if action == "start":
            return device_profile.start(logdir)
        return device_profile.stop()

    async def benchmark_hardware_handler(self) -> dict:
        """Tiny memory/disk bandwidth probes (reference worker benchmarks)."""
        import tempfile

        def bench() -> dict:
            out: dict = {}
            data = bytearray(64 * 2**20)
            t0 = time()
            for _ in range(4):
                bytes(data)  # memcpy
            out["memory_copy_bps"] = 4 * len(data) / max(time() - t0, 1e-9)
            with tempfile.NamedTemporaryFile(delete=True) as f:
                t0 = time()
                f.write(data)
                f.flush()
                out["disk_write_bps"] = len(data) / max(time() - t0, 1e-9)
            return out

        result = await asyncio.get_running_loop().run_in_executor(None, bench)
        return {"status": "OK", "result": Serialize(result)}

    async def get_profile(self, start: float | None = None) -> Any:
        """Sampled call tree (reference worker.py:2449)."""
        if self.profiler is None:
            from distributed_tpu.diagnostics.profile import create

            return Serialize(create())
        return Serialize(self.profiler.get_profile(start=start))

    async def plugin_add(self, plugin: Any = None, name: str | None = None) -> dict:
        plugin = unwrap(plugin)
        name = name or getattr(plugin, "name", None) or f"plugin-{len(self.plugins)}"
        self.plugins[name] = plugin
        setup = getattr(plugin, "setup", None)
        if setup is not None:
            try:
                res = setup(worker=self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception as e:
                return error_message(e)
        return {"status": "OK"}

    async def plugin_remove(self, name: str = "") -> dict:
        plugin = self.plugins.pop(name, None)
        if plugin is not None:
            teardown = getattr(plugin, "teardown", None)
            if teardown is not None:
                try:
                    res = teardown(self)
                    if asyncio.iscoroutine(res):
                        await res
                except Exception as e:
                    return error_message(e)
        return {"status": "OK"}

    # ------------------------------------------------------ stream handlers

    def _enqueue_stream_event(self, event: StateMachineEvent) -> None:
        """Coalesce stream stimuli within one payload: every message of
        a scheduler payload (often a whole planned tile of compute-tasks)
        lands in ONE handle_stimulus batch, so the state machine's
        communicating drain can aggregate their dep fetches into few
        GatherDep requests.  ``handle_stream`` flushes SYNCHRONOUSLY at
        each payload boundary (rpc/core.py stream_payload_flush), so no
        locally-generated event can interleave mid-payload; the
        call_soon is only a backstop for direct calls outside a stream
        (tests, debugging)."""
        self._stream_event_buffer.append(event)
        if not self._stream_flush_scheduled:
            self._stream_flush_scheduled = True
            asyncio.get_running_loop().call_soon(self.stream_payload_flush)

    def stream_payload_flush(self) -> None:
        self._stream_flush_scheduled = False
        events, self._stream_event_buffer = self._stream_event_buffer, []
        if events:
            self.handle_stimulus(*events)

    def _stream_compute_task(self, **msg: Any) -> None:
        msg.pop("op", None)
        msg["run_spec"] = unwrap(msg.get("run_spec"))
        msg["priority"] = tuple(msg.get("priority") or ())
        fields = ComputeTaskEvent.__dataclass_fields__
        msg = {
            k: v for k, v in msg.items()
            if k in fields and (v is not None or k in ("run_spec", "span_id"))
        }
        self._enqueue_stream_event(ComputeTaskEvent(**msg))

    def _stream_compute_tasks(self, tasks: list = (), **kw: Any) -> None:
        """Batch envelope from the scheduler's per-destination coalescer
        (scheduler/server.py _coalesce_worker_stream_msgs): each inner
        message is a full compute-task dict with its own stimulus_id.
        Expansion lands every task in the same payload-boundary
        handle_stimulus batch, so dep fetches still aggregate."""
        for msg in tasks:
            self._stream_compute_task(**msg)

    def _stream_free_keys(self, keys: tuple = (), stimulus_id: str = "") -> None:
        self._enqueue_stream_event(
            FreeKeysEvent(stimulus_id=stimulus_id, keys=tuple(keys))
        )

    def _stream_remove_replicas(self, keys: tuple = (), stimulus_id: str = "") -> None:
        self._enqueue_stream_event(
            RemoveReplicasEvent(stimulus_id=stimulus_id, keys=tuple(keys))
        )

    def _stream_acquire_replicas(
        self, who_has: dict | None = None, nbytes: dict | None = None,
        stimulus_id: str = "",
    ) -> None:
        self._enqueue_stream_event(
            AcquireReplicasEvent(
                stimulus_id=stimulus_id, who_has=who_has or {}, nbytes=nbytes or {}
            )
        )

    def _stream_steal_request(self, key: Key = "", stimulus_id: str = "") -> None:
        self._enqueue_stream_event(
            StealRequestEvent(stimulus_id=stimulus_id, key=key)
        )

    def _stream_refresh_who_has(self, who_has: dict | None = None,
                                stimulus_id: str = "") -> None:
        self._enqueue_stream_event(
            RefreshWhoHasEvent(
                stimulus_id=stimulus_id or seq_name("refresh"), who_has=who_has or {}
            )
        )

    def _stream_pubsub_msg(self, name: str = "", msg: Any = None,
                           **kw: Any) -> None:
        for sub in self._pubsub_subs.get(name, ()):
            sub._put(msg)

    def _stream_status_change(self, status: str = "", stimulus_id: str = "") -> None:
        if status in ("paused", "running"):
            # EVERY local flip bumps the seq, whatever initiated it —
            # heartbeats snapshotted before this flip must order behind
            # it (see Scheduler.heartbeat_worker)
            self._status_seq += 1
        if status == "paused":
            self._enqueue_stream_event(PauseEvent(stimulus_id=stimulus_id))
        elif status == "running":
            self._enqueue_stream_event(UnpauseEvent(stimulus_id=stimulus_id))

    # ------------------------------------------------- instruction execution

    def handle_stimulus(self, *events: StateMachineEvent) -> None:
        """Feed events into the state machine, act on the instructions
        (reference worker.py:1931)."""
        if self.status in (Status.closed, Status.failed):
            return
        instructions = self.state.handle_stimulus(*events)
        self._handle_instructions(instructions)

    def _handle_instructions(self, instructions: list[Instruction]) -> None:
        executes: list[Execute] = []
        for inst in instructions:
            if isinstance(inst, SendMessageToScheduler):
                msg = inst.to_dict()
                if msg.get("op") == "task-erred":
                    # exceptions cross the wire pickled
                    msg["exception"] = Serialize(msg["exception"])
                    msg["traceback"] = None
                try:
                    self.batched_stream.send(msg)
                except CommClosedError:
                    pass
            elif isinstance(inst, Execute):
                executes.append(inst)
            elif isinstance(inst, GatherDep):
                self._start_async_instruction(
                    self._gather_dep(inst.worker, inst.to_gather,
                                     inst.total_nbytes, inst.stimulus_id)
                )
            elif isinstance(inst, RetryBusyWorkerLater):
                self._ongoing_background_tasks.call_later(
                    0.15, self._retry_busy_worker, inst.worker
                )
            else:  # pragma: no cover - future instruction types
                raise TypeError(f"unknown instruction {inst!r}")
        if not executes:
            return
        # Batch gate: coalescing serializes a batch on ONE executor
        # thread and delays every task-finished event until that batch
        # returns, so only known-tiny tasks batch (the scheduler's
        # duration estimate; unknown prefixes report 0.5 s and never
        # qualify).  On multi-thread workers the batchable set is SPLIT
        # into nthreads chunks — one submission per pool thread — so
        # parallelism survives while handoffs still amortize.
        # _ensure_computing's BASE loop also emits multi-Execute lists
        # for tasks of any duration — those keep the per-task path.
        batchable: list[Execute] = []
        state = self.state
        if state.execute_pipeline:
            thresh = state.execute_pipeline_threshold
            rest: list[Execute] = []
            for inst in executes:
                ts = state.tasks.get(inst.key)
                if (
                    ts is not None
                    and not ts.actor
                    and 0.0 <= ts.duration < thresh
                ):
                    batchable.append(inst)
                else:
                    rest.append(inst)
            if len(batchable) < 2:
                rest = executes
                batchable = []
            executes = rest
        if batchable:
            T = state.nthreads
            chunk = -(-len(batchable) // T)  # ceil: T contiguous chunks
            for i in range(0, len(batchable), chunk):
                part = batchable[i:i + chunk]
                if len(part) == 1:
                    self._start_async_instruction(
                        self._execute(part[0].key, part[0].stimulus_id)
                    )
                else:
                    self._start_async_instruction(
                        self._execute_batch(
                            [(p.key, p.stimulus_id) for p in part]
                        )
                    )
        for inst in executes:
            self._start_async_instruction(
                self._execute(inst.key, inst.stimulus_id)
            )

    def _start_async_instruction(self, coro: Any) -> None:
        """Run an instruction coroutine; feed its resulting event back in
        (reference wsm.py:3603)."""
        task = asyncio.create_task(coro)
        self._async_instructions.add(task)

        def _done(task: asyncio.Task) -> None:
            self._async_instructions.discard(task)
            if task.cancelled():
                return
            exc = task.exception()
            if exc is not None:
                logger.exception("async instruction failed", exc_info=exc)
                return
            event = task.result()
            if event is not None:
                self.handle_stimulus(event)

        task.add_done_callback(_done)

    async def _retry_busy_worker(self, worker: str) -> None:
        self.handle_stimulus(
            RetryBusyWorkerEvent(stimulus_id=seq_name("retry-busy"), worker=worker)
        )

    # ------------------------------------------------------------- execute

    def _fine_metric(self, context: str, span_id: str | None, prefix: str,
                     label: str, unit: str, value: float) -> None:
        """File one activity sample: heartbeat delta + cumulative t-digest
        (reference metrics.py ContextMeter -> Worker.digest_metric)."""
        self.fine_metrics.add(context, span_id, prefix, label, unit, value)
        if unit == "seconds":
            self.digest_metric(f"{context}-{label}-seconds", value)

    def _execute_fine_metrics(self, span_id: str | None, prefix: str,
                              duration: float, nbytes: int) -> None:
        """One successful execution's activity rows, shared by _execute
        and _execute_batch: compute seconds (spans), plus the per-task
        output-bytes and task-count samples the scheduler's telemetry
        plane folds into per-prefix priors (telemetry.py
        fold_fine_rows — count makes the heartbeat sums per-task
        means)."""
        self._fine_metric(
            "execute", span_id, prefix, "compute", "seconds", duration
        )
        self._fine_metric(
            "execute", span_id, prefix, "output", "bytes", float(nbytes)
        )
        self._fine_metric("execute", span_id, prefix, "count", "tasks", 1.0)

    def _note_inner_duration(self, prefix: str, dur: float) -> None:
        """EMA of the bare in-thread fn duration per prefix (the inline
        fast-path gate).  Called from executor threads and the loop; a
        lost update under the GIL is harmless for an EMA."""
        ema = self._prefix_inner_ema.get(prefix)
        self._prefix_inner_ema[prefix] = (
            dur if ema is None else 0.7 * ema + 0.3 * dur
        )

    async def _execute_batch(self, items: list[tuple[Key, str]]) -> None:
        """Run one instruction batch of tiny sync tasks as a single
        executor submission.

        The execute-pipeline extension (state_machine._ensure_computing)
        over-fills slots with tasks whose duration estimate is tiny; all
        Execute instructions of one batch land here and cost ONE thread
        handoff and ONE completion wakeup total — the per-task
        run_in_executor round trip (~36 us serial on the bench box, plus
        self-pipe/epoll churn on the loop) was the dominant scheduler-
        side overhead for task storms.  Anything that is not a plain
        sync function (actors, async tasks, literal data, tasks whose
        state moved on) falls back to the per-task ``_execute`` path
        with identical semantics; results feed the state machine as one
        stimulus batch (one transition drain).

        KEEP IN SYNC with ``_execute``: the state filter, substitute
        failure event, metering wrappers, and success/reschedule/failure
        event construction are mirrored there — a change to either path
        (new event field, exception rule) must land in both."""
        import contextvars
        from time import perf_counter as _perf

        from distributed_tpu.utils.misc import key_split
        from distributed_tpu.worker.context import set_thread_worker
        from distributed_tpu.worker.metrics import context_meter

        events: list[StateMachineEvent] = []
        calls: list[tuple] = []
        for key, sid in items:
            ts = self.state.tasks.get(key)
            if ts is None or ts.state not in (
                "executing", "long-running", "cancelled", "resumed"
            ):
                continue
            rs = ts.run_spec
            fn = getattr(rs, "fn", None)
            if fn is None or ts.actor or asyncio.iscoroutinefunction(fn):
                self._start_async_instruction(self._execute(key, sid))
                continue
            prefix = key_split(key)
            start = time()
            try:
                fn, args, kwargs = rs.substitute(self.data)
            except BaseException as e:  # noqa: B036 - corrupt spec / missing dep
                e2 = truncate_exception(e)
                events.append(ExecuteFailureEvent(
                    stimulus_id=sid, key=key, exception=e2, traceback=None,
                    exception_text=repr(e2),
                    traceback_text=format_exception(e),
                    start=start, stop=time(),
                ))
                continue

            def _user_metric(label, value, unit, _sid=ts.span_id, _pre=prefix):
                self._fine_metric("execute", _sid, _pre, label, unit, value)

            with context_meter.add_callback(_user_metric):
                ctx = contextvars.copy_context()
            calls.append((key, sid, ts, prefix, ctx, fn, args, kwargs))

        if calls:
            def _run_batch():
                out = []
                for key, sid, ts, prefix, ctx, fn, args, kwargs in calls:
                    def _call(fn=fn, args=args, kwargs=kwargs,
                              _pre=prefix, _key=key):
                        set_thread_worker(self, _key)
                        t0 = _perf()
                        try:
                            if device_profile.active():
                                with device_profile.annotate(_key):
                                    return fn(*args, **kwargs)
                            return fn(*args, **kwargs)
                        finally:
                            self._note_inner_duration(_pre, _perf() - t0)

                    start = time()
                    try:
                        value = ctx.run(_call)
                        out.append((key, sid, ts, "ok", value, start, time()))
                    except Reschedule:
                        out.append((key, sid, ts, "resched", None, start, time()))
                    except BaseException as e:  # noqa: B036 - user code
                        if isinstance(e, (KeyboardInterrupt, SystemExit)):
                            raise
                        out.append((
                            key, sid, ts, "err",
                            (e, format_exception(e)), start, time(),
                        ))
                return out

            batch_start = time()
            try:
                results = await asyncio.get_running_loop().run_in_executor(
                    self.executor, _run_batch
                )
            except BaseException as e:  # noqa: B036 - mirror _execute
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
                if isinstance(e, asyncio.CancelledError) and self.status in (
                    Status.closing, Status.closed, Status.failed
                ):
                    # worker shutdown cancelled the batch: propagate,
                    # exactly like _execute (no task-erred during close)
                    raise
                # a CancelledError outside shutdown (or any executor
                # failure) must not wedge the whole batch in "executing"
                # with no completion event: emit a failure per task so
                # the scheduler can retry them elsewhere.  The executor
                # thread may still be running the batch — its results
                # are dropped, which is safe (transitions ignore
                # completions for released tasks).
                stop = time()
                e2 = truncate_exception(e)
                tb_text = format_exception(e)
                for key, sid, _ts, _prefix, _ctx, _fn, _a, _kw in calls:
                    events.append(ExecuteFailureEvent(
                        stimulus_id=sid, key=key, exception=e2,
                        traceback=None, exception_text=repr(e2),
                        traceback_text=tb_text,
                        start=batch_start, stop=stop,
                    ))
                results = []
            for key, sid, ts, kind, value, start, stop in results:
                if kind == "ok":
                    self.digest_metric("compute-duration", stop - start)
                    out_nbytes = sizeof(value)
                    self._execute_fine_metrics(
                        ts.span_id, key_split(key), stop - start, out_nbytes
                    )
                    events.append(ExecuteSuccessEvent(
                        stimulus_id=sid, key=key, value=value,
                        start=start, stop=stop, nbytes=out_nbytes,
                        type=type(value).__name__,
                    ))
                elif kind == "resched":
                    events.append(RescheduleEvent(stimulus_id=sid, key=key))
                else:
                    e, tb_text = value
                    e2 = truncate_exception(e)
                    events.append(ExecuteFailureEvent(
                        stimulus_id=sid, key=key, exception=e2,
                        traceback=None, exception_text=repr(e2),
                        traceback_text=tb_text, start=start, stop=stop,
                    ))
        if events:
            self.handle_stimulus(*events)
        return None

    async def _execute(self, key: Key, stimulus_id: str) -> StateMachineEvent | None:
        """Run one task (reference worker.py:2210).

        KEEP IN SYNC with ``_execute_batch`` (see its docstring)."""
        ts = self.state.tasks.get(key)
        # "resumed" must run too: if the task was cancelled and re-requested
        # BEFORE this coroutine's first tick (busy loop), bailing out here
        # would leave it in "resumed" forever — no execution exists to
        # complete it (the round-3 mid-shuffle restart hang)
        if ts is None or ts.state not in (
            "executing", "long-running", "cancelled", "resumed"
        ):
            return None
        run_spec = ts.run_spec
        start = time()
        try:
            if hasattr(run_spec, "substitute"):
                fn, args, kwargs = run_spec.substitute(self.data)
                if asyncio.iscoroutinefunction(fn):
                    from distributed_tpu.worker.context import (
                        reset_async_worker,
                        set_async_worker,
                    )

                    token = set_async_worker(self, key)
                    try:
                        value = await fn(*args, **kwargs)
                    finally:
                        reset_async_worker(token)
                else:
                    import contextvars
                    from time import perf_counter as _perf

                    from distributed_tpu.utils.misc import key_split
                    from distributed_tpu.worker.context import set_thread_worker
                    from distributed_tpu.worker.metrics import context_meter

                    prefix = key_split(key)

                    def _user_metric(label, value, unit,
                                     _sid=ts.span_id, _pre=prefix):
                        self._fine_metric(
                            "execute", _sid, _pre, label, unit, value
                        )

                    def _call(fn=fn, args=args, kwargs=kwargs, _pre=prefix):
                        set_thread_worker(self, key)
                        t0 = _perf()
                        try:
                            if device_profile.active():
                                # device trace running: mark this task's
                                # span on the XLA timeline so its device
                                # ops group under the task key
                                with device_profile.annotate(key):
                                    return fn(*args, **kwargs)
                            return fn(*args, **kwargs)
                        finally:
                            self._note_inner_duration(_pre, _perf() - t0)

                    inline = False
                    if not ts.actor and self._inline_threshold:
                        ema = self._prefix_inner_ema.get(prefix)
                        if ema is not None and ema < self._inline_threshold:
                            nowp = _perf()
                            if nowp - self._inline_window_t0 > 0.02:
                                self._inline_window_t0 = nowp
                                self._inline_spent = 0.0
                            inline = self._inline_spent < 0.005
                    if inline:
                        # known-tiny task: the executor handoff costs
                        # more loop work than the function itself
                        t0 = _perf()
                        try:
                            with context_meter.add_callback(_user_metric):
                                value = _call()
                        finally:
                            # _call installed a thread-local task key —
                            # on the LOOP thread here; clear it or every
                            # later coroutine task on the loop reads the
                            # stale key via get_task_key()
                            set_thread_worker(None, None)
                        self._inline_spent += _perf() - t0
                    else:
                        # context_meter callbacks installed here flow
                        # into the fine metrics; copy_context propagates
                        # them into the executor thread so user task
                        # code can emit samples
                        with context_meter.add_callback(_user_metric):
                            ctx = contextvars.copy_context()
                            value = await asyncio.get_running_loop().run_in_executor(
                                self.executor, ctx.run, _call
                            )
                if ts.actor:
                    # keep the instance resident; the task's value is a
                    # placeholder resolved to an Actor proxy client-side
                    from distributed_tpu.client.actor import ActorPlaceholder

                    self.state.actors[key] = value
                    value = ActorPlaceholder(type(value), key, self.address)
            else:
                value = unwrap(run_spec)  # literal data baked into the graph
            stop = time()
            self.digest_metric("compute-duration", stop - start)
            from distributed_tpu.utils.misc import key_split

            out_nbytes = sizeof(value)
            self._execute_fine_metrics(
                ts.span_id, key_split(key), stop - start, out_nbytes
            )
            return ExecuteSuccessEvent(
                stimulus_id=stimulus_id,
                key=key,
                value=value,
                start=start,
                stop=stop,
                nbytes=out_nbytes,
                type=type(value).__name__,
            )
        except Reschedule:
            return RescheduleEvent(stimulus_id=stimulus_id, key=key)
        except BaseException as e:  # noqa: B036 - user code may raise anything
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            if isinstance(e, asyncio.CancelledError) and self.status in (
                Status.closing, Status.closed, Status.failed
            ):
                # worker shutdown cancelled us: propagate (no task-erred).
                # A CancelledError leaking from USER code outside shutdown
                # falls through to the failure path instead — swallowing
                # it would wedge the task in 'executing' with no
                # completion event
                raise
            stop = time()
            e2 = truncate_exception(e)
            return ExecuteFailureEvent(
                stimulus_id=stimulus_id,
                key=key,
                exception=e2,
                traceback=None,
                exception_text=repr(e2),
                traceback_text=format_exception(e),
                start=start,
                stop=stop,
            )

    # ---------------------------------------------------------- gather_dep

    async def _gather_dep(
        self, worker: str, to_gather: tuple, total_nbytes: int, stimulus_id: str
    ) -> StateMachineEvent:
        """Fetch a batch of keys from one peer (reference worker.py:2030).

        Metered through a DelayedMetricsLedger (reference metrics.py:336):
        the instruction spans many loop iterations, and its network /
        deserialize split plus the un-metered remainder ("other": loop
        contention, pool queueing) must land on THIS activity."""
        from distributed_tpu.worker.metrics import (
            DelayedMetricsLedger,
            context_meter,
        )

        ledger = DelayedMetricsLedger(
            lambda label, value, unit: self._fine_metric(
                "gather-dep", None, "", label, unit, value
            )
        )
        try:
            with ledger.activity():
                net_t0 = time()
                try:
                    with context_meter.meter("network"):
                        resp = await self.rpc(worker).get_data(
                            keys=list(to_gather), who=self.address
                        )
                except (CommClosedError, OSError, asyncio.TimeoutError):
                    self.state._gather_finished(worker)
                    return GatherDepNetworkFailureEvent(
                        stimulus_id=stimulus_id, worker=worker,
                        keys=tuple(to_gather),
                    )
                except Exception as e:
                    self.state._gather_finished(worker)
                    return GatherDepFailureEvent(
                        stimulus_id=stimulus_id, worker=worker,
                        keys=tuple(to_gather), exception=e, traceback=None,
                    )
                self.state._gather_finished(worker)
                if resp.get("status") == "busy":
                    return GatherDepBusyEvent(
                        stimulus_id=stimulus_id, worker=worker,
                        keys=tuple(to_gather),
                    )
                # requesting-end link sample (peer -> us): payload bytes
                # as the SERVER sized them over the full fetch duration
                # — the cost the constant model prices, measured.
                # Failed/busy/empty fetches file nothing: no bytes moved
                # (an OK reply whose keys were already released carries
                # zero bytes, and a 0 B/s sample would poison the EWMA).
                payload_nbytes = sum((resp.get("nbytes") or {}).values())
                if payload_nbytes > 0:
                    self.telemetry.record(
                        worker, self.address, payload_nbytes,
                        time() - net_t0,
                    )
                with context_meter.meter("deserialize"):
                    data = {
                        k: unwrap(v) for k, v in resp.get("data", {}).items()
                    }
                    nbytes = sum(sizeof(v) for v in data.values())
            ledger.record("transfer", float(nbytes), "bytes")
        finally:
            # failed/busy fetches must be attributed too — a cluster
            # drowning in transfer retries would otherwise report zero
            # gather-dep network seconds
            ledger.finalize()
        return GatherDepSuccessEvent(
            stimulus_id=stimulus_id,
            worker=worker,
            data=data,
            total_nbytes=nbytes,
        )

    def __repr__(self) -> str:
        try:
            addr = self.address
        except ValueError:
            addr = "not-listening"
        return (
            f"<Worker {addr!r} status={self.status.name} "
            f"executing={len(self.state.executing)} stored={len(self.data)}>"
        )
