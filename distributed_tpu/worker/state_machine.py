"""Worker state machine — pure, deterministic, sans-IO.

The data-plane mirror of the reference's ``worker_state_machine.py``: a
``WorkerState`` holds every task the scheduler has told this worker about and
moves it through the states

    released -> waiting -> {fetch -> flight -> memory | missing}
                        -> {ready | constrained} -> executing -> memory
                                                -> long-running
    (any) -> cancelled/resumed -> released/forgotten, error, rescheduled

via ``handle_stimulus(event) -> [Instructions]`` (reference wsm.py:1330):
events are frozen dataclasses fed by the networked shell; instructions are
what the shell must do (run a task, gather dependencies from a peer, send a
message to the scheduler).  No asyncio, no sockets, no clocks — which makes
every distributed race deterministically reproducible in tests (reference
test strategy, SURVEY.md §4 tier 1).

Scheduling-within-worker mirrors the reference:
- ``ready``/``constrained`` priority heaps; ``_ensure_computing``
  (wsm.py:1726) fills ``nthreads`` slots;
- per-peer ``data_needed`` heaps; ``_ensure_communicating`` (wsm.py:1531)
  batches fetches <= ``transfer.message-bytes-limit`` per peer and
  <= ``connections.incoming`` concurrent peers, skipping busy/in-flight
  peers (wsm.py:1600).
"""

from __future__ import annotations

import functools
import logging
import random
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.diagnostics.census import build_worker_census
from distributed_tpu.diagnostics.selfprofile import WallBudget
from distributed_tpu.exceptions import InvalidTaskState, InvalidTransition
from distributed_tpu.tracing import FlightRecorder
from distributed_tpu.utils import HeapSet, OrderedSet

logger = logging.getLogger("distributed_tpu.worker.state")

Key = str

TASK_STATES = (
    "released",
    "waiting",
    "fetch",
    "flight",
    "missing",
    "ready",
    "constrained",
    "executing",
    "long-running",
    "memory",
    "cancelled",
    "resumed",
    "rescheduled",
    "error",
    "forgotten",
)

READY_STATES = frozenset({"ready", "constrained"})
PROCESSING_STATES = frozenset({"waiting", "ready", "constrained", "executing", "long-running"})
FETCH_STATES = frozenset({"fetch", "flight"})


class WTaskState:
    """Worker-side task record (reference wsm.py:TaskState)."""

    __slots__ = (
        "key",
        "run_spec",
        "state",
        "previous",
        "next",
        "priority",
        "dependencies",
        "dependents",
        "waiting_for_data",
        "waiters",
        "who_has",
        "coming_from",
        "nbytes",
        "duration",
        "resource_restrictions",
        "exception",
        "traceback",
        "exception_text",
        "traceback_text",
        "actor",
        "done",
        "attempt",
        "span_id",
        "annotations",
        "stimulus_id",
        "_hash",
    )

    def __init__(self, key: Key, run_spec: Any = None, priority: tuple = ()):
        self.key = key
        self._hash = hash(key)
        self.run_spec = run_spec
        self.state = "released"
        self.previous: str | None = None  # for cancelled/resumed
        self.next: str | None = None
        self.priority = priority
        # insertion-ordered (utils.collections.OrderedSet), NOT
        # hash-ordered sets: the worker machine iterates these to build
        # recommendations, fetch queues (data_needed row creation) and
        # instructions, so iteration order is decision order — same
        # contract as the scheduler's relation fields (PR 13)
        self.dependencies: OrderedSet[WTaskState] = OrderedSet()
        self.dependents: OrderedSet[WTaskState] = OrderedSet()
        self.waiting_for_data: OrderedSet[WTaskState] = OrderedSet()
        self.waiters: OrderedSet[WTaskState] = OrderedSet()
        self.who_has: OrderedSet[str] = OrderedSet()
        self.coming_from: str | None = None
        self.nbytes = 0
        self.duration: float = -1
        self.resource_restrictions: dict[str, float] = {}
        self.exception: Any = None
        self.traceback: Any = None
        self.exception_text = ""
        self.traceback_text = ""
        self.actor = False
        self.done = False
        self.attempt = 0
        self.span_id: str | None = None
        self.annotations: dict = {}
        self.stimulus_id = ""

    def __repr__(self) -> str:
        return f"<WTaskState {self.key!r} {self.state}>"

    def __hash__(self) -> int:
        return self._hash


# --------------------------------------------------------------------- events


@dataclass(frozen=True)
class StateMachineEvent:
    stimulus_id: str

    @classmethod
    def dummy(cls, stimulus_id: str = "dummy", **kwargs: Any) -> "StateMachineEvent":
        return cls(stimulus_id=stimulus_id, **kwargs)


@dataclass(frozen=True)
class ComputeTaskEvent(StateMachineEvent):
    """Scheduler asks this worker to run a task (reference wsm.py:738)."""

    key: Key
    run_spec: Any = None
    priority: tuple = ()
    who_has: dict[Key, list[str]] = field(default_factory=dict)
    nbytes: dict[Key, int] = field(default_factory=dict)
    duration: float = 0.5
    resource_restrictions: dict[str, float] = field(default_factory=dict)
    actor: bool = False
    annotations: dict = field(default_factory=dict)
    span_id: str | None = None

    @classmethod
    def dummy(cls, key: Key = "x", stimulus_id: str = "dummy", **kwargs: Any):
        kwargs.setdefault("run_spec", _DummySpec())
        return cls(stimulus_id=stimulus_id, key=key, **kwargs)


class _DummySpec:
    def substitute(self, data):
        return (lambda: None), (), {}


@dataclass(frozen=True)
class ExecuteSuccessEvent(StateMachineEvent):
    key: Key = ""
    value: Any = None
    start: float = 0.0
    stop: float = 0.0
    nbytes: int = 0
    type: str | None = None


@dataclass(frozen=True)
class ExecuteFailureEvent(StateMachineEvent):
    key: Key = ""
    exception: Any = None
    traceback: Any = None
    exception_text: str = ""
    traceback_text: str = ""
    start: float = 0.0
    stop: float = 0.0


@dataclass(frozen=True)
class RescheduleEvent(StateMachineEvent):
    key: Key = ""


@dataclass(frozen=True)
class LongRunningEvent(StateMachineEvent):
    """Task called secede() (reference worker.py:2799)."""

    key: Key = ""
    compute_duration: float = 0.0


@dataclass(frozen=True)
class GatherDepSuccessEvent(StateMachineEvent):
    worker: str = ""
    data: dict[Key, Any] = field(default_factory=dict)
    total_nbytes: int = 0


@dataclass(frozen=True)
class GatherDepBusyEvent(StateMachineEvent):
    worker: str = ""
    keys: tuple = ()


@dataclass(frozen=True)
class GatherDepNetworkFailureEvent(StateMachineEvent):
    worker: str = ""
    keys: tuple = ()


@dataclass(frozen=True)
class GatherDepFailureEvent(StateMachineEvent):
    """Deserialization or other local error while receiving."""

    worker: str = ""
    keys: tuple = ()
    exception: Any = None
    traceback: Any = None


@dataclass(frozen=True)
class FreeKeysEvent(StateMachineEvent):
    keys: tuple = ()


@dataclass(frozen=True)
class RemoveReplicasEvent(StateMachineEvent):
    keys: tuple = ()


@dataclass(frozen=True)
class AcquireReplicasEvent(StateMachineEvent):
    """AMM asks this worker to fetch replicas (reference wsm.py)."""

    who_has: dict[Key, list[str]] = field(default_factory=dict)
    nbytes: dict[Key, int] = field(default_factory=dict)


@dataclass(frozen=True)
class StealRequestEvent(StateMachineEvent):
    key: Key = ""


@dataclass(frozen=True)
class UpdateDataEvent(StateMachineEvent):
    """Client scattered data directly to this worker.

    ``report=False`` suppresses the add-keys message — used by scatter,
    where the scheduler registers the replicas itself and an early
    add-keys would race with that registration (reference worker.py
    update_data(report=False)).
    """

    data: dict[Key, Any] = field(default_factory=dict)
    report: bool = True


@dataclass(frozen=True)
class PauseEvent(StateMachineEvent):
    pass


@dataclass(frozen=True)
class UnpauseEvent(StateMachineEvent):
    pass


@dataclass(frozen=True)
class RetryBusyWorkerEvent(StateMachineEvent):
    worker: str = ""


@dataclass(frozen=True)
class FindMissingEvent(StateMachineEvent):
    pass


@dataclass(frozen=True)
class RefreshWhoHasEvent(StateMachineEvent):
    who_has: dict[Key, list[str]] = field(default_factory=dict)


# --------------------------------------------------------------- instructions


@dataclass(frozen=True)
class Instruction:
    stimulus_id: str


@dataclass(frozen=True)
class Execute(Instruction):
    key: Key = ""


@dataclass(frozen=True)
class GatherDep(Instruction):
    worker: str = ""
    to_gather: tuple = ()
    total_nbytes: int = 0


@dataclass(frozen=True)
class RetryBusyWorkerLater(Instruction):
    worker: str = ""


@dataclass(frozen=True)
class SendMessageToScheduler(Instruction):
    pass

    def to_dict(self) -> dict:
        d = {
            k: getattr(self, k)
            for k in self.__dataclass_fields__
        }
        d["op"] = self.op  # type: ignore[attr-defined]
        return d


@dataclass(frozen=True)
class TaskFinishedMsg(SendMessageToScheduler):
    op = "task-finished"
    key: Key = ""
    nbytes: int = 0
    typename: str | None = None
    startstops: tuple = ()
    metadata: dict = field(default_factory=dict)


@dataclass(frozen=True)
class TaskErredMsg(SendMessageToScheduler):
    op = "task-erred"
    key: Key = ""
    exception: Any = None
    traceback: Any = None
    exception_text: str = ""
    traceback_text: str = ""
    startstops: tuple = ()


@dataclass(frozen=True)
class ReleaseWorkerDataMsg(SendMessageToScheduler):
    op = "release-worker-data"
    key: Key = ""


@dataclass(frozen=True)
class RescheduleMsg(SendMessageToScheduler):
    op = "reschedule"
    key: Key = ""


@dataclass(frozen=True)
class LongRunningMsg(SendMessageToScheduler):
    op = "long-running"
    key: Key = ""
    compute_duration: float = 0.0


@dataclass(frozen=True)
class AddKeysMsg(SendMessageToScheduler):
    op = "add-keys"
    keys: tuple = ()


@dataclass(frozen=True)
class StealResponseMsg(SendMessageToScheduler):
    op = "steal-response"
    key: Key = ""
    state: str | None = None


@dataclass(frozen=True)
class MissingDataMsg(SendMessageToScheduler):
    op = "missing-data"
    key: Key = ""
    errant_worker: str = ""


@dataclass(frozen=True)
class RequestRefreshWhoHasMsg(SendMessageToScheduler):
    op = "request-refresh-who-has"
    keys: tuple = ()


Instructions = list  # list[Instruction]
Recs = dict  # dict[WTaskState, str]


class WorkerState:
    """Pure worker state (reference worker_state_machine.py:1060)."""

    def __init__(
        self,
        *,
        nthreads: int = 1,
        address: str = "",
        data: dict | None = None,
        resources: dict[str, float] | None = None,
        validate: bool | None = None,
        transfer_incoming_count_limit: int | None = None,
        transfer_message_bytes_limit: int | None = None,
        execute_pipeline: int = 0,
        execute_pipeline_threshold: float = 0.005,
        clock: Callable[[], float] | None = None,
    ):
        self.address = address
        self.nthreads = nthreads
        # issue up to this many EXTRA Executes beyond nthreads for tasks
        # whose scheduler duration estimate is below the threshold: the
        # server coalesces one instruction batch of tiny tasks into a
        # single executor submission (one thread handoff + one loop
        # wakeup for the whole batch instead of per task).  Unknown
        # prefixes (duration = UNKNOWN 0.5 s) never pipeline, so a slow
        # first-of-its-kind task cannot hide behind the gate.
        self.execute_pipeline = execute_pipeline
        self.execute_pipeline_threshold = execute_pipeline_threshold
        self.data: dict[Key, Any] = data if data is not None else {}
        self.tasks: dict[Key, WTaskState] = {}
        self.ready: HeapSet[WTaskState] = HeapSet(key=lambda ts: ts.priority)
        self.constrained: deque[WTaskState] = deque()
        # insertion-ordered: cancellation/pause sweeps and the census
        # walk these, and missing-dep retries re-enqueue in scan order
        self.executing: OrderedSet[WTaskState] = OrderedSet()
        self.long_running: OrderedSet[WTaskState] = OrderedSet()
        self.in_flight_tasks: OrderedSet[WTaskState] = OrderedSet()
        self.missing_dep_flight: OrderedSet[WTaskState] = OrderedSet()
        # fetch queues: per-peer heap of tasks to pull
        self.data_needed: defaultdict[str, HeapSet[WTaskState]] = defaultdict(
            lambda: HeapSet(key=lambda ts: ts.priority)
        )
        self.in_flight_workers: dict[str, OrderedSet[Key]] = {}
        self.busy_workers: OrderedSet[str] = OrderedSet()
        self.has_what: defaultdict[str, OrderedSet[Key]] = defaultdict(OrderedSet)
        self.actors: dict[Key, Any] = {}
        self.total_resources = dict(resources or {})
        self.available_resources = dict(resources or {})
        self.running = True  # False when paused
        self.transfer_incoming_count = 0
        self.transfer_incoming_bytes = 0
        self.transfer_incoming_count_limit = (
            transfer_incoming_count_limit
            if transfer_incoming_count_limit is not None
            else config.get("worker.connections.incoming")
        )
        self.transfer_message_bytes_limit = (
            transfer_message_bytes_limit
            if transfer_message_bytes_limit is not None
            else config.parse_bytes(config.get("worker.transfer.message-bytes-limit"))
        )
        self.validate = (
            validate if validate is not None else config.get("worker.validate")
        )
        self.nbytes_in_memory = 0
        self.transition_counter = 0
        self.log: deque = deque(maxlen=100_000)
        self.stimulus_log: deque = deque(maxlen=10_000)
        # flight recorder (tracing.py): stimulus batches land here with
        # the same scheduler-minted stimulus ids the scheduler's ring
        # carries, so /trace on both roles joins on one causal id.
        # This machine never reads a clock itself — the injectable
        # ``clock`` (ROADMAP item 1 simulator) only re-stamps its trace
        # ring onto virtual time.
        self.trace = FlightRecorder()
        if clock is not None:
            self.trace.clock = clock
        # wall-budget phase attribution (diagnostics/selfprofile.py):
        # ``wengine.stimulus`` per handle_stimulus batch, plus opt-in
        # ``wengine.scalar-arm:<start>,<finish>`` arms — always REAL
        # monotonic time (python cost, not virtual time), so the
        # injectable clock above deliberately does not re-point it
        self.wall = WallBudget()
        self.WALL_ARMS: bool = bool(
            config.get("scheduler.profile.arm-attribution", False)
        )
        self._arm_phases: dict[tuple[str, str], str] = {}
        self.rng = random.Random(0)  # deterministic (reference wsm.py:1328)
        self.task_counter: defaultdict[str, int] = defaultdict(int)

        self._transitions_table: dict[tuple[str, str], Callable] = {
            ("released", "waiting"): self._transition_released_waiting,
            ("released", "fetch"): self._transition_released_fetch,
            # released_fetch recommends "missing" when the dep has NO
            # known holders (a compute-task/acquire-replicas can name a
            # dep whose replicas just vanished): without this edge that
            # recommendation raised InvalidTransition and killed the
            # stimulus batch (found by the simulator's worker suite)
            ("released", "missing"): self._transition_fetch_missing,
            ("released", "memory"): self._transition_released_memory,
            ("released", "forgotten"): self._transition_released_forgotten,
            ("waiting", "ready"): self._transition_waiting_ready,
            ("waiting", "constrained"): self._transition_waiting_constrained,
            ("waiting", "released"): self._transition_generic_released,
            ("ready", "executing"): self._transition_ready_executing,
            ("ready", "released"): self._transition_generic_released,
            ("constrained", "executing"): self._transition_constrained_executing,
            ("constrained", "released"): self._transition_generic_released,
            ("executing", "memory"): self._transition_executing_memory,
            ("executing", "error"): self._transition_executing_error,
            ("executing", "released"): self._transition_executing_released,
            ("executing", "rescheduled"): self._transition_executing_rescheduled,
            ("executing", "long-running"): self._transition_executing_long_running,
            ("long-running", "memory"): self._transition_executing_memory,
            ("long-running", "error"): self._transition_executing_error,
            ("long-running", "released"): self._transition_executing_released,
            ("long-running", "rescheduled"): self._transition_executing_rescheduled,
            # a fetch/missing/error task re-targeted as a COMPUTE: the
            # compute-task handler wires waiting_for_data BEFORE the
            # transition, and the released fallback would wipe it —
            # released->waiting then sees no pending deps and sends the
            # task to ready with its inputs absent (tripped the ready
            # invariant; found by the simulator's partition chaos
            # scenario, where the recompute of a task whose replica the
            # partition stripped lands on a worker that had it "missing")
            ("missing", "waiting"): self._transition_redirected_waiting,
            ("fetch", "waiting"): self._transition_redirected_waiting,
            ("error", "waiting"): self._transition_redirected_waiting,
            ("fetch", "flight"): self._transition_fetch_flight,
            ("fetch", "released"): self._transition_generic_released,
            ("fetch", "missing"): self._transition_fetch_missing,
            ("flight", "memory"): self._transition_flight_memory,
            ("flight", "fetch"): self._transition_flight_fetch,
            ("flight", "released"): self._transition_flight_released,
            ("flight", "missing"): self._transition_flight_missing,
            # local failure while receiving (deserialization error): a
            # direct edge — the released fallback would park the task in
            # "cancelled" via flight->released (previous="flight" left
            # stale) and then release execution resources the fetch
            # never held on the cancelled->error hop (found by the
            # state-machine lint, rule 9)
            ("flight", "error"): self._transition_flight_error,
            ("missing", "fetch"): self._transition_missing_fetch,
            ("missing", "released"): self._transition_generic_released,
            ("memory", "released"): self._transition_memory_released,
            ("cancelled", "released"): self._transition_cancelled_released,
            ("cancelled", "memory"): self._transition_cancelled_memory,
            ("cancelled", "error"): self._transition_cancelled_error,
            ("cancelled", "rescheduled"): self._transition_cancelled_released,
            ("cancelled", "waiting"): self._transition_cancelled_waiting,
            ("cancelled", "fetch"): self._transition_cancelled_fetch,
            # resumed (cancelled then wanted again) execute ending in
            # Reschedule: nothing was produced — tell the scheduler to
            # re-place it, exactly like an executing task would
            ("resumed", "rescheduled"): self._transition_executing_rescheduled,
            ("resumed", "memory"): self._transition_executing_memory,
            ("resumed", "released"): self._transition_resumed_released,
            ("resumed", "error"): self._transition_executing_error,
            ("resumed", "fetch"): self._transition_resumed_fetch,
            ("resumed", "missing"): self._transition_resumed_missing,
            ("error", "released"): self._transition_generic_released,
            ("rescheduled", "released"): self._transition_generic_released,
        }

        # state census (diagnostics/census.py): typed inventory of every
        # long-lived container above — the scheduler-side census's
        # worker twin (docs/observability.md).  Built LAZILY on first
        # access: a census is ~17 KiB of probe closures, and the
        # simulator instantiates 10,000 of these machines whose
        # censuses are only read at the quiesce gate (or under
        # DTPU_CENSUS_CHECK).
        self._census: Any = None

    @property
    def census(self) -> Any:
        c = self._census
        if c is None:
            c = self._census = build_worker_census(self)
        return c

    # ------------------------------------------------------------- stimulus

    def handle_stimulus(self, *events: StateMachineEvent) -> Instructions:
        """Feed events, return the instructions the shell must execute
        (reference wsm.py:1330).

        The computing/communicating drains run ONCE per event batch, not
        per event: a scheduler stream payload carrying a whole tile of
        compute-task messages must aggregate its missing deps into few
        GatherDep instructions — per-event drains fired a 1-key request
        per message (measured 1.4 keys per gather on the tensordot
        bench, with per-request loop cost dwarfing the payload)."""
        instructions: Instructions = []
        tr = self.trace
        self.wall.push(
            "wengine.stimulus", events[0].stimulus_id if events else ""
        )
        # arm-attribution mode also breaks out the event-handler bodies
        # and ensure drains, so the worker half of sim.profile_run's
        # table names every compiled-core candidate, not only the arms
        arms = self.WALL_ARMS
        wall = self.wall
        try:
            for event in events:
                self.stimulus_log.append(event)
                # task-level trace hop (sampled): the payload-boundary batch
                # arrives as one handle_stimulus call, so each event's
                # stimulus id joins the scheduler envelope that carried it
                tr.emit_task("wstim", type(event).__name__, event.stimulus_id)
                handler = getattr(self, "_handle_" + _snake(type(event).__name__))
                if arms:
                    wall.push(
                        self._handler_phase(type(event).__name__),
                        event.stimulus_id,
                    )
                try:
                    recs, instr = handler(event)
                finally:
                    if arms:
                        wall.pop()
                instructions += instr
                instructions += self._transitions(recs, stimulus_id=event.stimulus_id)
            stimulus_id = events[-1].stimulus_id if events else "ensure"
            if arms:
                with wall.phase("wengine.ensure-computing", stimulus_id):
                    instructions += self._ensure_computing(stimulus_id)
                with wall.phase("wengine.ensure-communicating", stimulus_id):
                    instructions += self._ensure_communicating(stimulus_id)
            else:
                instructions += self._ensure_computing(stimulus_id)
                instructions += self._ensure_communicating(stimulus_id)
            if self.validate:
                self.validate_state()
            return instructions
        finally:
            wall.pop()

    # -------------------------------------------------------- event handlers

    def _handle_compute_task(self, ev: ComputeTaskEvent) -> tuple[Recs, Instructions]:
        ts = self.tasks.get(ev.key)
        if ts is None:
            ts = self.tasks[ev.key] = WTaskState(ev.key)
        ts.run_spec = ev.run_spec
        ts.priority = tuple(ev.priority)
        ts.duration = ev.duration
        ts.resource_restrictions = dict(ev.resource_restrictions)
        ts.actor = ev.actor
        ts.annotations = dict(ev.annotations)
        ts.span_id = ev.span_id
        ts.stimulus_id = ev.stimulus_id

        recs: Recs = {}
        if ts.state in ("executing", "long-running", "waiting",
                        "ready", "constrained"):
            # duplicate compute-task: already underway
            return recs, []
        if ts.state == "memory":
            return recs, [
                TaskFinishedMsg(
                    stimulus_id=ev.stimulus_id,
                    key=ts.key,
                    nbytes=ts.nbytes,
                    typename=None,
                    startstops=(),
                )
            ]
        # released / fetch / flight / missing / cancelled / resumed /
        # error: recommend "waiting" — the cancelled/resumed transitions
        # (and the through-released fallback) turn interrupted fetches
        # and executions into resumed-towards-compute
        # (reference wsm.py:2851-2861)

        # wire up dependencies
        for dep_key, workers in ev.who_has.items():
            dts = self.tasks.get(dep_key)
            if dts is None:
                dts = self.tasks[dep_key] = WTaskState(dep_key)
                dts.priority = ts.priority
            # drop has_what rows for peers the fresh view no longer
            # names (e.g. a dead worker): the replacement below would
            # otherwise strand them forever (census-found)
            for w in dts.who_has.difference(workers):
                self._drop_has_what(w, dep_key)
            dts.who_has = OrderedSet(workers)
            dts.nbytes = ev.nbytes.get(dep_key, dts.nbytes)
            ts.dependencies.add(dts)
            dts.dependents.add(ts)
            if dts.state not in ("memory", "flight", "executing", "long-running"):
                if dep_key in self.data:
                    recs[dts] = "memory"
                else:
                    ts.waiting_for_data.add(dts)
                    dts.waiters.add(ts)
                    if dts.state not in FETCH_STATES and dts.state not in (
                        "missing",
                        # locally QUEUED to (re)compute: recommending a
                        # fetch would route ready->released->fetch and
                        # discard the scheduler-assigned local compute —
                        # wait for _put_memory like any local producer
                        "ready", "constrained", "waiting",
                    ):
                        recs[dts] = "fetch"
            elif dts.state in ("flight", "executing", "long-running"):
                # the dep's data isn't here yet in EITHER case: in
                # flight from a peer, or being (re)computed locally — a
                # freed-then-recomputed dep races exactly like a fetch
                # (found by the tcp race suite: the dependent went
                # waiting->ready with the dep still executing and no
                # data, tripping the ready invariant).  If the local
                # execution ERRS instead, the scheduler's erred cascade
                # frees this dependent (it has the dep as processing
                # here, so the task-erred report is never fenced) and
                # generic_released clears waiting_for_data — same
                # resolution as a flight dep whose gather fails.
                ts.waiting_for_data.add(dts)
                dts.waiters.add(ts)
        # sever dependency edges from a previous incarnation that this
        # compute-task no longer names: ``who_has`` carries EVERY
        # current dependency (the target's own replicas included), so
        # an edge absent from it is scheduler-authoritative stale —
        # e.g. a pure-data input forgotten after its last replica
        # vanished, whose recompute proceeds without it.  Left in
        # place, waiting->ready demanded data that could never come
        # (partition chaos + the census-era remove-replicas repair
        # reproduced it deterministically).  Sorted: relation sets are
        # hash-ordered here, and the forget recommendations must land
        # in a process-independent order.
        stale = sorted(
            (d for d in ts.dependencies if d.key not in ev.who_has),
            key=lambda d: d.key,
        )
        for dts in stale:
            ts.dependencies.discard(dts)
            dts.dependents.discard(ts)
            ts.waiting_for_data.discard(dts)
            dts.waiters.discard(ts)
            if not dts.dependents and dts.state == "released":
                recs[dts] = "forgotten"
        recs[ts] = "waiting"
        return recs, []

    def _handle_execute_success(self, ev: ExecuteSuccessEvent) -> tuple[Recs, Instructions]:
        ts = self.tasks.get(ev.key)
        if ts is None:
            return {}, []
        ts.done = True
        if ts.state == "cancelled":
            return {ts: "released"}, []
        ts.nbytes = ev.nbytes
        self.data[ts.key] = ev.value
        return {ts: ("memory", ev)}, []

    def _handle_execute_failure(self, ev: ExecuteFailureEvent) -> tuple[Recs, Instructions]:
        ts = self.tasks.get(ev.key)
        if ts is None:
            return {}, []
        ts.done = True
        if ts.state == "cancelled":
            return {ts: "released"}, []
        return {ts: ("error", ev)}, []

    def _handle_reschedule(self, ev: RescheduleEvent) -> tuple[Recs, Instructions]:
        ts = self.tasks.get(ev.key)
        if ts is None:
            return {}, []
        ts.done = True
        return {ts: "rescheduled"}, []

    def _handle_long_running(self, ev: LongRunningEvent) -> tuple[Recs, Instructions]:
        ts = self.tasks.get(ev.key)
        if ts is None:
            return {}, []
        if ts.state == "executing":
            return {ts: ("long-running", ev)}, []
        if ts.state in ("cancelled", "resumed") and ts.previous == "executing":
            # the cancelled/resumed body is still running and just
            # seceded: free the slot NOW (the whole point of seceding)
            # and remember it as long-running so completion accounting
            # stays right (reference wsm.py sets previous accordingly —
            # dropping the event here re-wedges the worker the shuffle
            # secede fix exists for)
            self.executing.discard(ts)
            self.long_running.add(ts)
            ts.previous = "long-running"
        return {}, []

    def _handle_gather_dep_success(self, ev: GatherDepSuccessEvent) -> tuple[Recs, Instructions]:
        recs: Recs = {}
        instr: Instructions = []
        self._gather_finished(ev.worker)
        received = set(ev.data)
        stored: list[Key] = []
        for key, value in ev.data.items():
            ts = self.tasks.get(key)
            if ts is None or ts.state not in ("flight", "resumed"):
                # unsolicited data (e.g. the fetch was cancelled mid-
                # flight): drop it — and do NOT announce it, or the
                # scheduler would record a phantom replica here that
                # peers then try to fetch forever (livelock)
                if ts is not None and ts.state == "cancelled":
                    recs[ts] = "released"
                continue
            # "resumed": the fetch was cancelled then the key re-requested
            # as a compute — the arrived value satisfies it directly; no
            # Execute exists to complete it otherwise (wedge)
            if ts.state == "resumed":
                self.in_flight_tasks.discard(ts)
                ts.coming_from = None
                # resumed -> memory emits TaskFinishedMsg, which already
                # registers the replica — no AddKeysMsg needed
                self.data[key] = value
                recs[ts] = "memory"
                continue
            self.data[key] = value
            stored.append(key)
            recs[ts] = "memory"
        if stored:
            instr.append(AddKeysMsg(stimulus_id=ev.stimulus_id, keys=tuple(stored)))
        # keys requested but not received: the peer no longer has them.
        # Tell the scheduler (missing-data) so it drops the stale replica
        # from who_has — otherwise refresh-who-has keeps pointing us back
        # at the same errant peer (reference scheduler.py handle_missing_data)
        requested = self.in_flight_workers.pop(ev.worker, set())
        for key in requested - received:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            self.in_flight_tasks.discard(ts)
            ts.coming_from = None
            ts.who_has.discard(ev.worker)
            self._drop_has_what(ev.worker, key)
            instr.append(
                MissingDataMsg(
                    stimulus_id=ev.stimulus_id, key=key, errant_worker=ev.worker
                )
            )
            if ts.state == "flight":
                recs[ts] = "fetch" if ts.who_has else "missing"
            elif ts.state == "cancelled":
                ts.done = True
                recs[ts] = "released"
            elif ts.state == "resumed":
                # the fetch ended empty-handed but the scheduler asked for
                # a compute meanwhile: done=True lets resumed->fetch fall
                # through released->waiting and run it
                ts.done = True
                recs[ts] = "fetch"
        return recs, instr

    def _handle_gather_dep_busy(self, ev: GatherDepBusyEvent) -> tuple[Recs, Instructions]:
        self._gather_finished(ev.worker)
        self.busy_workers.add(ev.worker)
        recs: Recs = {}
        requested = self.in_flight_workers.pop(ev.worker, set())
        for key in requested:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            self.in_flight_tasks.discard(ts)
            ts.coming_from = None
            if ts.state == "flight":
                recs[ts] = "fetch"
            elif ts.state == "cancelled":
                ts.done = True
                recs[ts] = "released"
            elif ts.state == "resumed":
                ts.done = True
                recs[ts] = "fetch"
        return recs, [
            RetryBusyWorkerLater(stimulus_id=ev.stimulus_id, worker=ev.worker)
        ]

    def _handle_gather_dep_network_failure(
        self, ev: GatherDepNetworkFailureEvent
    ) -> tuple[Recs, Instructions]:
        self._gather_finished(ev.worker)
        recs: Recs = {}
        instr: Instructions = []
        requested = self.in_flight_workers.pop(ev.worker, set())
        for key in requested:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            self.in_flight_tasks.discard(ts)
            ts.coming_from = None
            ts.who_has.discard(ev.worker)
            self._drop_has_what(ev.worker, key)
            instr.append(
                MissingDataMsg(
                    stimulus_id=ev.stimulus_id, key=key, errant_worker=ev.worker
                )
            )
            if ts.state == "flight":
                recs[ts] = "fetch" if ts.who_has else "missing"
            elif ts.state == "cancelled":
                ts.done = True
                recs[ts] = "released"
            elif ts.state == "resumed":
                ts.done = True
                recs[ts] = "fetch"
        return recs, instr

    def _handle_gather_dep_failure(self, ev: GatherDepFailureEvent) -> tuple[Recs, Instructions]:
        self._gather_finished(ev.worker)
        recs: Recs = {}
        requested = self.in_flight_workers.pop(ev.worker, set())
        for key in requested:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            self.in_flight_tasks.discard(ts)
            ts.coming_from = None
            ts.exception = ev.exception
            ts.traceback = ev.traceback
            if ts.state == "flight":
                recs[ts] = ("error", ev)
            else:
                recs[ts] = "released"
        return recs, []

    def _handle_free_keys(self, ev: FreeKeysEvent) -> tuple[Recs, Instructions]:
        """Scheduler says these keys are no longer needed (cancellation)."""
        recs: Recs = {}
        for key in ev.keys:
            ts = self.tasks.get(key)
            if ts is not None:
                recs[ts] = "released"
        return recs, []

    def _handle_remove_replicas(self, ev: RemoveReplicasEvent) -> tuple[Recs, Instructions]:
        """AMM drops replicas; only memory tasks without local waiters go."""
        recs: Recs = {}
        instr: Instructions = []
        for key in ev.keys:
            ts = self.tasks.get(key)
            if ts is None:
                continue
            if ts.state == "memory" and not any(
                d.state in PROCESSING_STATES for d in ts.dependents
            ):
                recs[ts] = "released"
                instr.append(ReleaseWorkerDataMsg(stimulus_id=ev.stimulus_id, key=key))
            elif ts.state == "memory":
                instr.append(AddKeysMsg(stimulus_id=ev.stimulus_id, keys=(key,)))
        return recs, instr

    def _handle_acquire_replicas(self, ev: AcquireReplicasEvent) -> tuple[Recs, Instructions]:
        recs: Recs = {}
        for key, workers in ev.who_has.items():
            ts = self.tasks.get(key)
            if ts is None:
                ts = self.tasks[key] = WTaskState(key)
                ts.priority = (1_000_000,)  # replicas fetch at low priority
            for w in ts.who_has.difference(workers):
                self._drop_has_what(w, key)
            ts.who_has = OrderedSet(workers)
            ts.nbytes = ev.nbytes.get(key, ts.nbytes)
            if ts.state in ("released", "missing") and key not in self.data:
                recs[ts] = "fetch"
        return recs, []

    def _handle_steal_request(self, ev: StealRequestEvent) -> tuple[Recs, Instructions]:
        """Reference stealing.py:44-60: give up the task iff it has not
        started running."""
        ts = self.tasks.get(ev.key)
        state = ts.state if ts is not None else None
        instr: Instructions = [
            StealResponseMsg(stimulus_id=ev.stimulus_id, key=ev.key, state=state)
        ]
        recs: Recs = {}
        if ts is not None and state in ("ready", "constrained", "waiting"):
            recs[ts] = "released"
        return recs, instr

    def _handle_update_data(self, ev: UpdateDataEvent) -> tuple[Recs, Instructions]:
        recs: Recs = {}
        instr: Instructions = []
        for key, value in ev.data.items():
            ts = self.tasks.get(key)
            if ts is None:
                ts = self.tasks[key] = WTaskState(key)
                ts.priority = (0,)
            self.data[key] = value
            if ts.state in ("flight", "executing", "long-running", "cancelled",
                            "resumed"):
                # route through the transition table so in_flight/executing
                # bookkeeping is exited properly
                recs[ts] = "memory"
            else:
                r, i = self._put_memory(
                    ts, ev.stimulus_id, send_add_keys=ev.report
                )
                recs.update(r)
                instr += i
        return recs, instr

    def _handle_pause(self, ev: PauseEvent) -> tuple[Recs, Instructions]:
        self.running = False
        return {}, []

    def _handle_unpause(self, ev: UnpauseEvent) -> tuple[Recs, Instructions]:
        self.running = True
        return {}, []

    def _handle_retry_busy_worker(self, ev: RetryBusyWorkerEvent) -> tuple[Recs, Instructions]:
        self.busy_workers.discard(ev.worker)
        return {}, []

    def _handle_find_missing(self, ev: FindMissingEvent) -> tuple[Recs, Instructions]:
        missing = [
            ts for ts in self.tasks.values() if ts.state == "missing"
        ]
        if not missing:
            return {}, []
        return {}, [
            RequestRefreshWhoHasMsg(
                stimulus_id=ev.stimulus_id, keys=tuple(ts.key for ts in missing)
            )
        ]

    def _handle_refresh_who_has(self, ev: RefreshWhoHasEvent) -> tuple[Recs, Instructions]:
        recs: Recs = {}
        for key, workers in ev.who_has.items():
            ts = self.tasks.get(key)
            if ts is None:
                continue
            # drop rows for peers that no longer hold the key — a
            # refresh that only ever added left one has_what row per
            # departed replica behind (census-found)
            for w in ts.who_has.difference(workers):
                self._drop_has_what(w, key)
            ts.who_has = OrderedSet(workers)
            for w in workers:
                self.has_what[w].add(key)
            if ts.state == "missing" and ts.who_has:
                recs[ts] = "fetch"
            elif ts.state == "fetch" and not ts.who_has:
                recs[ts] = "missing"
        return recs, []

    # ------------------------------------------------------ transition engine

    def _transitions(self, recs: Recs, stimulus_id: str) -> Instructions:
        instructions: Instructions = []
        remaining = dict(recs)
        while remaining:
            ts, finish = remaining.popitem()
            instructions += self._transition(ts, finish, stimulus_id, remaining)
        return instructions

    def _transition(
        self, ts: WTaskState, finish: Any, stimulus_id: str, remaining: dict
    ) -> Instructions:
        recs, instructions = self._do_transition(ts, finish, stimulus_id)
        remaining.update(recs)
        return instructions

    def _do_transition(
        self, ts: WTaskState, finish: Any, stimulus_id: str
    ) -> tuple[Recs, Instructions]:
        kwargs: dict = {}
        if isinstance(finish, tuple):
            finish, payload = finish
            kwargs["payload"] = payload
        start = ts.state
        if start == finish:
            return {}, []
        self.transition_counter += 1
        # opt-in per-arm wall attribution (sim.profile_run's table);
        # routed pairs nest their released-leg arms, so self-time is
        # exact — mirrors SchedulerState._transition
        arms = self.WALL_ARMS
        if arms:
            self.wall.push(self._arm_phase(start, str(finish)), stimulus_id)
        try:
            func = self._transitions_table.get((start, finish))
            if func is not None:
                recs, instructions = func(ts, stimulus_id=stimulus_id, **kwargs)
                self.log.append((ts.key, start, ts.state, stimulus_id))
                return recs, instructions
            if "released" not in (start, finish):
                # no direct edge: route start -> released -> finish, replaying
                # any intermediate recommendations for ts along the way but
                # never forgetting it (reference wsm.py:2602-2629)
                recs, instructions = self._do_transition(
                    ts, "released", stimulus_id
                )
                while (v := recs.pop(ts, None)) is not None:
                    v_state = v[0] if isinstance(v, tuple) else v
                    if v_state == "forgotten":
                        continue
                    r2, i2 = self._do_transition(ts, v, stimulus_id)
                    recs.update(r2)
                    instructions += i2
                r3, i3 = self._do_transition(
                    ts, (finish, kwargs["payload"]) if kwargs else finish,
                    stimulus_id,
                )
                recs.update(r3)
                instructions += i3
                return recs, instructions
            raise InvalidTransition(ts.key, start, str(finish), list(self.log))
        finally:
            if arms:
                self.wall.pop()

    def _arm_phase(self, start: str, finish: str) -> str:
        """Interned wall-budget phase name for one worker transition arm."""
        p = self._arm_phases.get((start, finish))
        if p is None:
            p = self._arm_phases[(start, finish)] = (
                f"wengine.scalar-arm:{start},{finish}"
            )
        return p

    def _handler_phase(self, event_name: str) -> str:
        """Interned phase name for one stimulus-handler body."""
        key = (event_name, "")
        p = self._arm_phases.get(key)
        if p is None:
            p = self._arm_phases[key] = f"wengine.handler:{event_name}"
        return p

    # ------------------------------------------------------------- handlers

    def _transition_released_waiting(self, ts, *, stimulus_id):
        ts.state = "waiting"
        recs: Recs = {}
        if not ts.waiting_for_data:
            recs[ts] = "constrained" if ts.resource_restrictions else "ready"
        return recs, []

    def _transition_released_fetch(self, ts, *, stimulus_id):
        if not ts.who_has:
            return {ts: "missing"}, []
        ts.state = "fetch"
        for w in ts.who_has:
            self.has_what[w].add(ts.key)
            self.data_needed[w].add(ts)
        return {}, []

    def _transition_released_memory(self, ts, *, stimulus_id, payload=None):
        # ``payload`` arrives when an in-flight execute completes for a
        # task that went released (not cancelled-parked) in the
        # meantime: _handle_execute_success already stored the value
        # and nbytes, so keeping the replica and announcing it via
        # add-keys is the right outcome — the scheduler either wants it
        # or answers remove-replicas.  Without the parameter this arm
        # raised TypeError and killed the whole stimulus batch
        # (PYTHONHASHSEED-dependent crash found by the partition chaos
        # scenario; pre-existing — reproduced on the parent commit at
        # seeds 5 and 11).
        return self._put_memory(ts, stimulus_id, send_add_keys=True)

    def _transition_released_forgotten(self, ts, *, stimulus_id):
        if ts.dependents:
            return {}, []
        recs: Recs = {}
        for dts in ts.dependencies:
            dts.dependents.discard(ts)
            dts.waiters.discard(ts)
            if not dts.dependents and dts.state == "released":
                # orphaned released dependency: no release path will
                # ever run for it again, so forget it NOW (reference
                # wsm.py does the same; the old no-op here retained
                # ~14% of WTaskStates per chunk — found by the state
                # census's quiesce gate, tests/test_census.py)
                recs[dts] = "forgotten"
        ts.dependencies.clear()
        self._purge_replicas(ts)
        self.tasks.pop(ts.key, None)
        ts.state = "forgotten"
        return recs, []

    def _transition_redirected_waiting(self, ts, *, stimulus_id):
        """A data-target (fetch/missing) or failed task re-assigned as a
        compute: leave the dependency wiring the compute-task handler
        just built intact and enter waiting directly — the released
        fallback would clear ``waiting_for_data`` and race the task to
        ready without its inputs."""
        self._purge_data_needed(ts)
        ts.exception = None
        ts.traceback = None
        ts.exception_text = ""
        ts.traceback_text = ""
        return self._transition_released_waiting(ts, stimulus_id=stimulus_id)

    def _transition_waiting_ready(self, ts, *, stimulus_id):
        if self.validate:
            assert not ts.waiting_for_data, ts
            assert all(d.key in self.data for d in ts.dependencies), (
                ts,
                [(d.key, d.state, d.key in self.data)
                 for d in ts.dependencies],
                list(self.stimulus_log)[-8:],
            )
        ts.state = "ready"
        self.ready.add(ts)
        return {}, []

    def _transition_waiting_constrained(self, ts, *, stimulus_id):
        ts.state = "constrained"
        self.constrained.append(ts)
        return {}, []

    def _transition_ready_executing(self, ts, *, stimulus_id):
        self.ready.discard(ts)
        return self._start_executing(ts, stimulus_id)

    def _transition_constrained_executing(self, ts, *, stimulus_id):
        try:
            self.constrained.remove(ts)
        except ValueError:
            pass
        for r, q in ts.resource_restrictions.items():
            self.available_resources[r] -= q
        return self._start_executing(ts, stimulus_id)

    def _start_executing(self, ts, stimulus_id):
        ts.state = "executing"
        self.executing.add(ts)
        return {}, [Execute(stimulus_id=stimulus_id, key=ts.key)]

    def _transition_executing_memory(self, ts, *, stimulus_id, payload=None):
        self._exit_executing(ts)
        recs, instr = self._put_memory(ts, stimulus_id, send_add_keys=False)
        ev = payload
        startstops = ()
        if isinstance(ev, ExecuteSuccessEvent):
            startstops = (
                {"action": "compute", "start": ev.start, "stop": ev.stop},
            )
            ts.nbytes = ev.nbytes
        instr.append(
            TaskFinishedMsg(
                stimulus_id=stimulus_id,
                key=ts.key,
                nbytes=ts.nbytes,
                typename=getattr(ev, "type", None),
                startstops=startstops,
            )
        )
        return recs, instr

    def _transition_executing_error(self, ts, *, stimulus_id, payload=None):
        self._exit_executing(ts)
        ev = payload
        if ev is not None:
            ts.exception = getattr(ev, "exception", None)
            ts.traceback = getattr(ev, "traceback", None)
            ts.exception_text = getattr(ev, "exception_text", "")
            ts.traceback_text = getattr(ev, "traceback_text", "")
        ts.state = "error"
        return {}, [
            TaskErredMsg(
                stimulus_id=stimulus_id,
                key=ts.key,
                exception=ts.exception,
                traceback=ts.traceback,
                exception_text=ts.exception_text,
                traceback_text=ts.traceback_text,
            )
        ]

    def _transition_executing_released(self, ts, *, stimulus_id):
        """Cancellation while running: we cannot interrupt the thread, so the
        task enters `cancelled` until the executor reports back
        (reference wsm.py cancelled/resumed semantics)."""
        if ts.done:
            return self._transition_generic_released(ts, stimulus_id=stimulus_id)
        ts.previous = ts.state
        ts.state = "cancelled"
        ts.next = None
        return {}, []

    def _transition_executing_rescheduled(self, ts, *, stimulus_id):
        self._exit_executing(ts)
        ts.state = "rescheduled"
        recs = {ts: "released"}
        return recs, [RescheduleMsg(stimulus_id=stimulus_id, key=ts.key)]

    def _transition_executing_long_running(self, ts, *, stimulus_id, payload=None):
        self.executing.discard(ts)
        self.long_running.add(ts)
        ts.state = "long-running"
        dur = getattr(payload, "compute_duration", 0.0) if payload else 0.0
        return {}, [
            LongRunningMsg(
                stimulus_id=stimulus_id, key=ts.key, compute_duration=dur
            )
        ]

    def _transition_fetch_flight(self, ts, *, stimulus_id):
        ts.state = "flight"
        self.in_flight_tasks.add(ts)
        return {}, []

    def _transition_fetch_missing(self, ts, *, stimulus_id):
        self._purge_data_needed(ts)
        ts.state = "missing"
        return {}, []

    def _transition_missing_fetch(self, ts, *, stimulus_id):
        return self._transition_released_fetch(ts, stimulus_id=stimulus_id)

    def _transition_flight_memory(self, ts, *, stimulus_id):
        self.in_flight_tasks.discard(ts)
        ts.coming_from = None
        # add-keys tells the scheduler about the new replica — this is how
        # AMM replication registers (reference wsm.py flight->memory)
        return self._put_memory(ts, stimulus_id, send_add_keys=True)

    def _transition_flight_fetch(self, ts, *, stimulus_id):
        self.in_flight_tasks.discard(ts)
        ts.coming_from = None
        if not ts.who_has:
            return {ts: "missing"}, []
        ts.state = "fetch"
        for w in ts.who_has:
            self.data_needed[w].add(ts)
        return {}, []

    def _transition_flight_missing(self, ts, *, stimulus_id):
        self.in_flight_tasks.discard(ts)
        ts.coming_from = None
        ts.state = "missing"
        return {}, []

    def _transition_flight_error(self, ts, *, stimulus_id, payload=None):
        self.in_flight_tasks.discard(ts)
        ts.coming_from = None
        # state is still "flight" here, so _exit_executing inside the
        # shared error path cannot mis-release execution resources
        return self._transition_executing_error(
            ts, stimulus_id=stimulus_id, payload=payload
        )

    def _transition_flight_released(self, ts, *, stimulus_id):
        # data may still arrive; remember to drop it
        ts.previous = "flight"
        ts.state = "cancelled"
        return {}, []

    def _transition_memory_released(self, ts, *, stimulus_id):
        if ts.key in self.data:
            self.nbytes_in_memory -= ts.nbytes
            del self.data[ts.key]
        self.actors.pop(ts.key, None)
        return self._transition_generic_released(ts, stimulus_id=stimulus_id)

    def _transition_cancelled_released(self, ts, *, stimulus_id):
        if not ts.done and ts.previous in ("executing", "long-running"):
            return {}, []  # still running; stay cancelled until done
        ts.previous = None
        return self._transition_generic_released(ts, stimulus_id=stimulus_id)

    def _transition_cancelled_waiting(self, ts, *, stimulus_id):
        """The scheduler wants a cancelled task computed again (reference
        wsm.py:2157): revert an interrupted execution in place, or mark a
        cancelled fetch as resumed-towards-compute."""
        if ts.previous == "executing":
            ts.state = "executing"  # forget the cancellation entirely
            ts.previous = None
            ts.next = None
            return {}, []
        if ts.previous == "long-running":
            ts.state = "long-running"
            ts.previous = None
            ts.next = None
            return {}, [
                LongRunningMsg(
                    stimulus_id=stimulus_id, key=ts.key, compute_duration=0.0
                )
            ]
        # previous == "flight": the fetch still runs; compute once it ends
        ts.state = "resumed"
        ts.next = "waiting"
        return {}, []

    def _transition_cancelled_fetch(self, ts, *, stimulus_id):
        """(reference wsm.py:2130)"""
        if ts.previous == "flight":
            if ts.done:
                return {ts: "released"}, []
            ts.state = "flight"  # forget the cancellation
            ts.previous = None
            return {}, []
        # previous executing/long-running: keep running; fetch afterwards
        ts.state = "resumed"
        ts.next = "fetch"
        return {}, []

    def _transition_resumed_fetch(self, ts, *, stimulus_id):
        """(reference wsm.py:2076)"""
        if ts.previous == "flight":
            if ts.done:
                # the old fetch ended without producing the value: honor
                # the resume-to-compute request
                ts.state = "released"
                ts.done = False
                ts.previous = None
                ts.next = None
                return {ts: "waiting"}, []
            ts.state = "flight"  # back where we started
            ts.previous = None
            ts.next = None
            return {}, []
        return {}, []  # executing/long-running: completion event decides

    def _transition_resumed_missing(self, ts, *, stimulus_id):
        return {ts: "fetch"}, []

    def _transition_resumed_released(self, ts, *, stimulus_id):
        """(reference wsm.py:2120)"""
        if ts.done:
            ts.previous = None
            ts.next = None
            return self._transition_generic_released(ts, stimulus_id=stimulus_id)
        ts.state = "cancelled"
        ts.next = None
        return {}, []

    def _transition_cancelled_memory(self, ts, *, stimulus_id, payload=None):
        # task was cancelled but completed anyway and scheduler re-wants it
        return self._transition_executing_memory(
            ts, stimulus_id=stimulus_id, payload=payload
        )

    def _transition_cancelled_error(self, ts, *, stimulus_id, payload=None):
        return self._transition_executing_error(
            ts, stimulus_id=stimulus_id, payload=payload
        )

    def _transition_generic_released(self, ts, *, stimulus_id):
        """Pull the task out of every queue and release (or forget)."""
        self._exit_executing(ts)
        self.ready.discard(ts)
        try:
            self.constrained.remove(ts)
        except ValueError:
            pass
        self.in_flight_tasks.discard(ts)
        self._purge_data_needed(ts)
        if ts.key in self.data:
            self.nbytes_in_memory -= ts.nbytes
            del self.data[ts.key]
        self.actors.pop(ts.key, None)

        recs: Recs = {}
        for dts in ts.waiting_for_data:
            dts.waiters.discard(ts)
            if not dts.waiters and dts.state in (
                "fetch", "flight", "missing",
            ):
                recs[dts] = "released"
        ts.waiting_for_data.clear()
        for dts in ts.dependencies:
            dts.waiters.discard(ts)
            if not dts.waiters and not dts.dependents - {ts} and dts.state == "released":
                recs[dts] = "forgotten"
        self._purge_replicas(ts)
        ts.state = "released"
        if not ts.dependents:
            recs[ts] = "forgotten"
        return recs, []

    def _drop_has_what(self, worker: str, key: Key) -> None:
        """Remove one ``has_what`` row without the defaultdict creating
        an empty per-peer shell for an unknown worker (and deleting the
        shell when the last row goes — with peer churn the empty sets
        themselves leak)."""
        s = self.has_what.get(worker)
        if s is not None:
            s.discard(key)
            if not s:
                del self.has_what[worker]

    def _purge_replicas(self, ts) -> None:
        """Drop the task's peer-replica bookkeeping: ``who_has`` and the
        per-peer ``has_what`` rows (empty rows deleted — with peer churn
        the empty-set shells themselves are a leak).  Reference wsm.py
        does this in ``_purge_state``; the census quiesce gate found
        released tasks pinning both sides here."""
        if ts.who_has:
            for w in ts.who_has:
                self._drop_has_what(w, ts.key)
            ts.who_has.clear()

    # ---------------------------------------------------------- helper bits

    def _put_memory(self, ts, stimulus_id, *, send_add_keys: bool):
        if ts.key not in self.data:
            # value was produced but already dropped: nothing to do
            ts.state = "released"
            return {}, []
        self.nbytes_in_memory += ts.nbytes
        ts.state = "memory"
        self._purge_data_needed(ts)
        recs: Recs = {}
        for dts in list(ts.waiters):
            dts.waiting_for_data.discard(ts)
            if not dts.waiting_for_data and dts.state == "waiting":
                recs[dts] = "constrained" if dts.resource_restrictions else "ready"
        ts.waiters.clear()
        instr: Instructions = []
        if send_add_keys:
            instr.append(AddKeysMsg(stimulus_id=stimulus_id, keys=(ts.key,)))
        return recs, instr

    def _exit_executing(self, ts) -> None:
        self.executing.discard(ts)
        self.long_running.discard(ts)
        if ts.resource_restrictions and ts.state in ("executing", "long-running", "cancelled"):
            for r, q in ts.resource_restrictions.items():
                self.available_resources[r] += q

    def _purge_data_needed(self, ts) -> None:
        for w in ts.who_has:
            dn = self.data_needed.get(w)
            if dn is not None:
                dn.discard(ts)
                if not dn:
                    del self.data_needed[w]

    def _gather_finished(self, worker: str) -> None:
        self.transfer_incoming_count = max(0, self.transfer_incoming_count - 1)

    # ------------------------------------------------- scheduling decisions

    def _ensure_computing(self, stimulus_id: str) -> Instructions:
        """Fill execution slots from the ready/constrained queues
        (reference wsm.py:1726)."""
        if not self.running:
            return []
        instructions: Instructions = []
        while self.constrained and self._executing_count() < self.nthreads:
            ts = self.constrained[0]
            if ts.state != "constrained":
                self.constrained.popleft()
                continue
            if not all(
                self.available_resources.get(r, 0) >= q
                for r, q in ts.resource_restrictions.items()
            ):
                break
            self.constrained.popleft()
            instructions += self._transitions({ts: "executing"}, stimulus_id)
        while self.ready and self._executing_count() < self.nthreads:
            ts = self.ready.pop()
            if ts.state != "ready":
                continue
            instructions += self._transitions({ts: "executing"}, stimulus_id)
        if self.execute_pipeline and self.ready:
            # pipeline extension: tiny tasks queue behind the busy
            # threads so the server can batch their thread handoffs
            # (split across the pool on multi-thread workers); stop at
            # the first non-tiny head (priority order is preserved —
            # skipping over it would reorder execution)
            limit = self.nthreads + self.execute_pipeline
            while self.ready and self._executing_count() < limit:
                ts = self.ready.peek()
                if ts.state != "ready":
                    self.ready.pop()
                    continue
                if (
                    ts.actor
                    or not (0.0 <= ts.duration < self.execute_pipeline_threshold)
                ):
                    break
                self.ready.pop()
                instructions += self._transitions({ts: "executing"}, stimulus_id)
        return instructions

    def _executing_count(self) -> int:
        return len(self.executing)

    def _ensure_communicating(self, stimulus_id: str) -> Instructions:
        """Issue GatherDep instructions for fetchable tasks
        (reference wsm.py:1531)."""
        if not self.running:
            return []
        instructions: Instructions = []
        while (
            self.data_needed
            and self.transfer_incoming_count < self.transfer_incoming_count_limit
        ):
            worker = self._select_worker_for_gather()
            if worker is None:
                break
            to_gather, total_nbytes = self._select_keys_for_gather(worker)
            if not to_gather:
                break
            self.in_flight_workers[worker] = OrderedSet(to_gather)
            self.transfer_incoming_count += 1
            recs: Recs = {}
            for key in to_gather:
                ts = self.tasks[key]
                ts.coming_from = worker
                recs[ts] = "flight"
            instructions += self._transitions(recs, stimulus_id)
            instructions.append(
                GatherDep(
                    stimulus_id=stimulus_id,
                    worker=worker,
                    to_gather=tuple(to_gather),
                    total_nbytes=total_nbytes,
                )
            )
        return instructions

    def _select_worker_for_gather(self) -> str | None:
        """Pick the peer whose queue holds the highest-priority fetchable
        task, skipping busy and already-in-flight peers (reference
        wsm.py:1600)."""
        best = None
        best_pri = None
        for worker, heap in list(self.data_needed.items()):
            if worker in self.busy_workers or worker in self.in_flight_workers:
                continue
            while heap and heap.peek().state != "fetch":
                heap.discard(heap.peek())
            if not heap:
                del self.data_needed[worker]
                continue
            pri = heap.peek().priority
            if best_pri is None or pri < best_pri:
                best_pri = pri
                best = worker
        return best

    def _select_keys_for_gather(self, worker: str) -> tuple[list[Key], int]:
        """Batch keys from one peer up to the message byte limit
        (reference wsm.py:1664)."""
        heap = self.data_needed.get(worker)
        keys: list[Key] = []
        total = 0
        while heap:
            ts = heap.peek()
            if ts.state != "fetch":
                heap.discard(ts)
                continue
            if keys and total + ts.nbytes > self.transfer_message_bytes_limit:
                break
            heap.discard(ts)
            keys.append(ts.key)
            total += ts.nbytes
        if heap is not None and not heap:
            self.data_needed.pop(worker, None)
        return keys, total

    # ------------------------------------------------------------ validation

    def validate_state(self) -> None:
        try:
            for key, ts in self.tasks.items():
                assert ts.key == key
                if ts.state == "memory":
                    assert key in self.data or ts.actor, ts
                if ts.state == "executing":
                    assert ts in self.executing, ts
                if ts.state == "ready":
                    assert ts in self.ready, ts
                if ts.state == "flight":
                    assert ts in self.in_flight_tasks, ts
                for dts in ts.waiting_for_data:
                    assert ts in dts.waiters, (ts, dts)
                    assert dts.state != "memory", (ts, dts)
            for ts in self.executing:
                # resumed: cancelled mid-execute, then wanted again — the
                # in-flight execute keeps running and its result is reused
                assert ts.state in ("executing", "cancelled", "resumed"), ts
            for worker, keys in self.in_flight_workers.items():
                for key in keys:
                    ts = self.tasks.get(key)
                    assert ts is None or ts.state in ("flight", "cancelled", "resumed"), ts
        except AssertionError as e:
            raise InvalidTaskState(str(e)) from e

    def story(self, *keys: Key) -> list[tuple]:
        return [entry for entry in self.log if entry[0] in keys]


@functools.lru_cache(maxsize=None)
def _snake(name: str) -> str:
    # cached: runs once per event CLASS, not once per stimulus (this sat
    # near the top of the trivial-task profile before)
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i:
            out.append("_")
        out.append(c.lower())
    s = "".join(out)
    return s[: -len("_event")] if s.endswith("_event") else s
