"""SpillBuffer: dict-like store that overflows to disk (reference spill.py).

Fast layer = in-memory dict with LRU ordering; slow layer = one pickled
file per key in the worker's scratch directory (the reference composes
zict Buffer/File/Func, spill.py:69 — same semantics, no dependency).
``evict()`` moves the least-recently-used fast key to disk; reads from
slow promote back to fast.  Byte accounting feeds the worker memory
manager's spill decisions.
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import tempfile
from collections.abc import Iterator, MutableMapping
from typing import Any

from distributed_tpu.utils.sizeof import safe_sizeof

logger = logging.getLogger("distributed_tpu.spill")


class SpillBuffer(MutableMapping):
    """{key: value} with a byte-bounded fast layer (reference spill.py:69)."""

    def __init__(self, spill_directory: str | None = None, target: int = 0,
                 metrics_cb=None):
        # metrics_cb(label, value, unit): fine-metrics sink — the worker
        # wires this so serialize/disk-write/disk-read seconds and bytes
        # show up per activity in spans / performance_report (reference
        # metrics.py captures these inside its spill brackets)
        self.metrics_cb = metrics_cb
        self.spill_directory = spill_directory or tempfile.mkdtemp(
            prefix="dtpu-spill-"
        )
        os.makedirs(self.spill_directory, exist_ok=True)
        self.target = target  # fast-layer byte budget; 0 = unbounded
        self.fast: dict[str, Any] = {}  # insertion order = LRU order
        self.fast_sizes: dict[str, int] = {}
        self.fast_bytes = 0
        self.slow: dict[str, int] = {}  # key -> file size
        self.slow_bytes = 0
        # cumulative metrics (reference spill.py SpillBuffer.cumulative_metrics)
        self.spilled_count = 0
        self.unspilled_count = 0

    # ----------------------------------------------------------- mapping API

    def __setitem__(self, key: str, value: Any) -> None:
        # plain delete, NOT MutableMapping.pop — pop would round-trip a
        # stale slow-layer value through disk+unpickle just to discard it
        try:
            del self[key]
        except KeyError:
            pass
        size = safe_sizeof(value)
        self.fast[key] = value
        self.fast_sizes[key] = size
        self.fast_bytes += size
        if self.target:
            while self.fast_bytes > self.target and len(self.fast) > 1:
                if self.evict() < 0:
                    break

    def __getitem__(self, key: str) -> Any:
        if key in self.fast:
            # LRU touch: move to the back
            value = self.fast.pop(key)
            self.fast[key] = value
            return value
        if key in self.slow:
            value = self._unspill(key)
            return value
        raise KeyError(key)

    def __delitem__(self, key: str) -> None:
        if key in self.fast:
            del self.fast[key]
            self.fast_bytes -= self.fast_sizes.pop(key)
        elif key in self.slow:
            self.slow_bytes -= self.slow.pop(key)
            try:
                os.unlink(self._path(key))
            except OSError:
                pass
        else:
            raise KeyError(key)

    def __contains__(self, key: object) -> bool:
        return key in self.fast or key in self.slow

    def __iter__(self) -> Iterator[str]:
        yield from self.fast
        yield from self.slow

    def __len__(self) -> int:
        return len(self.fast) + len(self.slow)

    # ------------------------------------------------------------- spilling

    def _path(self, key: str) -> str:
        safe = key.replace(os.sep, "_").replace("\x00", "_")[:150]
        return os.path.join(self.spill_directory, f"{safe}-{abs(hash(key)):x}")

    def evict(self) -> int:
        """Spill the least-recently-used fast key; returns bytes freed or -1
        (reference spill.py:150 / worker_memory evict loop)."""
        if not self.fast:
            return -1
        key = next(iter(self.fast))
        value = self.fast[key]
        from distributed_tpu.utils.misc import time as _now

        t0 = _now()
        try:
            payload = pickle.dumps(value, protocol=5)
        except Exception:
            # unpicklable: keep in fast but move to the back so we don't
            # spin on it
            v = self.fast.pop(key)
            self.fast[key] = v
            logger.warning("cannot spill unpicklable key %r", key)
            return -1
        t1 = _now()
        with open(self._path(key), "wb") as f:
            f.write(payload)
        if self.metrics_cb is not None:
            self.metrics_cb("serialize", t1 - t0, "seconds")
            self.metrics_cb("disk-write", _now() - t1, "seconds")
            self.metrics_cb("disk-write", float(len(payload)), "bytes")
        del self.fast[key]
        size = self.fast_sizes.pop(key)
        self.fast_bytes -= size
        self.slow[key] = len(payload)
        self.slow_bytes += len(payload)
        self.spilled_count += 1
        return size

    def _unspill(self, key: str) -> Any:
        from distributed_tpu.utils.misc import time as _now

        t0 = _now()
        with open(self._path(key), "rb") as f:
            payload = f.read()
        t1 = _now()
        value = pickle.loads(payload)
        if self.metrics_cb is not None:
            self.metrics_cb("disk-read", t1 - t0, "seconds")
            self.metrics_cb("disk-read", float(len(payload)), "bytes")
            self.metrics_cb("deserialize", _now() - t1, "seconds")
        self.slow_bytes -= self.slow.pop(key)
        try:
            os.unlink(self._path(key))
        except OSError:
            pass
        size = safe_sizeof(value)
        self.fast[key] = value
        self.fast_sizes[key] = size
        self.fast_bytes += size
        self.unspilled_count += 1
        return value

    def close(self) -> None:
        shutil.rmtree(self.spill_directory, ignore_errors=True)

    def __repr__(self) -> str:
        return (
            f"<SpillBuffer fast={len(self.fast)} ({self.fast_bytes}B) "
            f"slow={len(self.slow)} ({self.slow_bytes}B)>"
        )
