"""AsyncProcess: asyncio-friendly subprocess management (reference process.py).

Wraps ``multiprocessing`` (spawn context — fork is unsafe with asyncio and
JAX runtimes) so a Server can start/kill/await child processes without
blocking its event loop.  A daemon watcher thread joins the child and
posts the exit code back onto the loop, firing registered exit callbacks
(the Nanny's auto-restart hook, reference nanny.py:546).
"""

from __future__ import annotations

import asyncio
import logging
import multiprocessing
import threading
from typing import Any, Callable

logger = logging.getLogger("distributed_tpu.process")

_ctx = multiprocessing.get_context("spawn")


class AsyncProcess:
    """A spawned child process with async start/join/kill (reference
    process.py:43)."""

    def __init__(self, target: Callable, args: tuple = (), kwargs: dict | None = None,
                 name: str | None = None):
        self._process = _ctx.Process(
            target=target, args=args, kwargs=kwargs or {}, name=name
        )
        self._process.daemon = True
        self._watch_thread: threading.Thread | None = None
        self._exit_future: asyncio.Future | None = None
        self._exit_callback: Callable[[int | None], None] | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    @property
    def pid(self) -> int | None:
        return self._process.pid

    @property
    def exitcode(self) -> int | None:
        return self._process.exitcode

    def is_alive(self) -> bool:
        return self._process.is_alive()

    def set_exit_callback(self, callback: Callable[[int | None], None]) -> None:
        self._exit_callback = callback

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._exit_future = self._loop.create_future()
        await self._loop.run_in_executor(None, self._process.start)
        self._watch_thread = threading.Thread(
            target=self._watch, name=f"AsyncProcess-watch-{self._process.name}",
            daemon=True,
        )
        self._watch_thread.start()

    def _watch(self) -> None:
        self._process.join()
        code = self._process.exitcode
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        def _fire() -> None:
            if self._exit_future is not None and not self._exit_future.done():
                self._exit_future.set_result(code)
            if self._exit_callback is not None:
                try:
                    self._exit_callback(code)
                except Exception:
                    logger.exception("process exit callback failed")
        try:
            loop.call_soon_threadsafe(_fire)
        except RuntimeError:
            pass  # loop shut down meanwhile

    async def join(self, timeout: float | None = None) -> int | None:
        assert self._exit_future is not None, "not started"
        return await asyncio.wait_for(asyncio.shield(self._exit_future), timeout)

    async def terminate(self) -> None:
        """SIGTERM (graceful-ish)."""
        if self._process.is_alive():
            await asyncio.get_running_loop().run_in_executor(
                None, self._process.terminate
            )

    async def kill(self) -> None:
        """SIGKILL."""
        if self._process.is_alive():
            await asyncio.get_running_loop().run_in_executor(
                None, self._process.kill
            )

    def __repr__(self) -> str:
        return (
            f"<AsyncProcess {self._process.name} pid={self.pid} "
            f"exitcode={self.exitcode}>"
        )
