"""Task execution context: ``get_worker()`` from inside a task
(reference worker.py get_worker / thread_state).

The worker sets a thread-local before invoking user code in its executor
(threads are per-worker pools, so the binding is exact even with several
in-process workers), and a contextvar for tasks executed as coroutines on
the event loop.
"""

from __future__ import annotations

import contextvars
import threading
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from distributed_tpu.worker.server import Worker

_thread_state = threading.local()
_async_worker: contextvars.ContextVar = contextvars.ContextVar(
    "dtpu_worker", default=None
)
_async_key: contextvars.ContextVar = contextvars.ContextVar(
    "dtpu_task_key", default=None
)


def set_thread_worker(worker: "Worker", key: str | None = None) -> None:
    _thread_state.worker = worker
    _thread_state.key = key


def get_thread_key() -> str | None:
    return getattr(_thread_state, "key", None)


def set_async_worker(worker: "Worker", key: str | None = None):
    return _async_worker.set(worker), _async_key.set(key)


def reset_async_worker(token) -> None:
    t1, t2 = token
    _async_worker.reset(t1)
    _async_key.reset(t2)


def get_task_key() -> str | None:
    """The key of the currently-executing task: thread-local for executor
    tasks, contextvar for coroutine bodies on the event loop."""
    key = getattr(_thread_state, "key", None)
    if key is not None:
        return key
    return _async_key.get()


def get_worker() -> "Worker":
    """The Worker hosting the currently-executing task."""
    worker = getattr(_thread_state, "worker", None)
    if worker is not None:
        return worker
    worker = _async_worker.get()
    if worker is not None:
        return worker
    raise ValueError("no worker found in this thread/task context")
