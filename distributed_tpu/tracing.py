"""Flight-recorder core: causal stimulus tracing for the control loop.

The repo's state machines already thread a ``stimulus_id`` through every
transition (``transition_log``, ``story``) and every scheduler<->worker
message.  This module adds the missing observation layer: an always-on,
bounded, allocation-free ring of structured events stamped with those
same stimulus ids, so one id joins an inbound flood (``ingress``) to the
engine pass it folded into (``engine``/``transition``), the device-kernel
cycles it touched (``kernel``), and the envelopes it emitted
(``egress``) — across scheduler and worker roles.

Three consumers (docs/observability.md):

- ``/trace`` on every node's HTTP server: JSONL tail of the ring;
- the Chrome/Perfetto exporter
  (``python -m distributed_tpu.diagnostics.flight_recorder``);
- the replayable **stimulus journal** (opt-in record mode): versioned
  JSONL records of every engine stimulus, re-feedable through
  ``transitions_batch`` offline with a bit-identical transition stream —
  the capture half of the ROADMAP item 1 simulator.

Hot-loop contract (enforced by the ``trace`` bench-smoke gate): ring
slots are preallocated lists mutated in place, ``emit`` performs no
per-event allocation, task-level events sample 1-in-N
(``scheduler.trace.sample``), and traced-on overhead on the engine flood
smoke stays under 5%.

This file is pure (no IO, no event loop, no threads): the sans-io
engines may import it, and the monotonic-time lint covers it — every
timestamp here is ``utils.misc.time`` (monotonic).
"""

from __future__ import annotations

import json
from bisect import bisect_left
from collections import deque
from typing import Any, Iterable

from distributed_tpu import config
from distributed_tpu.utils import time

#: bump when a field is added/renamed/retyped; every JSONL record and
#: journal record carries it as ``v`` (docs/observability.md)
TRACE_SCHEMA_VERSION = 1

#: slot layout of one ring event (kept in a flat preallocated list)
EVENT_FIELDS = ("ts", "cat", "name", "stim", "key", "n", "dest")

#: event vocabulary — the ``cat`` field (docs/observability.md)
CATEGORIES = (
    "ingress",     # a stream op entered a control plane (scheduler/worker)
    "engine",      # one batched/scalar transition-engine pass
    "transition",  # one task transition (task-level, sampled 1-in-N)
    "kernel",      # a device co-processor cycle (placement/steal/AMM/mirror)
    "egress",      # a coalesced envelope left on a batched stream
    "wstim",       # a worker state-machine stimulus (task-level, sampled)
    "shadow",      # a shadow cost-model divergence sample (task-level,
                   # sampled; telemetry.py — n = ratio in permille)
    "stall",       # the stall watchdog caught a blocked event loop
                   # (diagnostics/selfprofile.py — key = formatted
                   # traceback, name = in-progress phase, n = lag ms)
    "leak",        # the retention sentinel flagged a census family
                   # (diagnostics/census.py — name = family, n = its
                   # resident member count at flag time)
)


class FlightRecorder:
    """Bounded structured event ring + opt-in replayable stimulus journal.

    One per state machine (``SchedulerState.trace``, worker
    ``WorkerState.trace``) and per bare ``Server``; servers alias their
    state's recorder so role-level HTTP routes and the engines share one
    timeline.
    """

    def __init__(
        self,
        ring_size: int | None = None,
        enabled: bool | None = None,
        sample: int | None = None,
        journal: bool | None = None,
        journal_size: int | None = None,
    ):
        if ring_size is None:
            ring_size = int(config.get("scheduler.trace.ring-size"))
        if enabled is None:
            enabled = bool(config.get("scheduler.trace.enabled"))
        if sample is None:
            sample = int(config.get("scheduler.trace.sample")) or 1
        if journal is None:
            journal = bool(config.get("scheduler.trace.journal"))
        if journal_size is None:
            journal_size = int(config.get("scheduler.trace.journal-size"))
        size = 2
        while size < ring_size:
            size <<= 1  # pow2 so the hot path masks instead of modding
        self._mask = size - 1
        # injectable clock (ROADMAP item 1 simulator): the sim harness
        # re-points this at its VirtualClock so ring events and journal
        # records are stamped in VIRTUAL seconds — two same-seed sim
        # runs then produce byte-identical journals.  Live recorders
        # keep the monotonic utils.misc.time.
        self.clock = time
        # preallocated slots, mutated in place: the fast path allocates
        # nothing (gate: bench.py --smoke "trace" alloc check)
        self._slots: list[list] = [
            [0.0, "", "", "", "", 0, ""] for _ in range(size)
        ]
        self._i = 0          # total events ever emitted (ring head)
        self._tick = 0       # task-level sampling counter
        self.enabled = bool(enabled)
        self.sample = max(int(sample), 1)
        self.journal_enabled = bool(journal)
        self.journal: deque[dict] = deque(maxlen=max(int(journal_size), 1))
        self._journal_seq = 0  # records ever journaled (capture ordinal)
        # durable capture hook (scheduler/durability.py): called with
        # every journal record AFTER it lands in the deque.  The segment
        # writer subscribes here so a long capture stays complete on
        # disk even after the bounded deque evicts its head — the
        # eviction race ``verify_journal`` can only detect, never fix.
        self.journal_sink: Any | None = None

    # ------------------------------------------------------------ fast path

    def emit(self, cat: str, name: str, stim: str, key: str = "",
             n: int = 0, dest: str = "") -> None:
        """Record one event.  In-place slot write; no allocation."""
        if not self.enabled:
            return
        i = self._i
        slot = self._slots[i & self._mask]
        slot[0] = self.clock()
        slot[1] = cat
        slot[2] = name
        slot[3] = stim
        slot[4] = key
        slot[5] = n
        slot[6] = dest
        self._i = i + 1

    def emit_task(self, cat: str, name: str, stim: str, key: str = "",
                  n: int = 0, dest: str = "") -> None:
        """Task-level event: sampled 1-in-N (``scheduler.trace.sample``)
        so per-transition emission stays off the flood critical path at
        high sample rates while batch-level events stay exact."""
        if not self.enabled:
            return
        t = self._tick + 1
        self._tick = t
        if t % self.sample:
            return
        self.emit(cat, name, stim, key, n, dest)

    # ----------------------------------------------------- journal (record)

    def record(self, op: str, payload: dict, stim: str) -> None:
        """Append one replayable stimulus record (record mode only).

        Unlike ring events these are *inputs* to the engine — op, payload,
        stimulus id, monotonic ts — sufficient to re-drive
        ``transitions_batch`` offline (``diagnostics.flight_recorder.
        replay_stimulus_trace``) and reproduce the identical transition
        stream from the same starting state.  ``seq`` is the capture
        ordinal: the bounded deque silently evicts the OLDEST records on
        overflow, and a journal missing its head would replay cleanly
        from the wrong starting point — replay's ``verify_journal``
        refuses any capture whose seqs are not the contiguous run from 0
        (use :meth:`journal_start` to begin a fresh capture)."""
        seq = self._journal_seq
        self._journal_seq = seq + 1
        sink = self.journal_sink
        rec = {
            "v": TRACE_SCHEMA_VERSION,
            "seq": seq,
            "op": op,
            "stim": stim,
            "ts": self.clock(),
            # with a durable sink attached the digest is stamped at
            # segment-append time (stamp_digests — off the engine hot
            # path, on the writer thread in production); the deque
            # holds the SAME dict, so the in-memory record heals too
            "digest": payload_digest(payload) if sink is None else None,
            "payload": payload,
        }
        self.journal.append(rec)
        if sink is not None:
            sink(rec)

    def journal_start(self) -> None:
        """Begin a fresh replayable capture: clear the journal, reset
        the capture ordinal, enable record mode."""
        self.journal.clear()
        self._journal_seq = 0
        self.journal_enabled = True

    # ------------------------------------------------------------ slow path

    @property
    def total(self) -> int:
        """Events emitted over the recorder's lifetime."""
        return self._i

    def __len__(self) -> int:
        """Events currently resident in the ring."""
        return min(self._i, self._mask + 1)

    def tail(self, n: int | None = None) -> list[dict]:
        """Newest ``n`` (default: all resident) events as dicts, oldest
        first.  ``seq`` is the event's lifetime ordinal — gaps against a
        previous tail mean the ring wrapped in between."""
        total = self._i
        count = min(total, self._mask + 1)
        if n is not None:
            count = min(count, max(int(n), 0))
        out = []
        for j in range(total - count, total):
            s = self._slots[j & self._mask]
            out.append({
                "v": TRACE_SCHEMA_VERSION,
                "seq": j,
                "ts": s[0],
                "cat": s[1],
                "name": s[2],
                "stim": s[3],
                "key": s[4],
                "n": s[5],
                "dest": s[6],
            })
        return out

    def clear(self) -> None:
        self._i = 0
        self._tick = 0
        for slot in self._slots:
            slot[0] = 0.0
            slot[1] = slot[2] = slot[3] = slot[4] = slot[6] = ""
            slot[5] = 0

    def __repr__(self) -> str:
        return (
            f"<FlightRecorder {'on' if self.enabled else 'off'} "
            f"ring={self._mask + 1} events={self._i} "
            f"journal={len(self.journal)}>"
        )


# --------------------------------------------------------------- helpers


def to_jsonl(events: Iterable[dict]) -> str:
    """Serialize events/journal records as JSON Lines (the ``/trace``
    wire format and the on-disk trace format).  Non-JSON values (opaque
    payload frames in journaled erred events) degrade to ``repr`` —
    stated in the schema contract, docs/observability.md."""
    return "".join(
        json.dumps(ev, default=repr, separators=(",", ":")) + "\n"
        for ev in events
    )


def from_jsonl(text: str | bytes) -> list[dict]:
    if isinstance(text, bytes):
        text = text.decode()
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def dump_journal(records: Iterable[dict], path: str) -> int:
    """Write a stimulus journal (or any event list) to ``path`` as JSONL.
    Returns the number of records written.  The on-disk format is the
    same schema-versioned record stream ``/trace`` serves, so a dumped
    journal replays through ``replay_stimulus_trace`` and through the
    simulator's journal trace source unchanged."""
    records = list(records)
    with open(path, "w") as f:
        f.write(to_jsonl(records))
    return len(records)


def load_journal(path: str) -> list[dict]:
    """Load a JSONL stimulus journal from disk (the counterpart of
    :func:`dump_journal`; the simulator's recorded-trace source).
    Integrity is NOT checked here — ``verify_journal`` (diagnostics.
    flight_recorder) runs digest + contiguity checks before any replay."""
    with open(path) as f:
        return from_jsonl(f.read())


def atomic_write_bytes(path: str, blob: bytes) -> int:
    """Crash-consistent file write: temp sibling, flush, ``fsync``,
    ``os.replace``, directory ``fsync``.  A reader never observes a
    half-written file — it sees the old content or the new, which is
    the property the durability snapshots (scheduler/durability.py)
    build their no-torn-snapshot contract on.  Returns bytes written."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return len(blob)


def append_jsonl(path: str, records: Iterable[dict], fsync: bool = True) -> int:
    """Append records to a JSONL file (journal segments), optionally
    fsync'd.  Appends are NOT atomic: a crash mid-append leaves a torn
    final line, which the durability loader treats as
    never-made-durable and drops (docs/durability.md).  Returns bytes
    appended."""
    import os

    blob = to_jsonl(records).encode()
    if not blob:
        return 0
    with open(path, "ab") as f:
        f.write(blob)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    return len(blob)


def read_file_bytes(path: str) -> bytes:
    """Read one file whole (the durability loaders' delegated IO —
    scheduler/durability.py is in the sans-io lint scope and never
    opens files itself)."""
    with open(path, "rb") as f:
        return f.read()


def stamp_digests(records: Iterable[dict]) -> None:
    """Fill missing payload digests in place.  Durable capture defers
    digest computation off the engine hot path (FlightRecorder.record
    leaves ``digest: None`` while a journal_sink is attached); the
    durability sinks stamp here immediately before serializing a
    segment — on the writer thread in the live scheduler.  Records in
    the bounded deque are the same dict objects, so stamping heals the
    in-memory journal for ``verify_journal``/dump consumers too."""
    for rec in records:
        if rec.get("digest") is None:
            rec["digest"] = payload_digest(rec["payload"])


def payload_digest(payload: Any) -> str:
    """Stable short digest of a stimulus payload (canonical JSON,
    blake2b-8): lets a replay harness verify a journal wasn't edited and
    lets two captures of the same flood be diffed cheaply."""
    import hashlib

    blob = json.dumps(
        payload, default=repr, sort_keys=True, separators=(",", ":")
    ).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


class Histogram:
    """Minimal fixed-bucket histogram for the prom exposition
    (``http.server.prom_histogram_lines``): cumulative ``le`` buckets,
    sum and count — enough for p50/p99 estimation in any Prometheus UI.
    ``observe`` is hot-path-safe: one bisect + two adds."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Iterable[float]):
        self.bounds = tuple(sorted(bounds))
        # counts[i] = observations in (bounds[i-1], bounds[i]];
        # counts[-1] = observations above the last bound (+Inf bucket)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile off the bucket boundaries (tests and
        quick looks; dashboards should use histogram_quantile)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for bound, c in zip(self.bounds, self.counts):
            seen += c
            if seen >= target:
                return bound
        return self.bounds[-1] if self.bounds else 0.0

    def __repr__(self) -> str:
        return f"<Histogram n={self.count} sum={self.sum:.4g}>"


# engine/egress bucket layouts shared by scheduler state + exposition:
# powers of two for sizes, ~1-3-10 decades for seconds
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)
SECONDS_BUCKETS = (
    1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0,
)
