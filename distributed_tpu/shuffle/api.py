"""Shuffle graph builder + task bodies (reference shuffle/_shuffle.py,
_rechunk.py graph shapes).

``p2p_shuffle`` repartitions a list of record-partition futures into
``npartitions_out`` hash partitions; ``p2p_rechunk`` re-tiles a 1-D
chunked array; ``p2p_merge`` hash-joins two collections.  All build the
O(N+M) transfer/barrier/unpack graph whose data plane is the buffered
worker->worker push engine in ``shuffle.core``.

Task bodies fetch the CURRENT run spec from the scheduler extension
(``get_or_create_remote``), so a restarted shuffle (worker loss,
duplicate output fetch) transparently re-runs under a bumped run_id —
a body that discovers its run is stale asks the scheduler to restart
and reschedules itself (reference shuffle/_scheduler_plugin.py:336).
"""

from __future__ import annotations

import uuid
from typing import Any, Callable

from distributed_tpu.exceptions import Reschedule
from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec
from distributed_tpu.shuffle.core import (
    ShuffleClosedError,
    concat_records,
    make_keyed_splitter,
    split_records_by_hash,
    stable_hash,
)


# ------------------------------------------------------------ task bodies
# (async: they run on the worker event loop and reach the engine through
# the execution context, reference shuffle/_shuffle.py shuffle_transfer)

async def _run_for(shuffle_id: str):
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    return worker, await worker.shuffle.get_or_create_remote(shuffle_id)


async def _restart_and_reschedule(worker: Any, shuffle_id: str,
                                  run_id: int) -> None:
    """This epoch is unusable: ask the scheduler to bump it, then
    reschedule this task (it will re-run under the new epoch)."""
    try:
        await worker.rpc(worker.scheduler_addr).shuffle_restart(
            id=shuffle_id, run_id=run_id
        )
    except OSError:
        pass
    raise Reschedule(f"shuffle {shuffle_id} run {run_id} closed")


async def shuffle_transfer(data: Any, shuffle_id: str, partition_id: int,
                           key: Callable | None = None) -> int:
    worker, run = await _run_for(shuffle_id)
    splitter = make_keyed_splitter(key) if key is not None else split_records_by_hash
    try:
        await run.add_partition(data, partition_id, splitter)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    return partition_id


async def shuffle_barrier(shuffle_id: str, *transfer_results: int) -> int:
    worker, run = await _run_for(shuffle_id)
    try:
        await run.barrier()
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    return run.run_id


async def shuffle_unpack(shuffle_id: str, partition_id: int,
                         barrier_result: int) -> Any:
    worker, run = await _run_for(shuffle_id)
    try:
        return await run.get_output_partition(partition_id, concat_records)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)


# ------------------------------------------------------ columnar variants

async def shuffle_transfer_arrays(data: Any, shuffle_id: str,
                                  partition_id: int, on: str) -> int:
    """Columnar transfer: one vectorized hash-split per input partition
    (reference _shuffle.py:617 split_by_worker on arrow tables)."""
    from distributed_tpu.shuffle.columnar import make_columnar_splitter

    worker, run = await _run_for(shuffle_id)
    try:
        await run.add_partition(data, partition_id, make_columnar_splitter(on))
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    return partition_id


async def shuffle_unpack_arrays(shuffle_id: str, partition_id: int,
                                barrier_result: int) -> Any:
    from distributed_tpu.shuffle.columnar import concat_arrays

    worker, run = await _run_for(shuffle_id)
    try:
        return await run.get_output_partition(partition_id, concat_arrays)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)


# ------------------------------------------------------- rechunk variants

async def rechunk_transfer(chunk: Any, shuffle_id: str, partition_id: int,
                           old_offset: int, new_bounds: tuple) -> int:
    """Route slices of a 1-D chunk to their output-chunk owners
    (reference shuffle/_rechunk.py rechunk_transfer)."""
    worker, run = await _run_for(shuffle_id)

    def splitter(data: Any, npartitions: int) -> dict[int, Any]:
        out: dict[int, Any] = {}
        n = len(data)
        for j in range(npartitions):
            lo, hi = new_bounds[j], new_bounds[j + 1]
            s = max(lo - old_offset, 0)
            e = min(hi - old_offset, n)
            if s < e:
                # tag with the absolute offset so assembly can sort
                out[j] = (old_offset + s, data[s:e])
        return out

    try:
        await run.add_partition(chunk, partition_id, splitter)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    return partition_id


def _rechunk_assembler(shards: list) -> Any:
    import numpy as np

    pieces = sorted(shards, key=lambda t: t[0])
    arrays = [p[1] for p in pieces]
    if not arrays:
        return np.empty(0)
    if isinstance(arrays[0], np.ndarray):
        return np.concatenate(arrays)
    out: list = []
    for a in arrays:
        out.extend(a)
    return out


async def rechunk_unpack(shuffle_id: str, partition_id: int,
                         barrier_result: int) -> Any:
    worker, run = await _run_for(shuffle_id)
    try:
        return await run.get_output_partition(partition_id, _rechunk_assembler)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)


# ----------------------------------------------------------- merge bodies

async def merge_transfer(data: Any, shuffle_id: str, partition_id: int,
                         side: int, key: Callable | None) -> int:
    """Tag each record with its side (left=0/right=1) before hashing on
    the join key (reference shuffle/_merge.py semantics)."""
    worker, run = await _run_for(shuffle_id)
    keyfn = key if key is not None else (lambda rec: rec[0])

    def splitter(records: Any, npartitions: int) -> dict[int, list]:
        out: dict[int, list] = {}
        for rec in records:
            j = stable_hash(keyfn(rec)) % npartitions
            out.setdefault(j, []).append((side, rec))
        return out

    try:
        await run.add_partition(data, (side, partition_id), splitter)
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    return partition_id


def _make_merge_assembler(key: Callable | None, how: str) -> Callable:
    keyfn = key if key is not None else (lambda rec: rec[0])

    def assembler(shards: list) -> list:
        left: dict[Any, list] = {}
        right: dict[Any, list] = {}
        for shard in shards:
            for side, rec in shard:
                (left if side == 0 else right).setdefault(
                    keyfn(rec), []
                ).append(rec)
        out = []
        for k, lrecs in left.items():
            rrecs = right.get(k)
            if rrecs:
                for lr in lrecs:
                    for rr in rrecs:
                        out.append((k, lr, rr))
            elif how in ("left", "outer"):
                for lr in lrecs:
                    out.append((k, lr, None))
        if how in ("right", "outer"):
            for k, rrecs in right.items():
                if k not in left:
                    for rr in rrecs:
                        out.append((k, None, rr))
        return out

    return assembler


async def merge_unpack(shuffle_id: str, partition_id: int,
                       barrier_result: int, key: Callable | None,
                       how: str) -> list:
    worker, run = await _run_for(shuffle_id)
    try:
        return await run.get_output_partition(
            partition_id, _make_merge_assembler(key, how)
        )
    except ShuffleClosedError:
        await _restart_and_reschedule(worker, shuffle_id, run.run_id)


# --------------------------------------------------------- graph builders

async def _create_shuffle(client: Any, shuffle_id: str,
                          npartitions_out: int, n_inputs: int,
                          device: bool = False):
    """Register the shuffle with the scheduler extension.  Returns
    ``(worker_for, device_owned)``: the partition->worker map (for
    unpack restrictions) and whether it came from pod device ownership
    (only requested — and only possible — when ``device`` is set; host
    callers ignore the flag)."""
    resp = await client.scheduler.shuffle_get_or_create(
        id=shuffle_id, npartitions_out=npartitions_out, n_inputs=n_inputs,
        device=device,
    )
    if resp.get("status") != "OK":
        raise RuntimeError(f"shuffle registration failed: {resp!r}")
    spec = resp["spec"]
    worker_for = {int(k): v for k, v in spec["worker_for"].items()}
    return worker_for, bool(resp.get("device_owned"))


def _build_pipeline(
    g: Graph,
    shuffle_id: str,
    inputs: list,
    transfer_body: Callable,
    transfer_extra: Callable,
    unpack_body: Callable,
    unpack_extra: tuple,
    npartitions_out: int,
    worker_for: dict[int, str],
) -> tuple[list[str], dict]:
    transfer_keys = []
    for i, fut in enumerate(inputs):
        k = f"{shuffle_id}-transfer-{i}"
        g.tasks[k] = TaskSpec(
            transfer_body, (TaskRef(fut.key), shuffle_id, *transfer_extra(i))
        )
        transfer_keys.append(k)
    barrier_key = f"{shuffle_id}-barrier"
    g.tasks[barrier_key] = TaskSpec(
        shuffle_barrier, (shuffle_id, *[TaskRef(k) for k in transfer_keys]),
    )
    unpack_keys = []
    annotations = {}
    for j in range(npartitions_out):
        k = f"{shuffle_id}-unpack-{j}"
        g.tasks[k] = TaskSpec(
            unpack_body, (shuffle_id, j, TaskRef(barrier_key), *unpack_extra)
        )
        unpack_keys.append(k)
        annotations[k] = {"workers": [worker_for[j]]}
    return unpack_keys, annotations


async def p2p_shuffle(
    client: Any,
    inputs: list,
    npartitions_out: int | None = None,
    key: Callable | None = None,
) -> list:
    """Hash-shuffle record partitions (futures) into npartitions_out
    partitions; returns output futures."""
    npartitions_out = npartitions_out or len(inputs)
    shuffle_id = f"shuffle-{uuid.uuid4().hex[:12]}"
    worker_for, _ = await _create_shuffle(
        client, shuffle_id, npartitions_out, len(inputs)
    )
    g = Graph()
    unpack_keys, annotations = _build_pipeline(
        g, shuffle_id, inputs,
        shuffle_transfer, lambda i: (i, key),
        shuffle_unpack, (),
        npartitions_out, worker_for,
    )
    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]


async def p2p_shuffle_arrays(
    client: Any,
    inputs: list,
    npartitions_out: int | None = None,
    on: str = "key",
) -> list:
    """Hash-shuffle COLUMNAR partitions ({column: ndarray} dicts) on the
    ``on`` column; returns output futures of the same layout.  The
    columnar analogue of the reference's arrow dataframe shuffle
    (shuffle/_shuffle.py:617, _arrow.py): splitting and assembly are
    vectorized numpy, ~100x the record-list path."""
    npartitions_out = npartitions_out or len(inputs)
    shuffle_id = f"shuffle-{uuid.uuid4().hex[:12]}"
    worker_for, _ = await _create_shuffle(
        client, shuffle_id, npartitions_out, len(inputs)
    )
    g = Graph()
    unpack_keys, annotations = _build_pipeline(
        g, shuffle_id, inputs,
        shuffle_transfer_arrays, lambda i: (i, on),
        shuffle_unpack_arrays, (),
        npartitions_out, worker_for,
    )
    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]


def _join_parts(lp: Any, rp: Any, on: str = "key", how: str = "inner") -> Any:
    from distributed_tpu.shuffle.columnar import join_arrays

    return join_arrays(lp, rp, on, how)


async def p2p_merge_arrays(
    client: Any,
    left: list,
    right: list,
    on: str = "key",
    how: str = "inner",
    npartitions_out: int | None = None,
) -> list:
    """Columnar P2P hash join: both sides are shuffled on ``on`` with the
    SAME partition->worker assignment (the round-robin map is a pure
    function of the sorted running workers), then joined partition-wise
    with a local vectorized sort-merge join — the columnar analogue of
    reference shuffle/_merge.py:434."""
    npartitions_out = npartitions_out or max(len(left), len(right))
    louts = await p2p_shuffle_arrays(client, left, npartitions_out, on=on)
    routs = await p2p_shuffle_arrays(client, right, npartitions_out, on=on)
    return client.map(_join_parts, louts, routs, on=on, how=how, pure=False)


async def p2p_rechunk(client: Any, chunks: list, chunk_sizes: list[int],
                      new_chunk_sizes: list[int]) -> list:
    """Re-tile a 1-D chunked array (futures of chunks) onto new chunk
    boundaries (reference shuffle/_rechunk.py)."""
    assert sum(chunk_sizes) == sum(new_chunk_sizes)
    npartitions_out = len(new_chunk_sizes)
    shuffle_id = f"rechunk-{uuid.uuid4().hex[:12]}"
    worker_for, _ = await _create_shuffle(
        client, shuffle_id, npartitions_out, len(chunks)
    )

    old_offsets = [0]
    for s in chunk_sizes:
        old_offsets.append(old_offsets[-1] + s)
    new_bounds = [0]
    for s in new_chunk_sizes:
        new_bounds.append(new_bounds[-1] + s)
    new_bounds_t = tuple(new_bounds)

    g = Graph()
    unpack_keys, annotations = _build_pipeline(
        g, shuffle_id, chunks,
        rechunk_transfer, lambda i: (i, old_offsets[i], new_bounds_t),
        rechunk_unpack, (),
        npartitions_out, worker_for,
    )
    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]


async def p2p_merge(
    client: Any,
    left: list,
    right: list,
    npartitions_out: int | None = None,
    key: Callable | None = None,
    how: str = "inner",
) -> list:
    """P2P hash join of two collections of record partitions (reference
    shuffle/_merge.py:434).  Records are (key, ...) tuples unless ``key``
    extracts the join key; outputs are lists of (key, left_rec,
    right_rec) with None for outer-join misses."""
    assert how in ("inner", "left", "right", "outer"), how
    npartitions_out = npartitions_out or max(len(left), len(right))
    shuffle_id = f"merge-{uuid.uuid4().hex[:12]}"
    n_inputs = len(left) + len(right)
    worker_for, _ = await _create_shuffle(
        client, shuffle_id, npartitions_out, n_inputs
    )

    g = Graph()
    transfer_keys = []
    for i, fut in enumerate(left):
        k = f"{shuffle_id}-transfer-{i}"
        g.tasks[k] = TaskSpec(
            merge_transfer, (TaskRef(fut.key), shuffle_id, i, 0, key)
        )
        transfer_keys.append(k)
    for i, fut in enumerate(right):
        k = f"{shuffle_id}-transfer-{len(left) + i}"
        g.tasks[k] = TaskSpec(
            merge_transfer, (TaskRef(fut.key), shuffle_id, i, 1, key)
        )
        transfer_keys.append(k)
    barrier_key = f"{shuffle_id}-barrier"
    g.tasks[barrier_key] = TaskSpec(
        shuffle_barrier, (shuffle_id, *[TaskRef(k) for k in transfer_keys]),
    )
    unpack_keys = []
    annotations = {}
    for j in range(npartitions_out):
        k = f"{shuffle_id}-unpack-{j}"
        g.tasks[k] = TaskSpec(
            merge_unpack, (shuffle_id, j, TaskRef(barrier_key), key, how)
        )
        unpack_keys.append(k)
        annotations[k] = {"workers": [worker_for[j]]}

    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]
